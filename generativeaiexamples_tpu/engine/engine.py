"""Jitted serving core: paged chunked prefill → slot activate → batched decode.

Replaces the continuous-batching executor inside the reference's NIM
container (TRT-LLM inflight batching with paged attention; ref
docker-compose-nim-ms.yaml:2-28, docs/architecture.md:49-61).
TPU-first design constraints (SURVEY §7 "hard parts" #1-3):

  * **Static shapes everywhere.** The decode batch is a fixed-capacity slot
    array; requests are *inserted into* and *retired from* slots, the compiled
    program never changes shape. Prompts are processed in page-aligned chunks
    (``prefill_chunk`` mid-chunks, a small power-of-two bucket ladder for the
    final chunk), so prefill compiles once per bucket.
  * **Paged KV.** KV lives in a single block-table paged pool
    (engine/kv_cache.py): prefill chunks scatter whole pages, decode appends
    one row per slot, HBM is bounded by live tokens. Chunked prefill writes
    straight into the slot's pages — there is no separate prefill cache and
    no KV splice on insert.
  * **Chunked-prefill interleave.** Each chunk is its own dispatch, so the
    scheduler can interleave decode steps between the chunks of a long
    admission — active slots never stall for a whole prompt (the TTFT vs
    tok/s tradeoff of SURVEY hard-part #2). Long prompts are chunked, never
    truncated.
  * **Tensor-parallel over a device mesh.** Given a mesh, params are placed
    by `parallel.sharding.INFERENCE_RULES` (heads/kv-heads/mlp split over
    "tensor"), the KV pool is sharded on its kv-head axis, and XLA inserts
    the activation collectives — the same TP-by-config the reference gets
    from ``INFERENCE_GPU_COUNT`` (docker-compose-nim-ms.yaml:18-20).
  * **Per-slot sampling.** temperature/top-k/top-p ride the decode state as
    traced (B,) vectors (`sample_logits_dynamic`), so one compiled decode step
    serves heterogeneous requests.
  * **Dispatch-ahead streaming.** `decode` returns small (B,) arrays; the
    host only syncs on those, never on the KV pool.

All functions are pure; `EngineCore` owns the jitted callables and the donate
annotations (the paged pool is donated through every chunk/decode step).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from generativeaiexamples_tpu.core.config import EngineConfig
from generativeaiexamples_tpu.engine import kv_cache
from generativeaiexamples_tpu.engine.kv_cache import PagedKVCache
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.ops.sampling import sample_logits_dynamic


# order of the (R, steps, B) int32 "packed" decode output block; _LP_FIELDS
# rows carry float32 bits (bitcast, not cast) — unpack_decode_out restores
# them to float arrays on the host
_PACKED_FIELDS = ("sampled", "emitted", "done", "hit_eos", "input_tokens",
                  "sampled_lp", "input_lp")
_LP_FIELDS = frozenset({"sampled_lp", "input_lp"})
# top-logprobs rows appended past the base block: TOP_LP ids then TOP_LP
# bitcast logprobs (the OpenAI `top_logprobs` surface; 5 matches what
# grading flows read, and one static K keeps the compile-variant count at 2)
TOP_LP = 5


@dataclasses.dataclass(frozen=True)
class PrefillItem:
    """One prompt's next chunk, for a grouped prefill dispatch.

    ``is_last`` items get the fused sampling + slot-activation tail
    (the per-prompt analogue of `prefill_chunk_last`); mid-prompt items
    only write KV + lengths. ``gram_state`` is the flat constrained-
    decoding DFA state the fused first token samples under (0 = request
    is unconstrained; resumes carry the state walked over the tokens
    already emitted)."""

    chunk_ids: Any                # sequence of token ids (<= prefill_chunk)
    page_row: Any                 # (max_pages_per_slot,) int32
    slot: int
    start_pos: int
    is_last: bool = False
    generated: int = 0            # tokens produced incl. the fused one
    max_gen: int = 0
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    gram_state: int = 0
    seed: int = 0                 # per-request sampling seed (PRNGKey base)
    adapter_ix: int = 0           # resident LoRA slot (0 = base model)


def unpack_decode_out(packed) -> Dict[str, Any]:
    """Split a host-fetched ``out["packed"]`` block back into named arrays.
    Logprob rows are restored from their int32 bit patterns to float32;
    trailing rows (present when the dispatch ran with top-logprobs) become
    ``top_ids``/``top_lps`` of shape (TOP_LP, steps, B)."""
    out = {k: packed[i] for i, k in enumerate(_PACKED_FIELDS)}
    for k in _LP_FIELDS:
        out[k] = np.ascontiguousarray(out[k]).view(np.float32)
    base = len(_PACKED_FIELDS)
    if packed.shape[0] > base:
        out["top_ids"] = packed[base:base + TOP_LP]
        out["top_lps"] = np.ascontiguousarray(
            packed[base + TOP_LP:base + 2 * TOP_LP]).view(np.float32)
    return out


def bits_to_f32(x: int) -> float:
    """Host-side scalar int32-bits → float32 (the batched first-token fetch
    carries last_logprob bitcast alongside the token ids)."""
    return float(np.int32(x).view(np.float32))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DecodeState:
    """Fixed-capacity slot batch for continuous decoding."""

    cache: PagedKVCache       # paged pool; lengths (B,)
    tokens: jnp.ndarray       # (B,) last token per slot
    active: jnp.ndarray       # (B,) bool — slot currently generating
    generated: jnp.ndarray    # (B,) tokens generated so far per slot
    max_gen: jnp.ndarray      # (B,) per-request generation budget
    temperature: jnp.ndarray  # (B,) f32
    top_k: jnp.ndarray        # (B,) i32
    top_p: jnp.ndarray        # (B,) f32
    # (B, 2) uint32 — PER-SLOT raw threefry keys (the request's seed), so a
    # seeded request replays its exact token sequence regardless of batch
    # composition or scheduler interleaving; the sampling key for generated
    # token i is fold_in(rngs[b], i)
    rngs: jnp.ndarray
    gram_state: jnp.ndarray   # (B,) i32 — flat DFA state; 0 = unconstrained
    last_logprob: jnp.ndarray  # (B,) f32 — model logprob of tokens[b]
    # (B, max_seq) i32 — each slot's token at each absolute position, valid
    # through index cache.lengths[b] INCLUSIVE (history[b, lengths[b]] is
    # the token being fed next). Written by prefill chunks, activation, and
    # decode appends; read by prompt-lookup drafting (ops/speculative.py).
    history: jnp.ndarray
    # (B,) i32 — resident LoRA adapter slot per request (0 = base model);
    # selects rows of the stacked adapter tree in llama._maybe_lora
    adapter_ix: jnp.ndarray

    def tree_flatten(self):
        return ((self.cache, self.tokens, self.active, self.generated,
                 self.max_gen, self.temperature, self.top_k, self.top_p,
                 self.rngs, self.gram_state, self.last_logprob,
                 self.history, self.adapter_ix), None)

    @classmethod
    def tree_unflatten(cls, _, c):
        return cls(*c)


class EngineCore:
    """Owns params + jitted programs. Thread-safety: call from one driver
    thread (the scheduler); jax dispatch itself is async.

    With ``engine_cfg.quant == "int8"`` the constructor CONSUMES the params
    tree (buffer donation frees each bf16 leaf as its int8 copy lands — the
    only way a 3B+ model quantizes within one chip's HBM); callers must not
    reuse the tree they passed in."""

    def __init__(self, model_cfg: llama.LlamaConfig, engine_cfg: EngineConfig,
                 params: llama.Params, eos_id: int,
                 adapters: Optional[llama.Params] = None,
                 mesh: Optional[Mesh] = None) -> None:
        self.mesh = mesh
        tp = int(mesh.shape.get("tensor", 1)) if mesh is not None else 1
        attn = engine_cfg.attention
        if attn == "auto":
            # pallas on TPU regardless of TP degree: under tensor
            # parallelism the kernels run per-shard through shard_map
            # wrappers (engine/kv_cache.py), attending local head slices
            attn = "pallas" if jax.default_backend() == "tpu" else "xla"
        if attn != model_cfg.attn_impl:
            model_cfg = dataclasses.replace(model_cfg, attn_impl=attn)
        if tp > 1:
            if model_cfg.n_kv_heads % tp or model_cfg.n_heads % tp:
                raise ValueError(
                    f"tensor parallel degree {tp} must divide heads "
                    f"({model_cfg.n_heads}) and kv heads "
                    f"({model_cfg.n_kv_heads}) — set engine.mesh_shape "
                    f"(APP_ENGINE_MESH_SHAPE), e.g. 'DxT' with a dividing T")
        role = (getattr(engine_cfg, "role", "unified") or "unified")
        role = str(role).strip().lower()
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"APP_ENGINE_ROLE must be unified|prefill|"
                             f"decode, got {role!r}")
        # disaggregated serving role: "prefill" workers run chunked prefill
        # only and export finished requests' KV pages (export_slot_kv);
        # "decode" workers additionally import handed-off pages
        # (import_slot_kv) and decode from the first token on; "unified"
        # (default) is today's single-worker behavior, zero-config unchanged
        self.role = role
        self.model_cfg = model_cfg
        self.cfg = engine_cfg
        self.eos_id = eos_id
        self.batch = engine_cfg.max_batch_size
        self.max_seq = engine_cfg.max_seq_len
        self.page_size = engine_cfg.page_size
        self.chunk = engine_cfg.prefill_chunk
        if self.chunk % self.page_size:
            raise ValueError(
                f"prefill_chunk ({self.chunk}) must be a multiple of "
                f"page_size ({self.page_size})")
        if self.max_seq % self.chunk:
            # guarantees every chunk (mid or final bucket) stays inside the
            # block-table row — a clamped page scatter would silently corrupt
            # earlier pages
            raise ValueError(
                f"max_seq_len ({self.max_seq}) must be a multiple of "
                f"prefill_chunk ({self.chunk})")
        k = engine_cfg.decode_steps_per_dispatch
        if k < 1 or k & (k - 1):
            # the scheduler restricts dynamic step counts to powers of two
            # (each distinct value is a separate XLA compile); reject rather
            # than silently round the operator's setting down
            raise ValueError(
                f"decode_steps_per_dispatch ({k}) must be a power of two")
        km = engine_cfg.decode_steps_max
        if km and (km < k or km & (km - 1)):
            raise ValueError(
                f"decode_steps_max ({km}) must be a power of two >= "
                f"decode_steps_per_dispatch ({k})")
        # prompt-lookup speculative decoding: every decode step widens to
        # 1 + spec_draft positions per slot (drafted from the slot's own
        # history, verified in the same weight read)
        if engine_cfg.spec_decode not in ("on", "off"):
            raise ValueError(f"unknown spec_decode {engine_cfg.spec_decode!r}")
        if engine_cfg.spec_draft < 0:
            raise ValueError(f"spec_draft ({engine_cfg.spec_draft}) must be "
                             ">= 0 (0 disables drafting)")
        # acceptance-tuned speculative width (ROADMAP item 2): the dispatch
        # width is chosen per tick from a pow2-ish DRAFT ladder (1, 2, 4,
        # …, spec_draft_max) instead of always running the static
        # spec_draft — the scheduler caps each slot's draft by its
        # trailing acceptance EMA and picks the smallest rung covering
        # every cap; high-acceptance slots climb PAST the configured draft
        # up to the ceiling (the r05 static draft was wrong in both
        # directions). Every rung is a separate XLA compile, so the ladder
        # stays small and warmup pre-compiles all of it (zero mid-serving
        # recompiles, test-pinned). spec_width is the CEILING (1 + the
        # widest draft) — q_block sizing and the scheduler's page-growth
        # horizon derive from it.
        adaptive = str(getattr(engine_cfg, "spec_adaptive", "on")
                       or "on").strip().lower()
        if adaptive not in ("on", "off"):
            raise ValueError(f"engine.spec_adaptive must be on|off, "
                             f"got {adaptive!r}")
        dmax = int(getattr(engine_cfg, "spec_draft_max", 0) or 0)
        if dmax < 0:
            raise ValueError(f"spec_draft_max ({dmax}) must be >= 0")
        if engine_cfg.spec_decode != "on" or engine_cfg.spec_draft == 0:
            self.spec_width = 1
            self.spec_widths = (1,)
        elif adaptive == "off":
            self.spec_width = 1 + engine_cfg.spec_draft
            self.spec_widths = (self.spec_width,)
        else:
            dmax = dmax or 2 * engine_cfg.spec_draft
            if dmax < engine_cfg.spec_draft:
                raise ValueError(
                    f"spec_draft_max ({dmax}) must cover spec_draft "
                    f"({engine_cfg.spec_draft})")
            drafts, d = {engine_cfg.spec_draft, dmax}, 1
            while d < dmax:
                drafts.add(d)
                d *= 2
            self.spec_width = 1 + dmax
            self.spec_widths = tuple(sorted(1 + d for d in drafts))
        self.max_pages_per_slot = -(-self.max_seq // self.page_size)
        # total physical pages: 0 = full slot capacity (+ null page 0)
        self.num_pages = (engine_cfg.num_pages or
                          self.batch * self.max_pages_per_slot + 1)
        # final-chunk buckets: page-aligned powers of two up to the chunk size
        buckets = []
        b = self.page_size
        while b < self.chunk:
            buckets.append(b)
            b *= 2
        buckets.append(self.chunk)
        self.buckets = tuple(buckets)

        # ledger-driven decode batch-width ladder (ROADMAP item 2): the
        # pure-decode program also compiles at narrower slot widths (same
        # pattern as group_buckets), so a dispatch over 3 live slots of a
        # 16-slot engine stops padding a (16 x W) token block — the waste
        # the devtime ledger's padded-vs-useful counts price as
        # engine_padding_waste_frac. Rungs: the full batch plus up to two
        # pow2 sub-widths (floor 2); the scheduler allocates slots
        # lowest-id-first so the live set compacts into the narrow rungs.
        ladder = str(getattr(engine_cfg, "decode_width_ladder", "on")
                     or "on").strip().lower()
        if ladder not in ("on", "off"):
            raise ValueError(f"engine.decode_width_ladder must be on|off, "
                             f"got {ladder!r}")
        if ladder == "off" or self.batch <= 2:
            self.decode_widths = (self.batch,)
        else:
            # two rungs keep the warmup grid bounded: the full batch plus
            # the largest pow2 strictly below it (half, for pow2 batches)
            p = 1
            while p * 2 < self.batch:
                p *= 2
            self.decode_widths = tuple(sorted({self.batch, p}))

        # ---- mixed-phase dispatch gate (ragged paged attention) ----------
        # Resolved ONCE here, failing loudly — the config gate must never
        # select a kernel the chip rejects at trace time (first dispatch).
        # APP_MIXED_PHASE_DISPATCH overrides engine.mixed_phase_dispatch.
        import os
        from generativeaiexamples_tpu.ops import pallas as pallas_ops
        mixed = (os.environ.get("APP_MIXED_PHASE_DISPATCH", "").strip().lower()
                 or getattr(engine_cfg, "mixed_phase_dispatch", "auto"))
        if mixed not in ("on", "off", "auto"):
            raise ValueError(f"APP_MIXED_PHASE_DISPATCH must be on|off|auto, "
                             f"got {mixed!r}")
        # ragged rows carry q_block queries each; decode slots need their
        # full speculative width to fit one row
        qb = 8
        while qb < self.spec_width:
            qb *= 2
        self._mixed_q_block = qb
        reasons = []
        if tp > 1:
            reasons.append("tensor parallelism (mixed dispatch is the "
                           "single-chip path; TP keeps two dispatches)")
        if model_cfg.sliding_window:
            reasons.append("sliding-window attention")
        if self.chunk % qb:
            reasons.append(f"prefill_chunk ({self.chunk}) not a multiple of "
                           f"the ragged q_block ({qb})")
        if attn == "pallas" and not pallas_ops.ragged_paged_supported(
                self.page_size, model_cfg.head_dim, qb):
            reasons.append(
                f"page_size={self.page_size} / head_dim="
                f"{model_cfg.head_dim} outside the ragged kernel's limits")
        if attn == "pallas" and (
                pallas_ops.paged_decode_supported(self.page_size,
                                                  model_cfg.head_dim)
                != pallas_ops.ragged_paged_supported(self.page_size,
                                                     model_cfg.head_dim, qb)):
            # the two predicates are one predicate by construction; if they
            # ever drift, the decode gate and the mixed gate would disagree
            # about what the chip accepts — refuse to start
            raise ValueError(
                "paged_decode_supported and ragged_paged_supported disagree "
                f"for page_size={self.page_size}, head_dim="
                f"{model_cfg.head_dim} — kernel-support predicates have "
                "drifted (ops/pallas/attention.py)")
        if mixed == "on" and reasons:
            raise ValueError("APP_MIXED_PHASE_DISPATCH=on but the mixed "
                             "program cannot serve this config: "
                             + "; ".join(reasons))
        if mixed == "auto":
            # on-by-default where it pays: the real chip. CPU test configs
            # opt in explicitly so tier-1 does not pay extra compiles.
            mixed = ("on" if not reasons
                     and jax.default_backend() == "tpu" else "off")
            if mixed == "off":
                # the diagnostic an operator chasing mixed_dispatch_frac==0
                # follows (docs/observability.md): say WHY auto resolved off
                import logging
                logging.getLogger(__name__).info(
                    "mixed-phase dispatch: auto resolved off (%s)",
                    "; ".join(reasons) or
                    f"backend {jax.default_backend()!r} is not tpu")
        self._mixed = mixed == "on" and not reasons

        # ---- multi-step decode ladder (deferred token fetch) -------------
        # Eligible steady-state dispatches scan K x M plain decode steps in
        # ONE program (decode_multi) and the host fetches the accumulated
        # token block once — the per-step dispatch tail ROADMAP item 3
        # names. M rungs are powers of two (2..ceiling), bounded like the
        # width ladders so warmup compiles every rung: an M transition
        # must never pay an XLA compile mid-serving (test-pinned). The
        # bare env APP_DECODE_MULTISTEP overrides engine.decode_multistep.
        raw_mm = (os.environ.get("APP_DECODE_MULTISTEP", "").strip()
                  or str(getattr(engine_cfg, "decode_multistep", 0) or 0))
        try:
            mm = int(raw_mm)
        except ValueError:
            raise ValueError(f"APP_DECODE_MULTISTEP must be an integer "
                             f"(0 = off, else a power of two >= 2), "
                             f"got {raw_mm!r}")
        if mm < 0 or (mm > 1 and mm & (mm - 1)):
            raise ValueError(f"decode_multistep ({mm}) must be 0 (off) or "
                             f"a power of two >= 2")
        if mm >= 2:
            rungs, r = [], 2
            while r <= mm:
                rungs.append(r)
                r *= 2
            self.multi_ms = tuple(rungs)
        else:
            self.multi_ms = ()   # 1 is the per-step path already

        # device-time ledger gate (observability/devtime.py): the bare env
        # APP_DEVTIME wins, else the config field (engine.devtime /
        # APP_ENGINE_DEVTIME via the env overlay) — applied HERE so a
        # file-configured mode actually takes effect, same pattern as the
        # mixed-phase gate above; bad values fail loudly at init
        dv = (os.environ.get("APP_DEVTIME", "").strip().lower()
              or str(getattr(engine_cfg, "devtime", "off")
                     or "off").strip().lower())
        if dv not in ("off", "sample", "on"):
            raise ValueError(f"engine.devtime (APP_DEVTIME) must be "
                             f"off|sample|on, got {dv!r}")
        from generativeaiexamples_tpu.observability.devtime import DEVTIME
        DEVTIME.configure(mode=dv)

        if mesh is not None:
            from generativeaiexamples_tpu.parallel import sharding as psh
            params = psh.shard_params(
                params, llama.logical_axes(model_cfg),
                psh.INFERENCE_RULES, mesh)
            if adapters is not None:
                adapters = jax.device_put(
                    adapters, NamedSharding(mesh, P()))
            # KV pool (flat (L*P, page, KV*HD)): shard the fused kv-head/
            # head-dim axis over "tensor" — kv_heads % tp == 0, so the split
            # lands on whole-head boundaries; page rows stay local. The
            # int8 scale pools are (rows, KV, page) — heads on AXIS 1.
            self._kv_sharding = NamedSharding(
                mesh, P(None, None, "tensor"))
            self._scale_sharding = NamedSharding(
                mesh, P(None, "tensor", None))
            self._replicated = NamedSharding(mesh, P())
        else:
            self._kv_sharding = None
            self._scale_sharding = None
            self._replicated = None
        # analytic perf envelope (core/perfmodel.py): parameter count and
        # quant-aware weight footprint captured BEFORE quantization consumes
        # the tree — the live devtime ledger and bench derive MFU/HBM-read
        # utilization from these same numbers
        self.n_params = int(sum(int(x.size)
                                for x in jax.tree.leaves(params)))
        from generativeaiexamples_tpu.core import perfmodel as _perfmodel
        self.param_bytes = _perfmodel.weight_bytes(
            self.n_params, engine_cfg.quant,
            jax.dtypes.canonicalize_dtype(model_cfg.jdtype).itemsize)
        if engine_cfg.quant == "int8":
            # after shard_params: elementwise quantize + keepdims amax
            # propagate each weight's NamedSharding onto q and s, so TP
            # layouts survive quantization. donate=True frees each bf16
            # source buffer as its int8 copy lands (ops/quant.py) — the
            # caller's params tree is consumed, which is exactly the load
            # path's contract (EngineCore owns the weights from here on).
            from generativeaiexamples_tpu.ops import quant as quant_ops
            params = quant_ops.quantize_params(params, donate=True)
            import logging
            logging.getLogger(__name__).info(
                "serving with int8 weight-only quantization")
        elif engine_cfg.quant not in ("none", ""):
            raise ValueError(f"unknown quant mode {engine_cfg.quant!r}; "
                             "expected 'none' or 'int8'")
        self.params = params
        self.adapters = adapters
        # per-request multi-LoRA registry: name -> resident slot (0 = base).
        # register_adapter() stacks trees into slots; mutually exclusive
        # with a constructor-supplied GLOBAL adapter tree (which applies to
        # every request, the merged-serving compatibility path).
        self._adapter_names: Dict[str, int] = {"": 0}
        self._adapters_stacked = False

        # Donating the state through every dispatch is the memory-optimal
        # default, but a remote-attached PJRT client (the tunneled dev chip)
        # BLOCKS ~RTT per donated dispatch (measured 248 vs 21 ms/call) —
        # there the transient on-device pool copy is ~50x cheaper.
        donate = engine_cfg.donate_buffers
        if donate == "auto":
            import os
            donate = "off" if os.environ.get("PALLAS_AXON_POOL_IPS") else "on"
        dn = (0,) if donate == "on" else ()
        # callers that keep handles into the state (the scheduler's batched
        # first-token fetch) must copy them before the next dispatch
        # deletes the donated buffers
        self.donates_state = bool(dn)
        # grouped-prefill size buckets (compile one program per bucket);
        # group padding entries are dropped on-device (OOB slot id)
        gmax = max(1, engine_cfg.prefill_group)
        gb, g = [], 1
        while g < gmax:
            gb.append(g)
            g *= 2
        gb.append(gmax)
        self.group_buckets = tuple(gb)
        self._chunk_fn = jax.jit(self._chunk_impl, donate_argnums=dn)
        self._group_fn = jax.jit(self._group_impl, donate_argnums=dn,
                                 static_argnums=(23,))
        # KV handoff programs (disaggregated serving): the export gather
        # must NOT donate — the state keeps serving after the copy-out
        self._export_fn = jax.jit(self._export_impl)
        self._import_fn = jax.jit(self._import_impl, donate_argnums=dn)
        # prefix-tier promotion (engine/kv_tier.py): scatter only, no
        # slot state — the tail prefill owns lengths/activation
        self._import_pages_fn = jax.jit(self._import_pages_impl,
                                        donate_argnums=dn)
        # transported pool dtype, validated on both ends of a handoff
        self._kv_dtype = ("int8" if engine_cfg.kv_quant == "int8"
                          else str(jax.dtypes.canonicalize_dtype(
                              model_cfg.jdtype)))
        # constrained-decoding grammar registry: up to GRAM_SLOTS byte-DFAs
        # live in one flat device table; flat state g*GRAM_STATES+s, flat
        # state 0 = the shared reject sink (engine/grammar.py). Built lazily
        # on the first grammared request.
        self._grammars: Dict[str, int] = {}        # key -> grammar slot
        self._gram_starts: Dict[str, int] = {}     # key -> flat start state
        self._gram_dfas: Dict[str, Any] = {}       # key -> host ByteDFA
        self._gram_table = None                    # (GRAM_SLOTS*STATES, 256)
        self._gram_accept = None
        self._gram_dist = None
        self._tok_bytes = None                     # (V, L) int32
        self._tok_lens = None
        # stop-string suspect tables for the multi-step decode scan
        # (frozenset of stop bytes -> (V+1,) bool device array); bounded —
        # distinct stop-byte sets are few in practice
        self._suspect_cache: Dict[Any, jax.Array] = {}
        self._long_fn = jax.jit(self._prefill_long_impl, donate_argnums=dn)
        self._long_last_fn = jax.jit(self._prefill_long_last_impl,
                                     donate_argnums=dn)
        self._chunk_last_fn = jax.jit(self._chunk_last_impl,
                                      donate_argnums=dn)
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=dn,
                                  static_argnums=(10, 11, 12, 13, 14))
        self._decode_multi_fn = jax.jit(self._decode_multi_impl,
                                        donate_argnums=dn,
                                        static_argnums=(6, 7))
        self._mixed_fn = jax.jit(self._mixed_impl, donate_argnums=dn,
                                 static_argnums=(24, 25, 26, 27))
        self._activate_fn = jax.jit(self._activate_impl, donate_argnums=dn)
        self._release_fn = jax.jit(self._release_impl, donate_argnums=dn)
        self._seed_hist_fn = jax.jit(self._seed_history_impl,
                                     donate_argnums=dn)
        self._sample_fn = jax.jit(self._sample_impl)

    @property
    def perf_model(self):
        """Analytic FLOP/HBM model for THIS engine on THIS chip
        (core/perfmodel.py) — Scheduler.start attaches it to the devtime
        ledger so engine_mfu / engine_hbm_read_util gauges go live."""
        from generativeaiexamples_tpu.core import perfmodel
        peak_flops, peak_bw = perfmodel.chip_peaks(jax.devices()[0])
        return perfmodel.PerfModel(
            n_params=self.n_params, param_bytes=self.param_bytes,
            peak_flops=peak_flops, peak_bw=peak_bw)

    # ------------------------------------------------- ledger bucket names

    def decode_bucket(self, steps: int, spec_width: Optional[int] = None,
                      width: Optional[int] = None) -> str:
        """Canonical devtime-ledger bucket of a pure-decode compile unit.
        Width parts appear ONLY when the corresponding ladder has more than
        one rung (a single-rung engine's keys stay the historical
        ``s<K>``), so the scheduler's commits and warmup's mark_warm can
        never fork the key space."""
        parts = [f"s{steps}"]
        if len(self.spec_widths) > 1:
            parts.append(f"w{spec_width or self.spec_widths[-1]}")
        if len(self.decode_widths) > 1:
            parts.append(f"b{width or self.batch}")
        return "".join(parts)

    def mixed_bucket(self, group: int, steps: int) -> str:
        """Canonical ledger bucket of a mixed-phase compile unit. Mixed
        dispatches always run the full batch width AND the ceiling spec
        width: fused chunks already fill the rows a narrow batch rung
        would cut, and under pallas the ragged kernel pads every decode
        row to q_block regardless of W — narrowing would only cut
        accepted drafts, never padding. One compile per (G, K)."""
        return f"g{group}s{steps}"

    def decode_multi_bucket(self, steps: int, m: int) -> str:
        """Canonical ledger bucket of a multi-step decode compile unit
        (program ``decode_multi``). Multi-step dispatches always run the
        base K at full batch with spec width 1 — the eligibility predicate
        already excludes grammar/spec/narrow slots — so (K, M) is the
        whole compile key."""
        return f"s{steps}m{m}"

    # ------------------------------------------------------------------ state

    def init_state(self, rng: Optional[jax.Array] = None) -> DecodeState:
        B = self.batch
        # The KV pool is the big buffer: under a mesh, allocate it directly
        # with its target sharding (never materialized on one chip).
        cache = PagedKVCache.create(self.model_cfg, B, self.num_pages,
                                    self.page_size,
                                    kv_sharding=self._kv_sharding,
                                    aux_sharding=self._replicated,
                                    kv_quant=self.cfg.kv_quant,
                                    scale_sharding=self._scale_sharding)
        del rng   # per-slot keys are seeded at activation, not globally
        state = DecodeState(
            cache=cache,
            tokens=jnp.zeros((B,), jnp.int32),
            active=jnp.zeros((B,), bool),
            generated=jnp.zeros((B,), jnp.int32),
            max_gen=jnp.zeros((B,), jnp.int32),
            temperature=jnp.ones((B,), jnp.float32),
            top_k=jnp.zeros((B,), jnp.int32),
            top_p=jnp.ones((B,), jnp.float32),
            rngs=jnp.zeros((B, 2), jnp.uint32),
            gram_state=jnp.zeros((B,), jnp.int32),
            last_logprob=jnp.zeros((B,), jnp.float32),
            history=jnp.zeros((B, self.max_seq), jnp.int32),
            adapter_ix=jnp.zeros((B,), jnp.int32),
        )
        if self.mesh is not None:
            rest = jax.device_put(
                (state.tokens, state.active, state.generated, state.max_gen,
                 state.temperature, state.top_k, state.top_p, state.rngs,
                 state.gram_state, state.last_logprob, state.history,
                 state.adapter_ix),
                self._replicated)
            state = DecodeState(cache, *rest)
        return state

    def new_allocator(self):
        """Page allocator for the pool; with ``prefix_cache=on`` (default)
        a refcounting CachingAllocator, so the scheduler shares identical
        page-aligned prompt prefixes across requests."""
        if getattr(self.cfg, "prefix_cache", "on") != "off":
            from generativeaiexamples_tpu.engine.prefix_cache import (
                CachingAllocator)
            return CachingAllocator(self.num_pages, self.page_size)
        return kv_cache.PageAllocator(self.num_pages)

    def pages_for(self, n_tokens: int) -> int:
        """Pages required so positions 0..n_tokens (inclusive next-write) fit."""
        return n_tokens // self.page_size + 1

    def put_table(self, table: np.ndarray) -> jax.Array:
        """Host block table → device (replicated under a mesh)."""
        arr = jnp.asarray(table, jnp.int32)
        if self.mesh is not None:
            arr = jax.device_put(arr, self._replicated)
        return arr

    # ---------------------------------------------------------------- prefill

    def _hist_write_chunk(self, history, slot, tokens_row, start_pos,
                          chunk_len):
        """Record one chunk's tokens in the slot's history row (padding
        columns drop out of bounds)."""
        C = tokens_row.shape[0]
        j = jnp.arange(C, dtype=jnp.int32)
        cols = jnp.where(j < chunk_len, start_pos + j, self.max_seq)
        return history.at[slot, cols].set(tokens_row, mode="drop")

    def _chunk_impl(self, state: DecodeState, params, adapters, tokens,
                    page_row, slot, start_pos, chunk_len, aix
                    ) -> Tuple[DecodeState, jnp.ndarray]:
        # params/adapters ride as arguments, never closure constants — a
        # captured 6 GB pytree would be baked into the lowered program
        logits, cache = kv_cache.prefill_chunk(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            start_pos, chunk_len, self.num_pages, adapters=adapters,
            adapter_ix=aix[None], mesh=self.mesh)
        hist = self._hist_write_chunk(state.history, slot, tokens[0],
                                      start_pos, chunk_len)
        return dataclasses.replace(state, cache=cache, history=hist), logits[0]

    def prefill_chunk(self, state: DecodeState, chunk_ids, page_row, slot: int,
                      start_pos: int, adapter_ix: int = 0
                      ) -> Tuple[DecodeState, jax.Array]:
        """Host wrapper: pad the chunk to a bucket, run the jitted chunk.

        chunk_ids: the token ids of this chunk (<= prefill_chunk of them);
        page_row: (max_pages_per_slot,) int32 block-table row for the slot.
        Returns (state, last-position logits (V,)) — callers sample from the
        logits only on the final chunk.
        """
        n = len(chunk_ids)
        S = next(b for b in self.buckets if n <= b)
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = chunk_ids
        return self._chunk_fn(
            state, self.params, self.adapters, jnp.asarray(padded),
            jnp.asarray(page_row, jnp.int32), jnp.int32(slot),
            jnp.int32(start_pos), jnp.int32(n), jnp.int32(adapter_ix))

    @property
    def mixed_row_queries(self) -> int:
        """Padded query positions PER DECODE SLOT inside a mixed dispatch:
        the ragged kernel pads every slot's row to q_block, while the XLA
        fallback keeps the raw speculative width — the scheduler's
        ragged_row_util gauge divides by this so 'kernel occupancy' means
        what the kernel actually ran."""
        if self.model_cfg.attn_impl == "pallas":
            return self._mixed_q_block
        return self.spec_width

    @property
    def mixed_supported(self) -> bool:
        """Mixed-phase dispatch (one program = decode step + prefill chunk,
        kv_cache.mixed_step) available for the engine's CURRENT state: the
        init-time gate held AND no adapter tree is resident — the fused
        forward runs base weights for every row, so the first
        register_adapter() turns the mixed path off and the scheduler
        reverts to the two-dispatch path."""
        return self._mixed and self.adapters is None

    # ---------------------------------------------- long-context prefill

    @property
    def supports_long_prefill(self) -> bool:
        """Sequence-parallel whole-prompt prefill needs a mesh with a
        "seq" axis (the LONGCTX configuration)."""
        return (self.mesh is not None and "seq" in self.mesh.axis_names
                and int(self.mesh.shape["seq"]) > 1
                and self.model_cfg.sliding_window == 0)

    def prefill_long(self, state: DecodeState, prompt_ids, page_row,
                     slot: int) -> Tuple[DecodeState, jax.Array]:
        """Whole-prompt ring-attention prefill into the slot's pages —
        §5.7 long-context serving: one pass over the full prompt with the
        sequence sharded over mesh["seq"] instead of prefill_chunk-sized
        slices (kv_cache.prefill_seq_parallel). The caller allocates pages
        exactly as for chunked prefill; returns (state, last-position
        logits (V,)) ready for `sample` + `activate`."""
        if not self.supports_long_prefill:
            raise ValueError("prefill_long needs a mesh with a 'seq' axis "
                             "and a full-causal model")
        padded, n = self._pad_long(prompt_ids)
        toks = jax.device_put(
            jnp.asarray(padded),
            NamedSharding(self.mesh, P("data", "seq")))
        return self._long_fn(state, self.params, self.adapters, toks,
                             jnp.asarray(page_row, jnp.int32),
                             jnp.int32(slot), jnp.int32(n))

    def _hist_write_long(self, history, slot, tokens):
        """Whole padded prompt into the slot's row (padding past n_tokens
        is garbage beyond the valid index — allowed by the invariant)."""
        S = tokens.shape[1]
        if S >= self.max_seq:
            return history.at[slot, :].set(tokens[0, :self.max_seq])
        return jax.lax.dynamic_update_slice(
            history, tokens.astype(jnp.int32),
            (slot, jnp.int32(0)))

    def _prefill_long_impl(self, state: DecodeState, params, adapters,
                           tokens, page_row, slot, n_tokens):
        logits, cache = kv_cache.prefill_seq_parallel(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            n_tokens, self.num_pages, self.mesh, adapters=adapters)
        hist = self._hist_write_long(state.history, slot, tokens)
        return (dataclasses.replace(state, cache=cache, history=hist),
                logits[0])

    def _pad_long(self, prompt_ids) -> Tuple[np.ndarray, int]:
        n = len(prompt_ids)
        seq_n = int(self.mesh.shape["seq"])
        import math as _math

        # power-of-two bucket ladder over the alignment unit: without it
        # every distinct rounded prompt length is a fresh XLA compile on
        # the serving path (the chunked path buckets for the same reason);
        # cap: largest align-multiple that fits the block-table row (the
        # ring needs S % seq == 0 AND the page write S % page == 0)
        align = _math.lcm(self.page_size, seq_n)
        cap = (self.max_pages_per_slot * self.page_size // align) * align
        S = align
        while S < n:
            S *= 2
        S = min(S, cap)
        if S < n:
            raise ValueError(f"prompt of {n} tokens exceeds the long-"
                             f"prefill capacity ({cap} aligned tokens)")
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = prompt_ids
        return padded, n

    def prefill_long_last(self, state: DecodeState, prompt_ids, page_row,
                          slot: int, generated: int, max_gen: int,
                          temperature: float, top_k: int, top_p: float,
                          seed: int = 0) -> Tuple[DecodeState, jax.Array]:
        """Whole-prompt sequence-parallel prefill FUSED with first-token
        sampling and slot activation (the scheduler's long-prompt
        admission path — same no-host-round-trip contract as
        `prefill_chunk_last`)."""
        if not self.supports_long_prefill:
            raise ValueError("prefill_long needs a mesh with a 'seq' axis "
                             "and a full-causal model")
        padded, n = self._pad_long(prompt_ids)
        toks = jax.device_put(
            jnp.asarray(padded), NamedSharding(self.mesh, P("data", "seq")))
        return self._long_last_fn(
            state, self.params, self.adapters, toks,
            jnp.asarray(page_row, jnp.int32), jnp.int32(slot),
            jnp.int32(n), jnp.int32(generated), jnp.int32(max_gen),
            jnp.float32(temperature), jnp.int32(top_k), jnp.float32(top_p),
            jnp.int32(seed))

    def _prefill_long_last_impl(self, state: DecodeState, params, adapters,
                                tokens, page_row, slot, n_tokens, generated,
                                max_gen, temperature, top_k, top_p, seed):
        logits, cache = kv_cache.prefill_seq_parallel(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            n_tokens, self.num_pages, self.mesh, adapters=adapters)
        state = dataclasses.replace(
            state, history=self._hist_write_long(state.history, slot, tokens))
        return self._activate_sampled(state, cache, logits, slot, generated,
                                      max_gen, temperature, top_k, top_p,
                                      seed)

    def _sample_impl(self, logits, rng, temperature, top_k, top_p):
        return sample_logits_dynamic(rng, logits[None], temperature[None],
                                     top_k[None], top_p[None])[0]

    def sample(self, logits: jax.Array, rng: jax.Array, temperature: float,
               top_k: int, top_p: float) -> int:
        """Sample one token from final-chunk logits (host sync point: TTFT)."""
        tok = self._sample_fn(logits, rng, jnp.float32(temperature),
                              jnp.int32(top_k), jnp.float32(top_p))
        # tpulint: disable=devtime-fence -- the documented TTFT sync point:
        # one scalar per admitted request, never per decode step (batched
        # callers use the scheduler's _fetch seam instead)
        return int(jax.device_get(tok))

    def _activate_sampled(self, state: DecodeState, cache, logits, slot,
                          generated, max_gen, temperature, top_k, top_p,
                          seed, aix=None) -> Tuple[DecodeState, jnp.ndarray]:
        """Shared tail of the fused prefill programs: sample the first token
        from last-position logits and activate the slot, all on-device.
        An immediate eos or an exhausted budget leaves the slot inactive
        (the host resolves the outcome from the returned token at the next
        decode sync). ``seed`` becomes the slot's PRNG base key; the fused
        token samples under fold_in(key, generated-1), continuing the
        request's deterministic stream across preemption resumes."""
        from generativeaiexamples_tpu.ops.sampling import (
            sample_logits_per_slot, token_logprob)
        base = jax.random.PRNGKey(seed)
        sub = jax.random.fold_in(base, generated - 1)
        tok = sample_logits_per_slot(sub[None], logits, temperature[None],
                                     top_k[None], top_p[None])[0]
        lp = token_logprob(logits, tok[None])[0]
        alive = (tok != self.eos_id) & (generated < max_gen)
        # the fused token enters history at its position (= prompt length,
        # which prefill just stored in lengths[slot])
        hist = state.history.at[
            slot, jnp.minimum(cache.lengths[slot],
                              self.max_seq - 1)].set(tok)
        upd = lambda arr, val: arr.at[slot].set(val)
        new_state = dataclasses.replace(
            state,
            cache=cache,
            tokens=upd(state.tokens, tok),
            active=upd(state.active, alive),
            generated=upd(state.generated, generated),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
            rngs=upd(state.rngs, base),
            # activation always clears the DFA state: a slot vacated by a
            # grammared request must not leak its grammar onto the next
            # occupant (this path — single/long prefill — is unconstrained)
            gram_state=upd(state.gram_state, jnp.int32(0)),
            last_logprob=upd(state.last_logprob, lp),
            history=hist,
            adapter_ix=upd(state.adapter_ix,
                           jnp.int32(0) if aix is None else aix),
        )
        return new_state, tok

    def _chunk_last_impl(self, state: DecodeState, params, adapters, tokens,
                         page_row, slot, start_pos, chunk_len, generated,
                         max_gen, temperature, top_k, top_p, seed, aix
                         ) -> Tuple[DecodeState, jnp.ndarray]:
        """Final chunk fused with first-token sampling and slot activation —
        admission never blocks on a host round-trip; the first token's value
        reaches the host batched into the next decode sync."""
        logits, cache = kv_cache.prefill_chunk(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            start_pos, chunk_len, self.num_pages, adapters=adapters,
            adapter_ix=aix[None], mesh=self.mesh)
        state = dataclasses.replace(
            state, history=self._hist_write_chunk(
                state.history, slot, tokens[0], start_pos, chunk_len))
        return self._activate_sampled(state, cache, logits, slot, generated,
                                      max_gen, temperature, top_k, top_p,
                                      seed, aix)

    def prefill_chunk_last(self, state: DecodeState, chunk_ids, page_row,
                           slot: int, start_pos: int, generated: int,
                           max_gen: int, temperature: float, top_k: int,
                           top_p: float, seed: int = 0, adapter_ix: int = 0
                           ) -> Tuple[DecodeState, jax.Array]:
        """Final-chunk host wrapper: returns (state, first-token device
        scalar). ``generated`` counts tokens produced including this one."""
        n = len(chunk_ids)
        S = next(b for b in self.buckets if n <= b)
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = chunk_ids
        return self._chunk_last_fn(
            state, self.params, self.adapters, jnp.asarray(padded),
            jnp.asarray(page_row, jnp.int32), jnp.int32(slot),
            jnp.int32(start_pos), jnp.int32(n), jnp.int32(generated),
            jnp.int32(max_gen), jnp.float32(temperature), jnp.int32(top_k),
            jnp.float32(top_p), jnp.int32(seed), jnp.int32(adapter_ix))

    # ------------------------------------------------------- grouped prefill

    # grammar stack geometry: GRAM_SLOTS schemas resident at once, each up
    # to GRAM_STATES DFA states (json_value's depth-3 automaton is ~3.8k;
    # schema/tool grammars are typically tens to hundreds)
    GRAM_SLOTS = 4
    GRAM_STATES = 4096

    def _group_impl(self, state: DecodeState, params, adapters, tokens,
                    page_rows, slots, len_slots, start_pos, chunk_len,
                    is_last, generated, max_gen, temperature, top_k, top_p,
                    seeds, adapter_ixs, gram_states, gram_table, gram_accept,
                    gram_dist, tok_bytes, tok_lens, use_grammar: bool
                    ) -> Tuple[DecodeState, jnp.ndarray]:
        """G chunks in ONE dispatch; ``is_last`` rows additionally run the
        fused first-token sample + slot activation (the group generalization
        of `_chunk_last_impl`). Padding rows carry slot == B (out of range):
        every scatter for them is dropped on-device. ``len_slots`` is the
        lengths-scatter dedup of ``slots`` (see kv_cache.prefill_chunks).
        With ``use_grammar`` (static), the fused first token samples under
        each row's DFA state and the advanced state is scattered into
        DecodeState.gram_state — constrained decoding from token one."""
        from generativeaiexamples_tpu.ops.sampling import (
            sample_logits_per_slot, token_logprob)
        logits, cache = kv_cache.prefill_chunks(
            params, self.model_cfg, tokens, state.cache, page_rows,
            len_slots, start_pos, chunk_len, self.num_pages,
            adapters=adapters, adapter_ix=adapter_ixs, mesh=self.mesh)
        raw = logits   # pre-mask: logprobs report the model distribution
        if use_grammar:
            from generativeaiexamples_tpu.ops.sampling import (
                grammar_advance, grammar_mask)
            logits = grammar_mask(logits, gram_states, max_gen - generated,
                                  self.eos_id, gram_table, gram_accept,
                                  gram_dist, tok_bytes, tok_lens)
        bases = jax.vmap(jax.random.PRNGKey)(seeds)           # (G, 2)
        subs = jax.vmap(jax.random.fold_in)(bases, generated - 1)
        toks = sample_logits_per_slot(subs, logits, temperature, top_k,
                                      top_p)
        lps = token_logprob(raw, toks)
        alive = is_last & (toks != self.eos_id) & (generated < max_gen)
        # mid-chunk rows must not disturb slot state: retarget their
        # scatters out of range so they drop alongside the padding rows
        act_slots = jnp.where(is_last, slots, jnp.int32(self.batch))
        upd = lambda arr, val: arr.at[act_slots].set(val, mode="drop")
        # history: every row's chunk tokens, plus the fused first token at
        # its position (= prompt length) for is_last rows
        G, C = tokens.shape
        j = jnp.arange(C, dtype=jnp.int32)[None]              # (1, C)
        h_rows = jnp.broadcast_to(slots[:, None], (G, C))
        h_cols = jnp.where(j < chunk_len[:, None],
                           start_pos[:, None] + j, self.max_seq)
        hist = state.history.at[h_rows, h_cols].set(tokens, mode="drop")
        tok_col = jnp.minimum(start_pos + chunk_len, self.max_seq - 1)
        hist = hist.at[act_slots, tok_col].set(toks, mode="drop")
        new_state = dataclasses.replace(
            state,
            cache=cache,
            tokens=upd(state.tokens, toks),
            active=upd(state.active, alive),
            generated=upd(state.generated, generated),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
            rngs=upd(state.rngs, bases),
            last_logprob=upd(state.last_logprob, lps),
            history=hist,
            adapter_ix=upd(state.adapter_ix, adapter_ixs),
        )
        if use_grammar:
            nxt = grammar_advance(gram_states, toks, gram_table, tok_bytes,
                                  tok_lens)
        else:
            # still scatter: activation must CLEAR a previous occupant's
            # DFA state (gram_states is all zeros in this program variant)
            nxt = gram_states
        new_state = dataclasses.replace(
            new_state, gram_state=upd(state.gram_state, nxt))
        return new_state, toks

    def prefill_group(self, state: DecodeState, items: "list[PrefillItem]"
                      ) -> Tuple[DecodeState, jax.Array]:
        """Run up to ``prefill_group`` prefill chunks in a single dispatch —
        across distinct slots and/or CONSECUTIVE chunks of one prompt (rows
        of the same slot must appear in ascending start_pos order). Chunks
        are padded to the full prefill_chunk bucket and the group to its
        size bucket, so the program count stays at len(group_buckets).
        Returns (state, (G,) sampled tokens — valid only for is_last rows)."""
        G = next(b for b in self.group_buckets if len(items) <= b)
        C = self.chunk
        maxp = self.max_pages_per_slot
        tokens = np.zeros((G, C), np.int32)
        page_rows = np.zeros((G, maxp), np.int32)
        slots = np.full((G,), self.batch, np.int32)      # padding = OOB
        start_pos = np.zeros((G,), np.int32)
        chunk_len = np.zeros((G,), np.int32)
        is_last = np.zeros((G,), bool)
        generated = np.zeros((G,), np.int32)
        max_gen = np.zeros((G,), np.int32)
        temperature = np.ones((G,), np.float32)
        top_k = np.zeros((G,), np.int32)
        top_p = np.ones((G,), np.float32)
        seeds = np.zeros((G,), np.int32)
        adapter_ixs = np.zeros((G,), np.int32)
        for i, it in enumerate(items):
            n = len(it.chunk_ids)
            if n > C:
                raise ValueError(f"chunk of {n} tokens exceeds "
                                 f"prefill_chunk ({C})")
            tokens[i, :n] = it.chunk_ids
            page_rows[i] = it.page_row
            slots[i] = it.slot
            start_pos[i] = it.start_pos
            chunk_len[i] = n
            is_last[i] = it.is_last
            generated[i] = it.generated
            max_gen[i] = it.max_gen
            temperature[i] = it.temperature
            top_k[i] = it.top_k
            top_p[i] = it.top_p
            seeds[i] = it.seed
            adapter_ixs[i] = it.adapter_ix
        # lengths-scatter dedup: only a slot's highest-start_pos row keeps
        # its true id (duplicate-index scatters are nondeterministic)
        len_slots = slots.copy()
        newest: Dict[int, int] = {}
        for i, it in enumerate(items):
            newest[it.slot] = i
        for i in range(len(items)):
            if newest.get(int(slots[i])) != i:
                len_slots[i] = self.batch
        gram_states = np.zeros((G,), np.int32)
        for i, it in enumerate(items):
            gram_states[i] = it.gram_state
        use_grammar = bool(gram_states.any())
        return self._group_fn(
            state, self.params, self.adapters, jnp.asarray(tokens),
            jnp.asarray(page_rows), jnp.asarray(slots),
            jnp.asarray(len_slots), jnp.asarray(start_pos),
            jnp.asarray(chunk_len), jnp.asarray(is_last),
            jnp.asarray(generated), jnp.asarray(max_gen),
            jnp.asarray(temperature), jnp.asarray(top_k),
            jnp.asarray(top_p), jnp.asarray(seeds),
            jnp.asarray(adapter_ixs), jnp.asarray(gram_states),
            *self._gram_args(use_grammar), use_grammar)

    # -------------------------------------------- constrained decoding (DFA)

    def _gram_args(self, use_grammar: bool) -> tuple:
        """(table, accept, tok_bytes, tok_lens) device args for a grammared
        program; tiny dummies when unconstrained (shapes stay constant, so
        the unconstrained program never recompiles)."""
        if use_grammar:
            return (self._gram_table, self._gram_accept, self._gram_dist,
                    self._tok_bytes, self._tok_lens)
        z = jnp.zeros((1, 256), jnp.int32)
        return (z, jnp.zeros((1,), bool), jnp.zeros((1,), jnp.int32),
                jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.int32))

    def ensure_token_bytes(self, tokenizer) -> None:
        """Build + upload the vocab byte table once (grammar prerequisite).
        Padded to the MODEL vocab: ids past the tokenizer vocab (padding
        rows of the embedding) are permanently masked under a grammar."""
        if self._tok_bytes is not None:
            return
        from generativeaiexamples_tpu.engine import grammar as grammar_mod
        tb, tl = grammar_mod.token_byte_table(tokenizer)
        V = self.model_cfg.vocab_size     # the logits' vocab axis, exactly
        if V > tb.shape[0]:
            pad = V - tb.shape[0]
            tb = np.concatenate([tb, np.zeros((pad, tb.shape[1]), np.int32)])
            tl = np.concatenate([tl, np.full((pad,), -1, np.int32)])
        elif V < tb.shape[0]:             # tokenizer ids past the model head
            tb, tl = tb[:V], tl[:V]
        self._tok_bytes = jnp.asarray(tb)
        self._tok_lens = jnp.asarray(tl)

    def _stop_suspect(self, stops: tuple) -> jax.Array:
        """(V+1,) bool device table for the multi-step decode scan: token id
        -> conservative stop-string *maybe-match* flag (byte-set
        intersection, ops/sampling.stop_suspect_mask). The extra final
        entry is the on-device ring's padding index and is always False,
        so a freshly initialised ring never reads as suspect. Sound by
        construction: any token that could begin or continue a stop match
        is flagged; false positives only pause a slot's scan early (the
        host replay is the stop truth), never corrupt it. Without a vocab
        byte table (ensure_token_bytes not yet called) every real token is
        suspect — stop-bearing slots simply gain no multi-step depth."""
        stop_bytes = frozenset(b for s in stops for b in s.encode("utf-8"))
        hit = self._suspect_cache.get(stop_bytes)
        if hit is not None:
            return hit
        V = self.model_cfg.vocab_size
        if not stop_bytes:
            mask = np.zeros((V,), np.bool_)
        elif self._tok_bytes is None:
            mask = np.ones((V,), np.bool_)
        else:
            from generativeaiexamples_tpu.ops.sampling import (
                stop_suspect_mask)
            mask = stop_suspect_mask(np.asarray(self._tok_bytes),
                                     np.asarray(self._tok_lens), stop_bytes)
        table = jnp.asarray(np.concatenate([mask, np.zeros((1,), np.bool_)]))
        if len(self._suspect_cache) >= 64:   # bounded: stop sets are few
            self._suspect_cache.clear()
        self._suspect_cache[stop_bytes] = table
        return table

    def register_grammar(self, grammar, active_keys=()) -> int:
        """Install a compiled grammar (engine/grammar.py Grammar) into the
        flat device stack; returns its FLAT START STATE (what PrefillItem
        carries). ``active_keys`` are grammars of in-flight requests —
        NEVER evicted (their slots' DFA states index into the stack).
        Raises UnsupportedSchema when the grammar exceeds the stack
        geometry or all slots are pinned by active grammars (the caller
        falls back to prompt+parse for this request)."""
        from generativeaiexamples_tpu.engine import grammar as grammar_mod
        if grammar.key in self._grammars:
            return self._gram_starts[grammar.key]
        dfa = grammar.dfa
        S = self.GRAM_STATES
        if dfa.n_states > S:
            raise grammar_mod.UnsupportedSchema(
                f"grammar needs {dfa.n_states} DFA states; engine stack "
                f"holds {S} per slot")
        if len(self._grammars) >= self.GRAM_SLOTS:
            evictable = [k for k in self._grammars if k not in active_keys]
            if not evictable:
                raise grammar_mod.UnsupportedSchema(
                    f"all {self.GRAM_SLOTS} grammar slots pinned by active "
                    f"requests")
            victim = evictable[0]
            del self._grammars[victim]
            del self._gram_starts[victim]
            del self._gram_dfas[victim]
        g = next(i for i in range(self.GRAM_SLOTS)
                 if i not in self._grammars.values())
        from generativeaiexamples_tpu.engine.grammar import DIST_INF
        if self._gram_table is None:
            table = np.zeros((self.GRAM_SLOTS * S, 256), np.int32)
            accept = np.zeros((self.GRAM_SLOTS * S,), bool)
            dist = np.full((self.GRAM_SLOTS * S,), DIST_INF, np.int32)
        else:
            # np.asarray over a jax array is a read-only view; these rows
            # are about to be written
            table = np.array(self._gram_table)
            accept = np.array(self._gram_accept)
            dist = np.array(self._gram_dist)
        # remap local states: local 0 (reject) → flat 0; local s → g*S + s
        local = dfa.table
        flat = np.where(local > 0, g * S + local, 0).astype(np.int32)
        table[g * S: g * S + dfa.n_states] = flat
        table[g * S] = 0                       # unreachable row, keep clean
        accept[g * S: g * S + dfa.n_states] = dfa.accept
        dist[g * S: g * S + dfa.n_states] = dfa.dist
        self._gram_table = jnp.asarray(table)
        self._gram_accept = jnp.asarray(accept)
        self._gram_dist = jnp.asarray(dist)
        self._grammars[grammar.key] = g
        self._gram_starts[grammar.key] = g * S + dfa.start
        self._gram_dfas[grammar.key] = dfa
        return self._gram_starts[grammar.key]

    def walk_grammar(self, grammar, token_ids, active_keys=(),
                     prefix: bytes = b"") -> int:
        """Host-side walk of output already emitted (preemption resumes /
        cross-worker failover continuations): flat state after consuming
        ``prefix`` bytes then ``token_ids`` from the grammar's start.
        Returns 0 (unconstrained) if the walk rejects — e.g. a prefix
        emitted by a worker that was NOT constrained."""
        start = self.register_grammar(grammar, active_keys)
        dfa = self._gram_dfas[grammar.key]
        g = self._grammars[grammar.key]
        tb = np.asarray(self._tok_bytes)
        tl = np.asarray(self._tok_lens)
        s = start - g * self.GRAM_STATES
        for b in prefix:
            s = int(dfa.table[s, int(b)])
            if s == 0:
                return 0
        for t in token_ids:
            n = int(tl[t])
            if n <= 0:
                return 0
            for b in tb[t, :n]:
                s = int(dfa.table[s, int(b)])
                if s == 0:
                    return 0
        return g * self.GRAM_STATES + s

    def warmup(self, steps_list: Optional[Tuple[int, ...]] = None,
               tokenizer=None) -> None:
        """Compile the serving program grid against a throwaway state BEFORE
        real traffic: every grouped-prefill bucket and every decode depth.
        First compiles over a tunneled chip run ~20-40 s EACH — paying them
        lazily mid-serving stalls live requests (and a bench would measure
        compile, not serving). With ``tokenizer``, the CONSTRAINED-decoding
        program variants (use_grammar=True — separate compiles; the grammar
        tables have static shapes, so one tiny grammar warms them all) are
        compiled too."""
        if steps_list is None:
            # every power of two the adaptive scheduler can pick
            base = self.cfg.decode_steps_per_dispatch
            cap = max(self.cfg.decode_steps_max, base)
            steps_list, s = [], base
            while s <= cap:
                steps_list.append(s)
                s *= 2
        gram_start = 0
        if tokenizer is not None:
            from generativeaiexamples_tpu.engine import grammar as grammar_mod
            self.ensure_token_bytes(tokenizer)
            gram_start = self.register_grammar(
                grammar_mod.Grammar.from_schema({"type": "boolean"}))
        state = self.init_state()
        table = self.put_table(
            np.zeros((self.batch, self.max_pages_per_slot), np.int32))
        last_out = None
        for gs in ((0, gram_start) if gram_start else (0,)):
            for g in self.group_buckets:
                items = [PrefillItem(
                    chunk_ids=[1] * min(4, self.chunk), page_row=np.zeros(
                        (self.max_pages_per_slot,), np.int32),
                    slot=self.batch, start_pos=0, is_last=True, generated=1,
                    max_gen=0, gram_state=gs)
                    for _ in range(g)]  # OOB slots: compiles, writes nothing
                state, last_out = self.prefill_group(state, items)
            if self.role == "prefill":
                # a prefill-role worker never dispatches decode (the
                # scheduler gates it off): skip the whole decode/mixed
                # compile grid — most of a unified worker's warmup time
                continue
            # every (steps x spec-width x batch-width) rung the adaptive
            # controllers can pick — width-ladder transitions must never
            # pay an XLA compile mid-serving (test-pinned). The grammar
            # variant compiles at the CEILING width and full batch only
            # (the scheduler pins grammared dispatches there — a minority
            # of traffic is not worth ladder x grammar compiles).
            for steps in steps_list:
                if gs:
                    state, out = self.decode(state, table, steps,
                                             use_grammar=True)
                    last_out = out["packed"]
                    continue
                for wi in self.spec_widths:
                    for bw in self.decode_widths:
                        state, out = self.decode(state, table, steps,
                                                 spec_width=wi, width=bw)
                        last_out = out["packed"]
            if not gs and self.multi_ms:
                # every multi-step M rung, at the base K only: multi-step
                # dispatches never deepen K (the M ladder IS the depth
                # ladder there), so (base, m) is the whole compile grid —
                # an M transition mid-serving is always a cache hit
                for mi in self.multi_ms:
                    state, out = self.decode_multi(state, table, m=mi)
                    last_out = out["packed"]
            if self.mixed_supported:
                # the mixed-phase program at EVERY depth the adaptive
                # scheduler can pick, in BOTH grammar modes — a grammared
                # slot decoding when a plain prompt is admitted dispatches
                # decode_mixed(use_grammar=True), which must not pay its
                # compile mid-serving. ``is_last`` and ``gram_states``
                # ride as data (one compile serves any mid/final/grammared
                # mix); spec/batch width ladders do NOT apply to mixed
                # (see mixed_bucket); the single-chunk and full-group
                # buckets warm here, intermediate buckets compile lazily
                # like narrower page-pressure depths
                for g in sorted({1, self.group_buckets[-1]}):
                    items = [PrefillItem(
                        chunk_ids=[1] * min(4, self.chunk),
                        page_row=np.zeros((self.max_pages_per_slot,),
                                          np.int32),
                        slot=self.batch, start_pos=0, is_last=bool(i % 2),
                        generated=1, max_gen=0)
                        for i in range(g)]
                    for steps in steps_list:
                        state, out = self.decode_mixed(
                            state, table, steps, items,
                            use_grammar=bool(gs))
                        last_out = out["packed"]
        # suppressed devtime-fence: warmup's one deliberate fence — every
        # compile must land before serving starts (the whole point)
        jax.block_until_ready(last_out)   # tpulint: disable=devtime-fence -- warmup must block until the compile grid lands
        # compile-watch (observability/devtime.py): record exactly the keys
        # this grid compiled, so their first SERVING dispatch is not
        # mistaken for a mid-serving recompile. Keys warmup deliberately
        # leaves cold (want_top variants, intermediate mixed group buckets,
        # narrower page-pressure decode depths, the long-prefill ring pass)
        # stay unmarked — their first live use IS a real latency cliff and
        # must fire the recompile watch.
        from generativeaiexamples_tpu.observability.devtime import DEVTIME
        for g in self.group_buckets:
            DEVTIME.mark_warm("prefill", f"g{g}")
        for gs in ((0, gram_start) if gram_start else (0,)):
            suffix = "+gram" if gs else ""
            if self.role == "prefill":
                continue
            for steps in steps_list:
                if gs:
                    DEVTIME.mark_warm(f"decode{suffix}",
                                      self.decode_bucket(steps))
                    continue
                for wi in self.spec_widths:
                    for bw in self.decode_widths:
                        DEVTIME.mark_warm(f"decode{suffix}",
                                          self.decode_bucket(steps, wi, bw))
            if not gs and self.multi_ms:
                base_k = self.cfg.decode_steps_per_dispatch
                for mi in self.multi_ms:
                    DEVTIME.mark_warm("decode_multi",
                                      self.decode_multi_bucket(base_k, mi))
            if self.mixed_supported:
                for g in sorted({1, self.group_buckets[-1]}):
                    for steps in steps_list:
                        DEVTIME.mark_warm(f"mixed{suffix}",
                                          self.mixed_bucket(g, steps))
        # the throwaway pool frees here; callers init the real state after

    # --------------------------------------------------------- slot lifecycle

    def _activate_impl(self, state: DecodeState, slot, token, generated,
                       max_gen, temperature, top_k, top_p, seed, gram_state
                       ) -> DecodeState:
        upd = lambda arr, val: arr.at[slot].set(val)
        hist = state.history.at[
            slot, jnp.minimum(state.cache.lengths[slot],
                              self.max_seq - 1)].set(token)
        return dataclasses.replace(
            state,
            history=hist,
            tokens=upd(state.tokens, token),
            active=upd(state.active, True),
            generated=upd(state.generated, generated),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
            rngs=upd(state.rngs, jax.random.PRNGKey(seed)),
            # 0 (the default) clears any previous occupant's DFA state (no
            # leakage); a handed-off grammared request instead resumes at
            # the host-walked state the prefill worker's first token
            # reached (scheduler._admit_prefilled)
            gram_state=upd(state.gram_state, gram_state),
            last_logprob=upd(state.last_logprob, jnp.float32(0.0)),
            adapter_ix=upd(state.adapter_ix, jnp.int32(0)),
        )

    def activate(self, state: DecodeState, slot: int, token: int,
                 generated: int, max_gen: int, temperature: float, top_k: int,
                 top_p: float, seed: int = 0,
                 gram_state: int = 0) -> DecodeState:
        """Start decoding a prefilled slot (its lengths were set by the last
        chunk; ``generated`` counts tokens already produced, >=1).
        ``gram_state`` seeds the slot's constrained-decoding DFA state
        (flat, THIS engine's grammar stack) — the KV handoff's grammar
        continuation; 0 = unconstrained, and clears the slot either way."""
        return self._activate_fn(
            state, jnp.int32(slot), jnp.int32(token), jnp.int32(generated),
            jnp.int32(max_gen), jnp.float32(temperature), jnp.int32(top_k),
            jnp.float32(top_p), jnp.int32(seed), jnp.int32(gram_state))

    # ------------------------------------------------- multi-LoRA serving

    def register_adapter(self, name: str, tree) -> int:
        """Install a trained adapter pytree (train/lora.py layout: leaves
        (L, in, r)/(L, r, out)) into a resident slot; requests select it by
        name (Request.adapter / the OpenAI `model` field). The first
        registration switches the engine to STACKED adapter serving —
        programs retrace once (register before `warmup` in production).
        Slot 0 stays the all-zero base adapter."""
        if not name:
            raise ValueError("adapter name must be non-empty")
        if name in self._adapter_names:
            # No-rebind invariant: a registered name maps to the SAME
            # weights forever. The scheduler's prefix-cache hash seed
            # namespaces KV pages by adapter NAME alone (scheduler
            # _cache_seed) — rebinding a name to new weights would serve
            # pages computed under the old ones. Idempotent re-registration
            # of identical weights is allowed; anything else is refused.
            ix = self._adapter_names[name]
            if self._adapters_stacked:
                def _matches(s, leaf) -> bool:
                    # EXACT equality, not allclose: the slot was written via
                    # this same astype, so a true re-register matches
                    # bitwise — while an incremental fine-tune whose deltas
                    # sit under a tolerance must NOT be absorbed as
                    # "identical" (it would silently serve stale weights)
                    resident = s[:, ix]
                    return (tuple(leaf.shape) == tuple(resident.shape)
                            and bool(jnp.array_equal(resident,
                                                     leaf.astype(s.dtype))))
                try:
                    same = all(jax.tree.leaves(
                        jax.tree.map(_matches, self.adapters, tree)))
                except ValueError:   # different tree structure = rebind
                    same = False
                if not same:
                    raise ValueError(
                        f"adapter {name!r} is already registered with "
                        f"different weights; rebinding is not supported — "
                        f"prefix-cache pages are namespaced by adapter name "
                        f"and would go stale. Register under a new name.")
            return ix
        if self.adapters is not None and not self._adapters_stacked:
            raise ValueError(
                "engine was built with a global adapter tree; per-request "
                "adapters need a base-only engine (serve the global tree "
                "merged, or register it as a named adapter instead)")
        N = self.cfg.max_adapters
        ix = len(self._adapter_names)
        if ix >= N:
            raise ValueError(f"all {N} adapter slots in use "
                             f"(APP_ENGINE_MAX_ADAPTERS)")
        if not self._adapters_stacked:
            # (L, …) -> (L, N, …) zero-initialized slot stack
            self.adapters = jax.tree.map(
                lambda leaf: jnp.zeros(
                    (leaf.shape[0], N) + leaf.shape[1:], leaf.dtype),
                tree)
            self._adapters_stacked = True

        def _set(s, leaf):
            # explicit shape check: a rank-mismatched adapter must fail
            # loudly here — `.at[].set` would BROADCAST a rank-1 leaf
            # across a wider slot (serving it at rank-times its scale)
            if tuple(leaf.shape) != (s.shape[0],) + tuple(s.shape[2:]):
                raise ValueError(
                    f"adapter {name!r} leaf shape {tuple(leaf.shape)} does "
                    f"not match the resident slot layout "
                    f"{(s.shape[0],) + tuple(s.shape[2:])} — all resident "
                    f"adapters must share rank/targets (first registration "
                    f"fixes the layout)")
            return s.at[:, ix].set(leaf.astype(s.dtype))

        self.adapters = jax.tree.map(_set, self.adapters, tree)
        if self.mesh is not None:
            self.adapters = jax.device_put(self.adapters, self._replicated)
        self._adapter_names[name] = ix
        return ix

    def adapter_index(self, name: str) -> int:
        """Resolve a request's adapter name (KeyError for unknown names —
        the scheduler fails the request loudly, never silently serves
        base weights under a fine-tune's name)."""
        return self._adapter_names[name or ""]

    @property
    def adapter_names(self):
        return [n for n in self._adapter_names if n]

    def _seed_history_impl(self, state: DecodeState, slot, ids
                           ) -> DecodeState:
        return dataclasses.replace(
            state, history=state.history.at[slot].set(ids))

    def seed_history(self, state: DecodeState, slot: int, ids) -> DecodeState:
        """Host-side history seed for a slot whose prompt prefix was served
        from the prefix cache: those chunks never flow through a prefill
        dispatch, so the drafting history must be written explicitly (one
        (max_seq,) transfer per cache-hit admission)."""
        padded = np.zeros((self.max_seq,), np.int32)
        padded[:len(ids)] = ids[:self.max_seq]
        return self._seed_hist_fn(state, jnp.int32(slot), jnp.asarray(padded))

    def _release_impl(self, state: DecodeState, slot) -> DecodeState:
        return dataclasses.replace(state,
                                   active=state.active.at[slot].set(False))

    def release(self, state: DecodeState, slot: int) -> DecodeState:
        """Deactivate a slot (preemption); its pages may be reused at once —
        subsequent decode writes for the slot go to the null page."""
        return self._release_fn(state, jnp.int32(slot))

    # ------------------------------------------- KV handoff (disaggregation)

    def _export_bucket(self, n_exp: int) -> int:
        """Power-of-two page-count buckets bound the handoff programs' XLA
        compile count (the gather/scatter shapes are otherwise one compile
        per distinct prompt page count)."""
        b = 1
        while b < n_exp:
            b *= 2
        return min(b, self.max_pages_per_slot)

    def _export_impl(self, state: DecodeState, page_ids):
        return kv_cache.export_pages(state.cache, page_ids, self.num_pages)

    def export_slot_kv(self, state: DecodeState, pages, length,
                       fetch: bool = False) -> dict:   # tpulint: hot-path
        """Gather a prefilled slot's live pages into a dense handoff
        payload (kv_cache.export_pages) — the prefill worker's half of
        disaggregated serving. Dtype-preserving: an int8 pool ships int8
        values + f32 scales, never a dequantized copy.

        DEVICE-NATIVE by default (``fetch=False``): the payload's array
        values stay jax arrays — the gather is dispatched (in-order, so
        page reuse after release cannot race it: its outputs are fresh
        buffers) but the driver thread never blocks on a device→host
        copy. An in-process consumer (``import_slot_kv`` on a decode
        scheduler sharing this host/mesh — the bench's co-hosted roles,
        the tiered-cache demotion path) scatters the device buffers
        straight back in, skipping the host roundtrip entirely; the HTTP
        plane instead materializes them exactly once at wire-encode time
        (core/kv_wire.encode_for_wire — the one deliberate copy-out per
        remotely-handed-off request, now off the driver thread).
        ``fetch=True`` restores the old blocking host export (numpy
        buffers in the payload)."""
        n_exp = max(1, -(-int(length) // self.page_size))
        b = self._export_bucket(n_exp)
        ids = np.zeros((b,), np.int32)
        ids[:n_exp] = list(pages)[:n_exp]
        k, v, k_s, v_s = self._export_fn(state, jnp.asarray(ids))
        L = self.model_cfg.n_layers

        def trim(a):
            if a is None:
                return None
            if not fetch:
                # device-native: reshape/slice stay lazy device views;
                # whoever needs host bytes pays the copy there
                return a.reshape((L, b) + a.shape[1:])[:, :n_exp]
            # tpulint: disable=trace-hazard,devtime-fence -- the export IS
            # the copy-out: one deliberate device->host fetch per handed-off
            # request (the prefill role's per-request sync point, documented
            # above), not a decode-path result fetch
            host = np.asarray(jax.device_get(a))
            return np.ascontiguousarray(
                host.reshape((L, b) + host.shape[1:])[:, :n_exp])

        return {
            "version": 1,
            "length": int(length),
            "n_pages": n_exp,
            "page_size": self.page_size,
            "n_layers": L,
            "kv_dim": self.model_cfg.n_kv_heads * self.model_cfg.head_dim,
            "kv_dtype": self._kv_dtype,
            "k": trim(k), "v": trim(v),
            "k_s": trim(k_s), "v_s": trim(v_s),
        }

    def validate_handoff(self, payload: dict) -> None:
        """Loudly refuse a payload this pool cannot host — a silent page-
        size / layer-count / dtype mismatch would serve garbage KV as if it
        were the prompt."""
        mine = {"page_size": self.page_size,
                "n_layers": self.model_cfg.n_layers,
                "kv_dim": self.model_cfg.n_kv_heads * self.model_cfg.head_dim,
                "kv_dtype": self._kv_dtype}
        for key, want in mine.items():
            got = payload.get(key)
            if got != want:
                raise ValueError(
                    f"handoff {key} mismatch: payload carries {got!r}, this "
                    f"engine serves {want!r} — prefill and decode workers "
                    f"must share model geometry and kv_quant")
        n = int(payload.get("length", 0))
        if n < 1 or n + 1 >= self.max_seq:
            raise ValueError(f"handoff length {n} outside this engine's "
                             f"serving range (max_seq {self.max_seq})")
        n_pages = int(payload.get("n_pages", 0))
        if n_pages != max(1, -(-n // self.page_size)):
            raise ValueError("handoff n_pages inconsistent with length")
        # cross-check the parts the scalars only CLAIM: prompt ids and the
        # buffers themselves. A self-consistent-but-wrong payload must be a
        # loud admission failure here — discovered later it would either
        # crash mid-tick (failing every in-flight request via the driver's
        # reset) or be silently zero-padded into garbage KV.
        if "prompt_ids" in payload and len(payload["prompt_ids"]) != n:
            raise ValueError(
                f"handoff length {n} does not match its "
                f"{len(payload['prompt_ids'])} prompt_ids")
        kv_dim = mine["kv_dim"]
        want_kv = (mine["n_layers"], n_pages, self.page_size, kv_dim)
        want_sc = (mine["n_layers"], n_pages, self.model_cfg.n_kv_heads,
                   self.page_size)
        for key, want in (("k", want_kv), ("v", want_kv),
                          ("k_s", want_sc), ("v_s", want_sc)):
            arr = payload.get(key)
            if arr is None:
                if key in ("k", "v") or self.cfg.kv_quant == "int8":
                    raise ValueError(f"handoff payload is missing {key!r}")
                continue
            shape = tuple(getattr(arr, "shape", ()))
            if shape != want:
                raise ValueError(
                    f"handoff {key} buffer shape {shape} does not match "
                    f"the metadata's {want}")

    def _import_impl(self, state: DecodeState, page_ids, slot, length,
                     k, v, k_s, v_s) -> DecodeState:
        cache = kv_cache.import_pages(state.cache, page_ids, self.num_pages,
                                      slot, length, k, v, k_s=k_s, v_s=v_s)
        return dataclasses.replace(state, cache=cache)

    def import_slot_kv(self, state: DecodeState, slot: int, pages,
                       payload: dict) -> DecodeState:   # tpulint: hot-path
        """Scatter an exported handoff payload into freshly allocated pages
        of THIS pool and set ``lengths[slot]`` (kv_cache.import_pages) —
        the decode worker's half of disaggregated serving. The caller
        (scheduler) then seeds history and activates the slot with the
        payload's first token, after which decode proceeds exactly as if
        the prefill had run locally."""
        self.validate_handoff(payload)
        n_exp = int(payload["n_pages"])
        b = self._export_bucket(n_exp)
        ids = np.zeros((b,), np.int32)
        ids[:n_exp] = list(pages)[:n_exp]
        L = self.model_cfg.n_layers

        def pad(a):
            if a is None:
                return None
            if isinstance(a, jax.Array):
                # device-native shortcut: an export from a scheduler
                # sharing this host/mesh arrives as device arrays — pad
                # and reshape on device, no host roundtrip at all
                if a.shape[1] < b:
                    a = jnp.pad(a, ((0, 0), (0, b - a.shape[1]))
                                + ((0, 0),) * (a.ndim - 2))
                return a.reshape((L * b,) + a.shape[2:])
            # host path: `a` may be a READ-ONLY np.frombuffer view into
            # the wire body (core/kv_wire.decode_kv_frames) — never write
            # into it; both branches below only read
            a = np.asarray(a)
            if a.shape[1] < b:
                a = np.concatenate(
                    [a, np.zeros((L, b - a.shape[1]) + a.shape[2:],
                                 a.dtype)], axis=1)
            return jnp.asarray(a.reshape((L * b,) + a.shape[2:]))

        quant = self.cfg.kv_quant == "int8"
        return self._import_fn(
            state, jnp.asarray(ids), jnp.int32(slot),
            jnp.int32(int(payload["length"])), pad(payload["k"]),
            pad(payload["v"]),
            pad(payload["k_s"]) if quant else None,
            pad(payload["v_s"]) if quant else None)

    def validate_pages_payload(self, payload: dict, n_pages: int) -> None:
        """Loudly refuse a PARTIAL page import this pool cannot host —
        the prefix-tier variant of :meth:`validate_handoff`. Geometry
        only: the tier promotes the first ``n_pages`` full pages of a
        cached run, so length/prompt consistency is the SCHEDULER's
        admission plan (it prefills the tail), but a page-size / layer /
        dtype mismatch would still scatter garbage KV."""
        mine = {"page_size": self.page_size,
                "n_layers": self.model_cfg.n_layers,
                "kv_dim": self.model_cfg.n_kv_heads * self.model_cfg.head_dim,
                "kv_dtype": self._kv_dtype}
        for key, want in mine.items():
            got = payload.get(key)
            if got != want:
                raise ValueError(
                    f"tier import {key} mismatch: payload carries {got!r}, "
                    f"this engine serves {want!r}")
        total = int(payload.get("n_pages", 0))
        if n_pages < 1 or n_pages > total:
            raise ValueError(f"tier import of {n_pages} pages from a "
                             f"{total}-page payload")
        if n_pages > self.max_pages_per_slot:
            raise ValueError(f"tier import of {n_pages} pages exceeds this "
                             f"engine's {self.max_pages_per_slot} pages/slot")
        kv_dim = mine["kv_dim"]
        want_kv = (mine["n_layers"], total, self.page_size, kv_dim)
        want_sc = (mine["n_layers"], total, self.model_cfg.n_kv_heads,
                   self.page_size)
        for key, want in (("k", want_kv), ("v", want_kv),
                          ("k_s", want_sc), ("v_s", want_sc)):
            arr = payload.get(key)
            if arr is None:
                if key in ("k", "v") or self.cfg.kv_quant == "int8":
                    raise ValueError(f"tier payload is missing {key!r}")
                continue
            shape = tuple(getattr(arr, "shape", ()))
            if shape != want:
                raise ValueError(
                    f"tier {key} buffer shape {shape} does not match the "
                    f"metadata's {want}")

    def _import_pages_impl(self, state: DecodeState, page_ids,
                           k, v, k_s, v_s) -> DecodeState:
        cache = kv_cache.import_pages_partial(
            state.cache, page_ids, self.num_pages, k, v, k_s=k_s, v_s=v_s)
        return dataclasses.replace(state, cache=cache)

    def import_pages_kv(self, state: DecodeState, pages, payload: dict,
                        n_pages: Optional[int] = None
                        ) -> DecodeState:   # tpulint: hot-path
        """Scatter the first ``n_pages`` pages of an exported payload into
        freshly allocated pages of THIS pool — the prefix-tier promotion
        (engine/kv_tier.py). No slot state is touched: the caller starts
        its chunked prefill at the covered boundary, so the promoted span
        costs zero prefill programs and the tail runs exactly as a fresh
        admission."""
        n_imp = int(payload["n_pages"] if n_pages is None else n_pages)
        self.validate_pages_payload(payload, n_imp)
        b = self._export_bucket(n_imp)
        ids = np.zeros((b,), np.int32)
        ids[:n_imp] = list(pages)[:n_imp]
        L = self.model_cfg.n_layers

        def pad(a):
            if a is None:
                return None
            if isinstance(a, jax.Array):
                a = a[:, :n_imp]
                if a.shape[1] < b:
                    a = jnp.pad(a, ((0, 0), (0, b - a.shape[1]))
                                + ((0, 0),) * (a.ndim - 2))
                return a.reshape((L * b,) + a.shape[2:])
            # host path: slicing may alias a READ-ONLY wire/disk view —
            # both branches below only read
            a = np.asarray(a)[:, :n_imp]
            if a.shape[1] < b:
                a = np.concatenate(
                    [a, np.zeros((L, b - a.shape[1]) + a.shape[2:],
                                 a.dtype)], axis=1)
            return jnp.asarray(np.ascontiguousarray(
                a.reshape((L * b,) + a.shape[2:])))

        quant = self.cfg.kv_quant == "int8"
        return self._import_pages_fn(
            state, jnp.asarray(ids), pad(payload["k"]), pad(payload["v"]),
            pad(payload["k_s"]) if quant else None,
            pad(payload["v_s"]) if quant else None)

    # ----------------------------------------------------------------- decode

    def _decode_step_fn(self, params, adapters, page_table, gram_table,
                        gram_accept, gram_dist, tok_bytes, tok_lens,
                        use_grammar: bool, want_top: bool,
                        spec_width: Optional[int] = None,
                        batch: Optional[int] = None, draft_cap=None):
        """Build the one-decode-step body shared by the pure-decode scan
        (`_decode_impl`) and the mixed-phase program (`_mixed_impl`).
        Returns ``step(state, forward=None) -> (state, out)`` with out
        leaves shaped (W, B); ``forward`` overrides the model call of THIS
        step — the mixed program injects kv_cache.mixed_step as step 0's
        forward so a prefill chunk rides the same dispatch. ``spec_width``
        (static) selects a width-ladder rung; ``batch`` (static) the slot
        width this program runs over (< self.batch for a narrow-rung
        pure-decode dispatch — the state/table the caller passes are
        already sliced); ``draft_cap`` is the traced (batch,) per-slot
        draft budget of the adaptive controller (None = uncapped)."""
        from generativeaiexamples_tpu.ops.sampling import (
            sample_logits_per_slot, token_logprob)
        W = spec_width or self.spec_width
        B = batch or self.batch
        batch_ix = jnp.arange(B, dtype=jnp.int32)

        def hist_append(history, active, cols, vals):
            """Append emitted tokens to history rows (inactive / OOB drop)."""
            safe = jnp.where(active & (cols < self.max_seq), cols,
                             self.max_seq)
            return history.at[batch_ix if vals.ndim == 1 else
                              batch_ix[:, None], safe].set(vals, mode="drop")

        def step_narrow(state, forward=None):
            if forward is None:
                forward = lambda st: kv_cache.decode_step(
                    params, self.model_cfg, st.tokens, st.cache,
                    page_table, st.active, self.num_pages, adapters=adapters,
                    adapter_ix=st.adapter_ix, mesh=self.mesh)
            logits, cache = forward(state)
            raw = logits.astype(jnp.float32)   # logprobs: model distribution
            if use_grammar:
                # constrained decoding INSIDE the fused step: byte-DFA
                # walk masks disallowed tokens, state advances with the
                # sample — no host round trip, fusion intact
                from generativeaiexamples_tpu.ops.sampling import (
                    grammar_advance, grammar_mask)
                logits = grammar_mask(
                    logits, state.gram_state,
                    state.max_gen - state.generated - 1, self.eos_id,
                    gram_table, gram_accept, gram_dist, tok_bytes, tok_lens)
            # inactive slots' stale temperatures must not defeat the
            # all-greedy fast path inside the sampler
            live_temp = jnp.where(state.active, state.temperature, 0.0)
            live_topk = jnp.where(state.active, state.top_k, 0)
            live_topp = jnp.where(state.active, state.top_p, 1.0)
            keys = jax.vmap(jax.random.fold_in)(state.rngs, state.generated)
            sampled = sample_logits_per_slot(keys, logits, live_temp,
                                             live_topk, live_topp)
            lp = token_logprob(raw, sampled)
            generated = state.generated + state.active.astype(jnp.int32)
            hit_eos = sampled == self.eos_id
            out_of_budget = generated >= state.max_gen
            out_of_cache = cache.lengths >= self.max_seq - 1
            done = state.active & (hit_eos | out_of_budget | out_of_cache)
            active = state.active & ~done
            # inactive slots keep their old lengths so cache positions stay
            lengths = jnp.where(state.active, cache.lengths,
                                state.cache.lengths)
            new_state = dataclasses.replace(
                state,
                cache=dataclasses.replace(cache, lengths=lengths),
                tokens=jnp.where(state.active, sampled, state.tokens),
                active=active,
                generated=generated,
                last_logprob=jnp.where(state.active, lp, state.last_logprob),
                history=hist_append(state.history, state.active, lengths,
                                    sampled),
            )
            if use_grammar:
                adv = grammar_advance(state.gram_state, sampled, gram_table,
                                      tok_bytes, tok_lens)
                new_state = dataclasses.replace(
                    new_state,
                    gram_state=jnp.where(state.active, adv,
                                         state.gram_state))
            out = {"sampled": sampled[None], "emitted": state.active[None],
                   "done": done[None], "hit_eos": hit_eos[None],
                   "input_tokens": state.tokens[None],
                   "sampled_lp": lp[None],
                   "input_lp": state.last_logprob[None]}
            if want_top:
                # top-TOP_LP alternatives per step (the OpenAI top_logprobs
                # surface) — a separate compile variant, so the common path
                # never pays the extra vocab sort
                top_vals, top_ids = jax.lax.top_k(raw, TOP_LP)
                lse = jax.nn.logsumexp(raw, axis=-1, keepdims=True)
                out["top_ids"] = top_ids.astype(jnp.int32)[None]  # (1, B, K)
                out["top_lps"] = (top_vals - lse)[None]
            return new_state, out

        def step_wide(state, forward=None):
            # prompt-lookup speculative verify: draft W-1 tokens from the
            # slot's own history, run ONE widened step over current+drafts,
            # accept the longest prefix matching the per-position seeded
            # samples. Decode is weight-read-bound, so the widened step
            # costs ~one narrow step; accepted drafts are ~free tokens,
            # and the emitted stream is token-identical to sequential
            # decoding (exact-match acceptance under the request's keys).
            from generativeaiexamples_tpu.ops.sampling import (
                grammar_advance, grammar_mask)
            from generativeaiexamples_tpu.ops.speculative import (
                acceptance, draft_lookup)
            if forward is None:
                forward = lambda inp, st: kv_cache.decode_step_wide(
                    params, self.model_cfg, inp, st.cache, page_table,
                    st.active, self.num_pages, adapters=adapters,
                    adapter_ix=st.adapter_ix, mesh=self.mesh)
            L = state.cache.lengths
            draft, dlen = draft_lookup(state.history, L, W - 1,
                                       self.cfg.spec_ngram)
            if draft_cap is not None:
                # adaptive spec width: the controller's per-slot draft
                # budget rides as traced data — capping only voids drafted
                # positions, so the emitted stream stays token-identical
                dlen = jnp.minimum(dlen, draft_cap)
            if use_grammar:
                # constrained slots decode sequentially (the DFA advances
                # one sampled token at a time); their drafts are voided
                dlen = jnp.where(state.gram_state > 0, 0, dlen)
            inputs = jnp.concatenate([state.tokens[:, None], draft], axis=1)
            logits_w, cache = forward(inputs, state)
            raw = logits_w.astype(jnp.float32)            # (B, W, V)
            logits_s = raw
            if use_grammar:
                m0 = grammar_mask(
                    logits_s[:, 0], state.gram_state,
                    state.max_gen - state.generated - 1, self.eos_id,
                    gram_table, gram_accept, gram_dist, tok_bytes, tok_lens)
                logits_s = jnp.concatenate([m0[:, None], logits_s[:, 1:]],
                                           axis=1)
            live_temp = jnp.where(state.active, state.temperature, 0.0)
            live_topk = jnp.where(state.active, state.top_k, 0)
            live_topp = jnp.where(state.active, state.top_p, 1.0)
            pos_w = jnp.arange(W, dtype=jnp.int32)[None]      # (1, W)
            gen_i = state.generated[:, None] + pos_w          # (B, W)
            keys = jax.vmap(jax.vmap(jax.random.fold_in, (None, 0)))(
                state.rngs, gen_i)                            # (B, W, 2)
            V = logits_s.shape[-1]
            rep = lambda x: jnp.repeat(x, W, axis=0)
            sampled = sample_logits_per_slot(
                keys.reshape(B * W, 2), logits_s.reshape(B * W, V),
                rep(live_temp), rep(live_topk),
                rep(live_topp)).reshape(B, W)
            lp = token_logprob(raw.reshape(B * W, V),
                               sampled.reshape(B * W)).reshape(B, W)
            e = acceptance(sampled, draft, dlen)              # (B,) 1..W
            # accepted positions must have REAL pages behind their KV
            # writes (the scheduler may not have grown the row that far
            # yet — such rows landed on the null page): clamp to the
            # leading covered span. Position L is always covered.
            covered = page_table[
                batch_ix[:, None],
                jnp.minimum(L[:, None] + pos_w,
                            self.max_seq - 1) // self.page_size] != 0
            lead_cov = jnp.sum(jnp.cumprod(covered.astype(jnp.int32),
                                           axis=1), axis=1)
            e = jnp.minimum(e, jnp.maximum(lead_cov, 1))
            # budget / capacity caps (active slots always afford >= 1)
            e = jnp.minimum(e, jnp.maximum(state.max_gen - state.generated,
                                           1))
            e = jnp.minimum(e, jnp.maximum((self.max_seq - 1) - L, 1))
            # eos inside the accepted window truncates it
            is_eos = sampled == self.eos_id
            first_eos = jnp.min(jnp.where(is_eos, pos_w,
                                          jnp.int32(W)), axis=1)
            e = jnp.minimum(e, first_eos + 1)
            emitted_w = state.active[:, None] & (pos_w < e[:, None])
            generated = state.generated + jnp.where(state.active, e, 0)
            lengths = jnp.where(state.active, L + e, L)
            last_ix = (e - 1)[:, None]
            last_tok = jnp.take_along_axis(sampled, last_ix, axis=1)[:, 0]
            last_lp = jnp.take_along_axis(lp, last_ix, axis=1)[:, 0]
            last_eos = jnp.take_along_axis(is_eos, last_ix, axis=1)[:, 0]
            out_of_budget = generated >= state.max_gen
            out_of_cache = lengths >= self.max_seq - 1
            done_slot = state.active & (last_eos | out_of_budget
                                        | out_of_cache)
            done_w = emitted_w & (pos_w == last_ix) & done_slot[:, None]
            active = state.active & ~done_slot
            new_state = dataclasses.replace(
                state,
                cache=dataclasses.replace(cache, lengths=lengths),
                tokens=jnp.where(state.active, last_tok, state.tokens),
                active=active,
                generated=generated,
                last_logprob=jnp.where(state.active, last_lp,
                                       state.last_logprob),
                history=hist_append(state.history, emitted_w,
                                    L[:, None] + 1 + pos_w, sampled),
            )
            if use_grammar:
                adv = grammar_advance(state.gram_state, sampled[:, 0],
                                      gram_table, tok_bytes, tok_lens)
                new_state = dataclasses.replace(
                    new_state,
                    gram_state=jnp.where(state.active, adv,
                                         state.gram_state))
            t = lambda x: jnp.transpose(x)                    # (B,W)→(W,B)
            out = {"sampled": t(sampled), "emitted": t(emitted_w),
                   "done": t(done_w), "hit_eos": t(is_eos),
                   "input_tokens": t(inputs),
                   "sampled_lp": t(lp),
                   "input_lp": jnp.concatenate(
                       [state.last_logprob[None],
                        jnp.zeros((W - 1, B), jnp.float32)])}
            if want_top:
                top_vals, top_ids = jax.lax.top_k(raw, TOP_LP)  # (B, W, K)
                lse = jax.nn.logsumexp(raw, axis=-1, keepdims=True)
                out["top_ids"] = jnp.transpose(
                    top_ids.astype(jnp.int32), (1, 0, 2))       # (W, B, K)
                out["top_lps"] = jnp.transpose(top_vals - lse, (1, 0, 2))
            return new_state, out

        return step_wide if W > 1 else step_narrow

    def _slice_state(self, state: DecodeState, width: int
                     ) -> DecodeState:
        """Narrow-rung view of the per-slot state: every (B, …) leaf (and
        the cache's lengths) sliced to the first ``width`` slots. The KV
        pools themselves are slot-agnostic (physical pages) and ride whole."""
        sl = lambda a: a[:width]
        return DecodeState(
            cache=dataclasses.replace(state.cache,
                                      lengths=sl(state.cache.lengths)),
            tokens=sl(state.tokens), active=sl(state.active),
            generated=sl(state.generated), max_gen=sl(state.max_gen),
            temperature=sl(state.temperature), top_k=sl(state.top_k),
            top_p=sl(state.top_p), rngs=sl(state.rngs),
            gram_state=sl(state.gram_state),
            last_logprob=sl(state.last_logprob), history=sl(state.history),
            adapter_ix=sl(state.adapter_ix))

    def _merge_state(self, full: DecodeState, narrow: DecodeState,
                     width: int) -> DecodeState:
        """Scatter a narrow-rung run's per-slot results back into the full
        state (slots >= width were untouched by construction — the width
        rung covers every live slot)."""
        up = lambda f, n: f.at[:width].set(n)
        return DecodeState(
            cache=dataclasses.replace(
                narrow.cache,
                lengths=up(full.cache.lengths, narrow.cache.lengths)),
            tokens=up(full.tokens, narrow.tokens),
            active=up(full.active, narrow.active),
            generated=up(full.generated, narrow.generated),
            max_gen=up(full.max_gen, narrow.max_gen),
            temperature=up(full.temperature, narrow.temperature),
            top_k=up(full.top_k, narrow.top_k),
            top_p=up(full.top_p, narrow.top_p),
            rngs=up(full.rngs, narrow.rngs),
            gram_state=up(full.gram_state, narrow.gram_state),
            last_logprob=up(full.last_logprob, narrow.last_logprob),
            history=up(full.history, narrow.history),
            adapter_ix=up(full.adapter_ix, narrow.adapter_ix))

    def _decode_impl(self, state: DecodeState, params, adapters, page_table,
                     gram_table, gram_accept, gram_dist, tok_bytes, tok_lens,
                     draft_cap, steps: int, use_grammar: bool,
                     want_top: bool, spec_width: int, width: int
                     ) -> Tuple[DecodeState, Dict[str, Any]]:
        full = state
        narrow = width < self.batch
        if narrow:
            # batch-width ladder rung: run the scan over the first `width`
            # slots only — the scheduler guarantees every live slot is
            # below the rung (lowest-id-first allocation) — then scatter
            # the per-slot results back into the full state
            state = self._slice_state(state, width)
            page_table = page_table[:width]
            draft_cap = draft_cap[:width] if draft_cap is not None else None
        step = self._decode_step_fn(params, adapters, page_table, gram_table,
                                    gram_accept, gram_dist, tok_bytes,
                                    tok_lens, use_grammar, want_top,
                                    spec_width=spec_width, batch=width,
                                    draft_cap=draft_cap)
        # K fused steps per dispatch: the host syncs once per K (or K·W
        # with speculation) tokens/slot, which is what makes decode
        # dispatch-latency-proof (SURVEY hard-part #3; essential over the
        # tunneled single-chip dev setup, still a win on local PCIe/ICI-
        # attached hosts). outs arrays are (K, W, width).
        state, outs = jax.lax.scan(lambda s, _: step(s), state, None,
                                   length=steps)
        if narrow:
            state = self._merge_state(full, state, width)
        return state, self._pack_decode_outs(outs, steps, want_top,
                                             spec_width)

    def _pack_decode_outs(self, outs: Dict[str, Any], steps: int,
                          want_top: bool, spec_width: Optional[int] = None
                          ) -> Dict[str, Any]:
        # one contiguous int32 block so the host fetches the whole dispatch
        # result in a single transfer (a pytree device_get pays one round
        # trip PER LEAF — 5x the latency on a remote-attached chip);
        # float rows ride as raw bits (bitcast), not int casts. Micro-rows
        # are (step, position) pairs flattened in order. B is the dispatch's
        # slot width (< self.batch on a narrow batch-width rung).
        B = outs["sampled"].shape[-1]
        W = spec_width or self.spec_width
        R = steps * W

        def as_row(k):
            v = outs[k]
            if k in _LP_FIELDS:
                v = jax.lax.bitcast_convert_type(v, jnp.int32)
            return v.astype(jnp.int32).reshape(R, B)
        rows = [as_row(k) for k in _PACKED_FIELDS]
        if want_top:
            tid = jnp.moveaxis(outs["top_ids"], -1, 0)    # (K_top, K, W, B)
            tlp = jnp.moveaxis(jax.lax.bitcast_convert_type(
                outs["top_lps"], jnp.int32), -1, 0)
            rows += [r.reshape(R, B) for r in tid]
            rows += [r.reshape(R, B) for r in tlp]
        outs["packed"] = jnp.stack(rows)
        # device-side convenience views share the packed micro-row layout
        # ((steps·W, B) — identical to the pre-speculation (steps, B) when
        # W == 1, which direct-decode callers and tests rely on)
        for k in _PACKED_FIELDS:
            outs[k] = outs[k].reshape(R, B)
        if want_top:
            outs["top_ids"] = outs["top_ids"].reshape(R, B, TOP_LP)
            outs["top_lps"] = outs["top_lps"].reshape(R, B, TOP_LP)
        return outs

    # multi-step decode: recent-token ring width for the on-device
    # stop-string maybe-match. 8 tokens cover any practical stop string's
    # first contributing token (the flag is per-token, not per-match).
    STOP_RING = 8

    def _decode_multi_impl(self, state: DecodeState, params, adapters,
                           page_table, stop_suspect, has_stop,
                           steps: int, m: int
                           ) -> Tuple[DecodeState, Dict[str, Any]]:
        """K·M plain decode steps in ONE program (the multi-step ladder's
        whole point: one host interaction — dispatch + deferred packed
        fetch — per K·M tokens/slot). Reuses the per-step scan body
        unchanged, so the emitted token stream is identical to M
        consecutive per-step dispatches by construction. The per-slot
        stop tail runs on-device: EOS/budget/capacity already end a slot
        inside the step (``done`` masking); the conservative stop-string
        maybe-match additionally *pauses* a slot's scan (``running``
        mask over a ring of recent sampled ids) without touching
        ``state.active`` — the host replays the fetched block, applies
        the real stop-string holdback, and the slot resumes on the next
        dispatch if the suspicion was false. When every slot is done or
        paused, remaining iterations skip the forward pass entirely
        (lax.cond) — the while_loop-style early exit."""
        B = self.batch
        step = self._decode_step_fn(params, adapters, page_table, None,
                                    None, None, None, None,
                                    use_grammar=False, want_top=False,
                                    spec_width=1, batch=B)
        v_pad = stop_suspect.shape[0] - 1   # ring padding id, never suspect

        def body(carry, _):
            st, running, ring = carry
            live = st.active & running

            def run(st):
                masked = dataclasses.replace(st, active=live)
                new_st, out = step(masked)
                # a paused (not done) slot keeps its activation — it
                # resumes token-identically on the next dispatch
                new_st = dataclasses.replace(
                    new_st,
                    active=jnp.where(running, new_st.active, st.active))
                return new_st, out

            def skip(st):
                zb = jnp.zeros((1, B), bool)
                zi = jnp.zeros((1, B), jnp.int32)
                zf = jnp.zeros((1, B), jnp.float32)
                return st, {"sampled": zi, "emitted": zb, "done": zb,
                            "hit_eos": zb, "input_tokens": zi,
                            "sampled_lp": zf, "input_lp": zf}

            st, out = jax.lax.cond(live.any(), run, skip, st)
            emitted = out["emitted"][0]
            ring = jnp.where(
                emitted[:, None],
                jnp.concatenate([ring[:, 1:], out["sampled"][0][:, None]],
                                axis=1),
                ring)
            maybe = has_stop & stop_suspect[ring].any(axis=1)
            running = running & ~maybe
            return (st, running, ring), out

        running0 = jnp.ones((B,), bool)
        ring0 = jnp.full((B, self.STOP_RING), v_pad, jnp.int32)
        (state, _, _), outs = jax.lax.scan(
            body, (state, running0, ring0), None, length=steps * m)
        return state, self._pack_decode_outs(outs, steps * m,
                                             want_top=False, spec_width=1)

    def _activate_group(self, state: DecodeState, logits, slots, is_last,
                        start_pos, chunk_len, generated, max_gen,
                        temperature, top_k, top_p, seeds, gram_states,
                        gram_table, gram_accept, gram_dist, tok_bytes,
                        tok_lens, use_grammar: bool) -> DecodeState:
        """Grouped on-device first-token sample + slot activation for the
        ``is_last`` rows of a mixed dispatch — `_group_impl`'s activation
        tail. With ``use_grammar`` (static) the fused first token samples
        under each row's DFA state and the advanced state is scattered
        into DecodeState.gram_state, exactly as the grouped prefill
        program does — grammared finals ride the mixed fast path instead
        of forcing a separate dispatch. Rows with is_last False — and
        padding rows, slot == batch — drop every scatter, so one compile
        serves any mid/final mix."""
        from generativeaiexamples_tpu.ops.sampling import (
            sample_logits_per_slot, token_logprob)
        raw = logits   # pre-mask: logprobs report the model distribution
        if use_grammar:
            from generativeaiexamples_tpu.ops.sampling import (
                grammar_advance, grammar_mask)
            logits = grammar_mask(logits, gram_states, max_gen - generated,
                                  self.eos_id, gram_table, gram_accept,
                                  gram_dist, tok_bytes, tok_lens)
        bases = jax.vmap(jax.random.PRNGKey)(seeds)           # (G, 2)
        subs = jax.vmap(jax.random.fold_in)(bases, generated - 1)
        toks = sample_logits_per_slot(subs, logits, temperature, top_k,
                                      top_p)
        lps = token_logprob(raw, toks)
        alive = is_last & (toks != self.eos_id) & (generated < max_gen)
        act_slots = jnp.where(is_last, slots, jnp.int32(self.batch))
        upd = lambda arr, val: arr.at[act_slots].set(val, mode="drop")
        # the fused token enters history at its position (= prompt length,
        # which the step-0 lengths scatter just set for these slots)
        tok_col = jnp.minimum(start_pos + chunk_len, self.max_seq - 1)
        hist = state.history.at[act_slots, tok_col].set(toks, mode="drop")
        zeros = jnp.zeros_like(slots)
        if use_grammar:
            nxt = grammar_advance(gram_states, toks, gram_table, tok_bytes,
                                  tok_lens)
        else:
            # still scatter: activation must CLEAR a previous occupant's
            # DFA state (gram_states is all zeros in this program variant)
            nxt = gram_states
        return dataclasses.replace(
            state,
            tokens=upd(state.tokens, toks),
            active=upd(state.active, alive),
            generated=upd(state.generated, generated),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
            rngs=upd(state.rngs, bases),
            gram_state=upd(state.gram_state, nxt),
            last_logprob=upd(state.last_logprob, lps),
            history=hist,
            adapter_ix=upd(state.adapter_ix, zeros),
        )

    def _mixed_impl(self, state: DecodeState, params, adapters, page_table,
                    gram_table, gram_accept, gram_dist, tok_bytes, tok_lens,
                    tokens, page_rows, slots, len_slots, start_pos,
                    chunk_len, is_last, generated, max_gen, temperature,
                    top_k, top_p, seeds, gram_states, draft_cap, steps: int,
                    use_grammar: bool, want_top: bool, spec_width: int
                    ) -> Tuple[DecodeState, Dict[str, Any]]:
        """The MIXED-PHASE program: `steps` fused decode steps where step 0's
        forward ALSO prefills up to G chunks from DISTINCT prefilling slots
        (kv_cache.mixed_step) — prefill stops being a separate dispatch, so
        admissions no longer stall the decode tick (ROADMAP item 2; the r05
        third-phase TTFT tail). Decode semantics are bit-identical to
        `_decode_impl` (same step body, with step 0's model call swapped);
        the chunks follow the `_group_impl` contract: lengths (via the
        ``len_slots`` duplicate-scatter dedup) + history are set after step
        0, and ``is_last`` rows run the fused first-token sample + slot
        activation AFTER the scan, so fresh slots start decoding next
        dispatch exactly as on the two-dispatch path. ``is_last`` rides as
        data, so one compile per group bucket serves any mid/final mix.
        Grammared finals ride too: ``gram_states`` is traced data and the
        activation tail samples/advances under the DFA exactly as the
        grouped prefill program does (`_activate_group`)."""
        step = self._decode_step_fn(params, adapters, page_table, gram_table,
                                    gram_accept, gram_dist, tok_bytes,
                                    tok_lens, use_grammar, want_top,
                                    spec_width=spec_width, batch=self.batch,
                                    draft_cap=draft_cap)
        W = spec_width
        cell: Dict[str, Any] = {}

        if W > 1:
            def forward(inputs, st):
                dec, ch, cache = kv_cache.mixed_step(
                    params, self.model_cfg, inputs, st.cache, page_table,
                    st.active, self.num_pages, tokens, page_rows, start_pos,
                    chunk_len, mesh=self.mesh, q_block=self._mixed_q_block)
                cell["chunk_logits"] = ch
                return dec, cache
        else:
            def forward(st):
                dec, ch, cache = kv_cache.mixed_step(
                    params, self.model_cfg, st.tokens[:, None], st.cache,
                    page_table, st.active, self.num_pages, tokens, page_rows,
                    start_pos, chunk_len, mesh=self.mesh,
                    q_block=self._mixed_q_block)
                cell["chunk_logits"] = ch
                # mirror kv_cache.decode_step's narrow wrapper contract
                return dec[:, 0], dataclasses.replace(
                    cache, lengths=cache.lengths + 1)

        state, out0 = step(state, forward=forward)
        # the chunks' page writes are now part of the dispatched program:
        # record lengths + history exactly as _group_impl does (the chunk
        # slots are inactive during the scan, so later steps keep both
        # untouched; padding rows carry OOB slots and drop)
        G, C = tokens.shape
        j = jnp.arange(C, dtype=jnp.int32)[None]              # (1, C)
        h_rows = jnp.broadcast_to(slots[:, None], (G, C))
        h_cols = jnp.where(j < chunk_len[:, None],
                           start_pos[:, None] + j, self.max_seq)
        state = dataclasses.replace(
            state,
            cache=dataclasses.replace(
                state.cache,
                lengths=state.cache.lengths.at[len_slots].set(
                    start_pos + chunk_len, mode="drop")),
            history=state.history.at[h_rows, h_cols].set(tokens,
                                                         mode="drop"))
        if steps > 1:
            state, outs = jax.lax.scan(lambda s, _: step(s), state, None,
                                       length=steps - 1)
            outs = jax.tree.map(
                lambda a, b: jnp.concatenate([a[None], b], axis=0), out0,
                outs)
        else:
            outs = jax.tree.map(lambda x: x[None], out0)
        # fused first-token sample + activation AFTER the scan for is_last
        # rows: fresh slots join decode at the NEXT dispatch, so their
        # first tokens resolve through the same batched fetch /
        # input_tokens paths as a grouped-prefill activation
        state = self._activate_group(state, cell["chunk_logits"], slots,
                                     is_last, start_pos, chunk_len,
                                     generated, max_gen, temperature,
                                     top_k, top_p, seeds, gram_states,
                                     gram_table, gram_accept, gram_dist,
                                     tok_bytes, tok_lens, use_grammar)
        return state, self._pack_decode_outs(outs, steps, want_top,
                                             spec_width)

    def decode_mixed(self, state: DecodeState, page_table: jax.Array,   # tpulint: hot-path
                     steps: int, items, use_grammar: bool = False,
                     want_top: bool = False, *,
                     spec_width: Optional[int] = None, draft_cap=None
                     ) -> Tuple[DecodeState, Dict[str, Any]]:
        """One mixed-phase dispatch: ``steps`` fused decode steps PLUS up to
        ``prefill_group`` prefill chunks from DISTINCT prefilling jobs
        riding the same program as extra ragged rows (`_mixed_impl`).
        ``items`` is a PrefillItem or a list of them, exactly as
        `prefill_group` would take them (the scheduler's packing policy is
        unchanged — the same chunks, fused instead of dispatched
        separately); groups pad to the `group_buckets` power-of-two ladder
        so the program count stays bounded. Requires `mixed_supported`;
        the out block is identical to `decode`'s."""
        if isinstance(items, PrefillItem):
            items = [items]
        if not self.mixed_supported:
            raise ValueError("mixed-phase dispatch is gated off for this "
                             "engine (APP_MIXED_PHASE_DISPATCH, adapters, "
                             "or an unsupported config)")
        G = next(b for b in self.group_buckets if len(items) <= b)
        C = self.chunk
        maxp = self.max_pages_per_slot
        tokens = np.zeros((G, C), np.int32)
        page_rows = np.zeros((G, maxp), np.int32)
        slots = np.full((G,), self.batch, np.int32)      # padding = OOB
        start_pos = np.zeros((G,), np.int32)
        chunk_len = np.zeros((G,), np.int32)
        is_last = np.zeros((G,), bool)
        generated = np.ones((G,), np.int32)
        max_gen = np.zeros((G,), np.int32)
        temperature = np.ones((G,), np.float32)
        top_k = np.zeros((G,), np.int32)
        top_p = np.ones((G,), np.float32)
        seeds = np.zeros((G,), np.int32)
        gram_states = np.zeros((G,), np.int32)
        for i, it in enumerate(items):
            n = len(it.chunk_ids)
            if n > C:
                raise ValueError(f"chunk of {n} tokens exceeds "
                                 f"prefill_chunk ({C})")
            tokens[i, :n] = it.chunk_ids
            page_rows[i] = it.page_row
            slots[i] = it.slot
            start_pos[i] = it.start_pos
            chunk_len[i] = n
            is_last[i] = it.is_last
            generated[i] = it.generated
            max_gen[i] = it.max_gen
            temperature[i] = it.temperature
            top_k[i] = it.top_k
            top_p[i] = it.top_p
            seeds[i] = it.seed
            gram_states[i] = it.gram_state
        # lengths-scatter dedup, as in prefill_group (the packer sends one
        # chunk per DISTINCT slot, so this is normally the identity — kept
        # so a buggy caller cannot trigger nondeterministic scatters)
        len_slots = slots.copy()
        newest: Dict[int, int] = {}
        for i, it in enumerate(items):
            newest[it.slot] = i
        for i in range(len(items)):
            if newest.get(int(slots[i])) != i:
                len_slots[i] = self.batch
        W = spec_width or self.spec_widths[-1]
        if W not in self.spec_widths:
            raise ValueError(f"spec_width {W} is not a ladder rung "
                             f"{self.spec_widths}")
        if draft_cap is None:
            draft_cap = np.full((self.batch,), W - 1, np.int32)
        return self._mixed_fn(
            state, self.params, self.adapters, page_table,
            *self._gram_args(use_grammar), jnp.asarray(tokens),
            jnp.asarray(page_rows), jnp.asarray(slots),
            jnp.asarray(len_slots), jnp.asarray(start_pos),
            jnp.asarray(chunk_len), jnp.asarray(is_last),
            jnp.asarray(generated), jnp.asarray(max_gen),
            jnp.asarray(temperature), jnp.asarray(top_k),
            jnp.asarray(top_p), jnp.asarray(seeds),
            jnp.asarray(gram_states, jnp.int32),
            jnp.asarray(draft_cap, jnp.int32), steps, use_grammar,
            want_top, W)

    def decode(self, state: DecodeState, page_table: jax.Array,
               steps: int = 1, use_grammar: bool = False,
               want_top: bool = False, *, spec_width: Optional[int] = None,
               width: Optional[int] = None, draft_cap=None
               ) -> Tuple[DecodeState, Dict[str, Any]]:
        """Run ``steps`` fused decode steps over all slots; ``page_table``
        from `put_table`. Out arrays are stacked (steps, B); ``input_tokens``
        carries each step's input so a just-activated slot's first token (not
        host-synced at admission) is recoverable from the same sync.
        ``use_grammar`` (compiled separately) applies constrained-decoding
        masks for slots whose gram_state > 0; ``want_top`` (also a separate
        compile) appends TOP_LP top-logprob rows to the packed block.
        ``spec_width`` / ``width`` select a speculative-width and a
        batch-width ladder rung (each a separate compile, all warmed);
        ``draft_cap`` is the adaptive controller's per-slot draft budget
        (traced data — no compile impact). Defaults reproduce the static
        full-width dispatch exactly."""
        W = spec_width or self.spec_widths[-1]
        if W not in self.spec_widths:
            raise ValueError(f"spec_width {W} is not a ladder rung "
                             f"{self.spec_widths}")
        bw = width or self.batch
        if bw not in self.decode_widths:
            raise ValueError(f"width {bw} is not a ladder rung "
                             f"{self.decode_widths}")
        if draft_cap is None:
            draft_cap = np.full((self.batch,), W - 1, np.int32)
        return self._decode_fn(state, self.params, self.adapters, page_table,
                               *self._gram_args(use_grammar),
                               jnp.asarray(draft_cap, jnp.int32), steps,
                               use_grammar, want_top, W, bw)

    def decode_multi(self, state: DecodeState, page_table: jax.Array,
                     steps: Optional[int] = None, m: Optional[int] = None,
                     *, stops: tuple = (), has_stop=None
                     ) -> Tuple[DecodeState, Dict[str, Any]]:
        """Run ``steps``·``m`` plain decode steps as ONE dispatch with one
        deferred packed fetch (the decode-dispatch-tail killer; ledger
        program ``decode_multi``, bucket ``s<K>m<M>``). Only for
        steady-state slots: no grammar, no top-logprobs, spec width 1 —
        the scheduler's eligibility predicate (``_multi_plan``) enforces
        this; the engine enforces the compile key (``m`` must be a warmed
        ladder rung, ``steps`` the base K). ``stops``: union of the live
        slots' stop strings (builds the conservative on-device suspect
        table); ``has_stop``: (B,) bool marking which slots carry stop
        strings (only those can be paused by a suspect token). Out arrays
        are stacked (steps·m, B) — identical layout to `decode`, so the
        host replay path is shared."""
        if not self.multi_ms:
            raise ValueError("multi-step decode is off "
                             "(APP_DECODE_MULTISTEP=0)")
        m = m or self.multi_ms[-1]
        if m not in self.multi_ms:
            raise ValueError(f"multistep m {m} is not a ladder rung "
                             f"{self.multi_ms}")
        base = self.cfg.decode_steps_per_dispatch
        steps = steps or base
        if steps != base:
            raise ValueError(f"multi-step dispatches run the base K "
                             f"({base}), got steps={steps}")
        suspect = self._stop_suspect(tuple(stops))
        if has_stop is None:
            has_stop = np.zeros((self.batch,), np.bool_)
        return self._decode_multi_fn(state, self.params, self.adapters,
                                     page_table, suspect,
                                     jnp.asarray(has_stop, bool), steps, m)
