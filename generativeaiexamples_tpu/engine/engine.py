"""Jitted serving core: prefill → slot insert → batched decode step.

Replaces the continuous-batching executor inside the reference's NIM
container (TRT-LLM inflight batching; ref docker-compose-nim-ms.yaml:2-28).
TPU-first design constraints (SURVEY §7 "hard parts" #1-3):

  * **Static shapes everywhere.** The decode batch is a fixed-capacity slot
    array; requests are *inserted into* and *retired from* slots, the compiled
    program never changes shape. Prompts are right-padded to a small set of
    power-of-two buckets so prefill compiles once per bucket.
  * **Prefill/decode disaggregation.** Prefill runs as its own jitted program
    per request (batch=1, bucketed length), producing the slot's KV block and
    first token; `insert` splices both into the decode state with
    `dynamic_update_slice` (no host round-trip of KV).
  * **Per-slot sampling.** temperature/top-k/top-p ride the decode state as
    traced (B,) vectors (`sample_logits_dynamic`), so one compiled decode step
    serves heterogeneous requests.
  * **Dispatch-ahead streaming.** `decode_step` returns small (B,) arrays;
    the host only syncs on those, never on the KV cache.

All functions are pure; `EngineCore` owns the jitted callables and the donate
annotations (cache buffers are donated through insert/decode to avoid copies).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.core.config import EngineConfig
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.ops.sampling import sample_logits_dynamic


@jax.tree_util.register_pytree_node_class
@dataclass
class DecodeState:
    """Fixed-capacity slot batch for continuous decoding."""

    cache: llama.KVCache      # (L, B, T, n_kv, hd); lengths (B,)
    tokens: jnp.ndarray       # (B,) last token per slot
    active: jnp.ndarray       # (B,) bool — slot currently generating
    generated: jnp.ndarray    # (B,) tokens generated so far per slot
    max_gen: jnp.ndarray      # (B,) per-request generation budget
    temperature: jnp.ndarray  # (B,) f32
    top_k: jnp.ndarray        # (B,) i32
    top_p: jnp.ndarray        # (B,) f32
    rng: jnp.ndarray          # PRNG key

    def tree_flatten(self):
        return ((self.cache, self.tokens, self.active, self.generated,
                 self.max_gen, self.temperature, self.top_k, self.top_p,
                 self.rng), None)

    @classmethod
    def tree_unflatten(cls, _, c):
        return cls(*c)


def _round_up_bucket(n: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest prefill bucket {buckets[-1]}")


class EngineCore:
    """Owns params + jitted programs. Thread-safety: call from one driver
    thread (the scheduler); jax dispatch itself is async."""

    def __init__(self, model_cfg: llama.LlamaConfig, engine_cfg: EngineConfig,
                 params: llama.Params, eos_id: int,
                 adapters: Optional[llama.Params] = None) -> None:
        attn = engine_cfg.attention
        if attn == "auto":
            # pallas kernels assume unsharded head layouts; the engine runs
            # the model unsharded today, so TPU ⇒ pallas is safe. When TP
            # sharding lands here, this gate must also check the mesh.
            attn = "pallas" if jax.default_backend() == "tpu" else "xla"
        if attn != model_cfg.attn_impl:
            model_cfg = dataclasses.replace(model_cfg, attn_impl=attn)
        self.model_cfg = model_cfg
        self.cfg = engine_cfg
        self.params = params
        self.adapters = adapters
        self.eos_id = eos_id
        self.batch = engine_cfg.max_batch_size
        self.max_seq = engine_cfg.max_seq_len
        # prefill buckets: powers of two from 64 (or prefill_chunk) to max
        buckets = []
        b = min(64, engine_cfg.prefill_chunk)
        while b < min(engine_cfg.prefill_chunk * 4, self.max_seq):
            buckets.append(b)
            b *= 2
        buckets.append(min(engine_cfg.prefill_chunk * 4, self.max_seq))
        self.buckets = tuple(sorted(set(buckets)))

        self._prefill = jax.jit(self._prefill_impl)
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._decode = jax.jit(self._decode_impl, donate_argnums=(0,))

    # ------------------------------------------------------------------ state

    def init_state(self, rng: Optional[jax.Array] = None) -> DecodeState:
        B = self.batch
        cache = llama.KVCache.create(self.model_cfg, B, self.max_seq)
        return DecodeState(
            cache=cache,
            tokens=jnp.zeros((B,), jnp.int32),
            active=jnp.zeros((B,), bool),
            generated=jnp.zeros((B,), jnp.int32),
            max_gen=jnp.zeros((B,), jnp.int32),
            temperature=jnp.ones((B,), jnp.float32),
            top_k=jnp.zeros((B,), jnp.int32),
            top_p=jnp.ones((B,), jnp.float32),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
        )

    # ---------------------------------------------------------------- prefill

    def _prefill_impl(self, params, tokens, true_len, rng, temperature, top_k, top_p):
        """tokens (1, S_bucket) right-padded; true_len (1,). Returns first
        sampled token (1,) and the prefill KV block (L, 1, S, kv, hd)."""
        cache = llama.KVCache.create(self.model_cfg, 1, tokens.shape[1])
        logits, cache = llama.prefill(
            params, self.model_cfg, tokens, cache,
            start_pos=jnp.zeros((1,), jnp.int32), seq_lens=true_len,
            adapters=self.adapters, last_only=True)
        first_tok = sample_logits_dynamic(rng, logits[:, 0], temperature,
                                          top_k, top_p)
        return first_tok, cache.k, cache.v

    def prefill(self, prompt_ids, temperature: float, top_k: int, top_p: float,
                rng: jax.Array):
        """Host wrapper: bucket/pad the prompt, run the jitted prefill."""
        n = len(prompt_ids)
        S = _round_up_bucket(n, self.buckets)
        padded = jnp.zeros((1, S), jnp.int32).at[0, :n].set(
            jnp.asarray(prompt_ids, jnp.int32))
        return self._prefill(
            self.params, padded, jnp.array([n], jnp.int32), rng,
            jnp.array([temperature], jnp.float32),
            jnp.array([top_k], jnp.int32), jnp.array([top_p], jnp.float32))

    # ----------------------------------------------------------------- insert

    def _insert_impl(self, state: DecodeState, k_pre, v_pre, first_tok,
                     slot, length, max_gen, temperature, top_k, top_p) -> DecodeState:
        """Splice a prefilled request into decode slot ``slot``."""
        L = self.model_cfg.n_layers
        S = k_pre.shape[2]
        zeros5 = (jnp.int32(0),) * 5
        # write (L, 1, S, kv, hd) into (L, B, T, kv, hd) at batch=slot
        idx = (jnp.int32(0), slot, jnp.int32(0), jnp.int32(0), jnp.int32(0))
        k = jax.lax.dynamic_update_slice(state.cache.k, k_pre, idx)
        v = jax.lax.dynamic_update_slice(state.cache.v, v_pre, idx)
        upd = lambda arr, val: arr.at[slot].set(val)
        return DecodeState(
            cache=llama.KVCache(k=k, v=v, lengths=upd(state.cache.lengths, length)),
            tokens=upd(state.tokens, first_tok),
            active=upd(state.active, True),
            generated=upd(state.generated, 1),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
            rng=state.rng,
        )

    def insert(self, state: DecodeState, prefill_result, slot: int, length: int,
               max_gen: int, temperature: float, top_k: int, top_p: float) -> DecodeState:
        first_tok, k_pre, v_pre = prefill_result
        return self._insert(
            state, k_pre, v_pre, first_tok[0], jnp.int32(slot),
            jnp.int32(length), jnp.int32(max_gen), jnp.float32(temperature),
            jnp.int32(top_k), jnp.float32(top_p))

    # ----------------------------------------------------------------- decode

    def _decode_impl(self, state: DecodeState, params) -> Tuple[DecodeState, Dict[str, Any]]:
        logits, cache = llama.decode_step(
            params, self.model_cfg, state.tokens, state.cache,
            adapters=self.adapters)
        rng, sub = jax.random.split(state.rng)
        sampled = sample_logits_dynamic(sub, logits, state.temperature,
                                        state.top_k, state.top_p)
        generated = state.generated + state.active.astype(jnp.int32)
        hit_eos = sampled == self.eos_id
        out_of_budget = generated >= state.max_gen
        out_of_cache = cache.lengths >= self.max_seq - 1
        done = state.active & (hit_eos | out_of_budget | out_of_cache)
        active = state.active & ~done
        # inactive slots keep their old lengths so cache positions stay put
        lengths = jnp.where(state.active, cache.lengths, state.cache.lengths)
        new_state = DecodeState(
            cache=llama.KVCache(k=cache.k, v=cache.v, lengths=lengths),
            tokens=jnp.where(state.active, sampled, state.tokens),
            active=active,
            generated=generated,
            max_gen=state.max_gen,
            temperature=state.temperature,
            top_k=state.top_k,
            top_p=state.top_p,
            rng=rng,
        )
        out = {"sampled": sampled, "emitted": state.active, "done": done,
               "hit_eos": hit_eos}
        return new_state, out

    def decode(self, state: DecodeState) -> Tuple[DecodeState, Dict[str, Any]]:
        return self._decode(state, self.params)
