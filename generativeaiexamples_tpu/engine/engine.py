"""Jitted serving core: paged chunked prefill → slot activate → batched decode.

Replaces the continuous-batching executor inside the reference's NIM
container (TRT-LLM inflight batching with paged attention; ref
docker-compose-nim-ms.yaml:2-28, docs/architecture.md:49-61).
TPU-first design constraints (SURVEY §7 "hard parts" #1-3):

  * **Static shapes everywhere.** The decode batch is a fixed-capacity slot
    array; requests are *inserted into* and *retired from* slots, the compiled
    program never changes shape. Prompts are processed in page-aligned chunks
    (``prefill_chunk`` mid-chunks, a small power-of-two bucket ladder for the
    final chunk), so prefill compiles once per bucket.
  * **Paged KV.** KV lives in a single block-table paged pool
    (engine/kv_cache.py): prefill chunks scatter whole pages, decode appends
    one row per slot, HBM is bounded by live tokens. Chunked prefill writes
    straight into the slot's pages — there is no separate prefill cache and
    no KV splice on insert.
  * **Chunked-prefill interleave.** Each chunk is its own dispatch, so the
    scheduler can interleave decode steps between the chunks of a long
    admission — active slots never stall for a whole prompt (the TTFT vs
    tok/s tradeoff of SURVEY hard-part #2). Long prompts are chunked, never
    truncated.
  * **Tensor-parallel over a device mesh.** Given a mesh, params are placed
    by `parallel.sharding.INFERENCE_RULES` (heads/kv-heads/mlp split over
    "tensor"), the KV pool is sharded on its kv-head axis, and XLA inserts
    the activation collectives — the same TP-by-config the reference gets
    from ``INFERENCE_GPU_COUNT`` (docker-compose-nim-ms.yaml:18-20).
  * **Per-slot sampling.** temperature/top-k/top-p ride the decode state as
    traced (B,) vectors (`sample_logits_dynamic`), so one compiled decode step
    serves heterogeneous requests.
  * **Dispatch-ahead streaming.** `decode` returns small (B,) arrays; the
    host only syncs on those, never on the KV pool.

All functions are pure; `EngineCore` owns the jitted callables and the donate
annotations (the paged pool is donated through every chunk/decode step).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from generativeaiexamples_tpu.core.config import EngineConfig
from generativeaiexamples_tpu.engine import kv_cache
from generativeaiexamples_tpu.engine.kv_cache import PagedKVCache
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.ops.sampling import sample_logits_dynamic


# order of the (5, steps, B) int32 "packed" decode output block
_PACKED_FIELDS = ("sampled", "emitted", "done", "hit_eos", "input_tokens")


def unpack_decode_out(packed) -> Dict[str, Any]:
    """Split a host-fetched ``out["packed"]`` block back into named arrays."""
    return {k: packed[i] for i, k in enumerate(_PACKED_FIELDS)}


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DecodeState:
    """Fixed-capacity slot batch for continuous decoding."""

    cache: PagedKVCache       # paged pool; lengths (B,)
    tokens: jnp.ndarray       # (B,) last token per slot
    active: jnp.ndarray       # (B,) bool — slot currently generating
    generated: jnp.ndarray    # (B,) tokens generated so far per slot
    max_gen: jnp.ndarray      # (B,) per-request generation budget
    temperature: jnp.ndarray  # (B,) f32
    top_k: jnp.ndarray        # (B,) i32
    top_p: jnp.ndarray        # (B,) f32
    rng: jnp.ndarray          # PRNG key

    def tree_flatten(self):
        return ((self.cache, self.tokens, self.active, self.generated,
                 self.max_gen, self.temperature, self.top_k, self.top_p,
                 self.rng), None)

    @classmethod
    def tree_unflatten(cls, _, c):
        return cls(*c)


class EngineCore:
    """Owns params + jitted programs. Thread-safety: call from one driver
    thread (the scheduler); jax dispatch itself is async.

    With ``engine_cfg.quant == "int8"`` the constructor CONSUMES the params
    tree (buffer donation frees each bf16 leaf as its int8 copy lands — the
    only way a 3B+ model quantizes within one chip's HBM); callers must not
    reuse the tree they passed in."""

    def __init__(self, model_cfg: llama.LlamaConfig, engine_cfg: EngineConfig,
                 params: llama.Params, eos_id: int,
                 adapters: Optional[llama.Params] = None,
                 mesh: Optional[Mesh] = None) -> None:
        self.mesh = mesh
        tp = int(mesh.shape.get("tensor", 1)) if mesh is not None else 1
        attn = engine_cfg.attention
        if attn == "auto":
            # pallas on TPU regardless of TP degree: under tensor
            # parallelism the kernels run per-shard through shard_map
            # wrappers (engine/kv_cache.py), attending local head slices
            attn = "pallas" if jax.default_backend() == "tpu" else "xla"
        if attn != model_cfg.attn_impl:
            model_cfg = dataclasses.replace(model_cfg, attn_impl=attn)
        if tp > 1:
            if model_cfg.n_kv_heads % tp or model_cfg.n_heads % tp:
                raise ValueError(
                    f"tensor parallel degree {tp} must divide heads "
                    f"({model_cfg.n_heads}) and kv heads "
                    f"({model_cfg.n_kv_heads}) — set engine.mesh_shape "
                    f"(APP_ENGINE_MESH_SHAPE), e.g. 'DxT' with a dividing T")
        self.model_cfg = model_cfg
        self.cfg = engine_cfg
        self.eos_id = eos_id
        self.batch = engine_cfg.max_batch_size
        self.max_seq = engine_cfg.max_seq_len
        self.page_size = engine_cfg.page_size
        self.chunk = engine_cfg.prefill_chunk
        if self.chunk % self.page_size:
            raise ValueError(
                f"prefill_chunk ({self.chunk}) must be a multiple of "
                f"page_size ({self.page_size})")
        if self.max_seq % self.chunk:
            # guarantees every chunk (mid or final bucket) stays inside the
            # block-table row — a clamped page scatter would silently corrupt
            # earlier pages
            raise ValueError(
                f"max_seq_len ({self.max_seq}) must be a multiple of "
                f"prefill_chunk ({self.chunk})")
        k = engine_cfg.decode_steps_per_dispatch
        if k < 1 or k & (k - 1):
            # the scheduler restricts dynamic step counts to powers of two
            # (each distinct value is a separate XLA compile); reject rather
            # than silently round the operator's setting down
            raise ValueError(
                f"decode_steps_per_dispatch ({k}) must be a power of two")
        self.max_pages_per_slot = -(-self.max_seq // self.page_size)
        # total physical pages: 0 = full slot capacity (+ null page 0)
        self.num_pages = (engine_cfg.num_pages or
                          self.batch * self.max_pages_per_slot + 1)
        # final-chunk buckets: page-aligned powers of two up to the chunk size
        buckets = []
        b = self.page_size
        while b < self.chunk:
            buckets.append(b)
            b *= 2
        buckets.append(self.chunk)
        self.buckets = tuple(buckets)

        if mesh is not None:
            from generativeaiexamples_tpu.parallel import sharding as psh
            params = psh.shard_params(
                params, llama.logical_axes(model_cfg),
                psh.INFERENCE_RULES, mesh)
            if adapters is not None:
                adapters = jax.device_put(
                    adapters, NamedSharding(mesh, P()))
            # KV pool (flat (L*P, page, KV*HD)): shard the fused kv-head/
            # head-dim axis over "tensor" — kv_heads % tp == 0, so the split
            # lands on whole-head boundaries; page rows stay local.
            self._kv_sharding = NamedSharding(
                mesh, P(None, None, "tensor"))
            self._replicated = NamedSharding(mesh, P())
        else:
            self._kv_sharding = None
            self._replicated = None
        if engine_cfg.quant == "int8":
            # after shard_params: elementwise quantize + keepdims amax
            # propagate each weight's NamedSharding onto q and s, so TP
            # layouts survive quantization. donate=True frees each bf16
            # source buffer as its int8 copy lands (ops/quant.py) — the
            # caller's params tree is consumed, which is exactly the load
            # path's contract (EngineCore owns the weights from here on).
            from generativeaiexamples_tpu.ops import quant as quant_ops
            params = quant_ops.quantize_params(params, donate=True)
            import logging
            logging.getLogger(__name__).info(
                "serving with int8 weight-only quantization")
        elif engine_cfg.quant not in ("none", ""):
            raise ValueError(f"unknown quant mode {engine_cfg.quant!r}; "
                             "expected 'none' or 'int8'")
        self.params = params
        self.adapters = adapters

        # Donating the state through every dispatch is the memory-optimal
        # default, but a remote-attached PJRT client (the tunneled dev chip)
        # BLOCKS ~RTT per donated dispatch (measured 248 vs 21 ms/call) —
        # there the transient on-device pool copy is ~50x cheaper.
        donate = engine_cfg.donate_buffers
        if donate == "auto":
            import os
            donate = "off" if os.environ.get("PALLAS_AXON_POOL_IPS") else "on"
        dn = (0,) if donate == "on" else ()
        # callers that keep handles into the state (the scheduler's batched
        # first-token fetch) must copy them before the next dispatch
        # deletes the donated buffers
        self.donates_state = bool(dn)
        self._chunk_fn = jax.jit(self._chunk_impl, donate_argnums=dn)
        self._long_fn = jax.jit(self._prefill_long_impl, donate_argnums=dn)
        self._long_last_fn = jax.jit(self._prefill_long_last_impl,
                                     donate_argnums=dn)
        self._chunk_last_fn = jax.jit(self._chunk_last_impl,
                                      donate_argnums=dn)
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=dn,
                                  static_argnums=(4,))
        self._activate_fn = jax.jit(self._activate_impl, donate_argnums=dn)
        self._release_fn = jax.jit(self._release_impl, donate_argnums=dn)
        self._sample_fn = jax.jit(self._sample_impl)

    # ------------------------------------------------------------------ state

    def init_state(self, rng: Optional[jax.Array] = None) -> DecodeState:
        B = self.batch
        # The KV pool is the big buffer: under a mesh, allocate it directly
        # with its target sharding (never materialized on one chip).
        cache = PagedKVCache.create(self.model_cfg, B, self.num_pages,
                                    self.page_size,
                                    kv_sharding=self._kv_sharding,
                                    aux_sharding=self._replicated)
        state = DecodeState(
            cache=cache,
            tokens=jnp.zeros((B,), jnp.int32),
            active=jnp.zeros((B,), bool),
            generated=jnp.zeros((B,), jnp.int32),
            max_gen=jnp.zeros((B,), jnp.int32),
            temperature=jnp.ones((B,), jnp.float32),
            top_k=jnp.zeros((B,), jnp.int32),
            top_p=jnp.ones((B,), jnp.float32),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
        )
        if self.mesh is not None:
            rest = jax.device_put(
                (state.tokens, state.active, state.generated, state.max_gen,
                 state.temperature, state.top_k, state.top_p, state.rng),
                self._replicated)
            state = DecodeState(cache, *rest)
        return state

    def new_allocator(self) -> kv_cache.PageAllocator:
        return kv_cache.PageAllocator(self.num_pages)

    def pages_for(self, n_tokens: int) -> int:
        """Pages required so positions 0..n_tokens (inclusive next-write) fit."""
        return n_tokens // self.page_size + 1

    def put_table(self, table: np.ndarray) -> jax.Array:
        """Host block table → device (replicated under a mesh)."""
        arr = jnp.asarray(table, jnp.int32)
        if self.mesh is not None:
            arr = jax.device_put(arr, self._replicated)
        return arr

    # ---------------------------------------------------------------- prefill

    def _chunk_impl(self, state: DecodeState, params, adapters, tokens,
                    page_row, slot, start_pos, chunk_len
                    ) -> Tuple[DecodeState, jnp.ndarray]:
        # params/adapters ride as arguments, never closure constants — a
        # captured 6 GB pytree would be baked into the lowered program
        logits, cache = kv_cache.prefill_chunk(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            start_pos, chunk_len, self.num_pages, adapters=adapters,
            mesh=self.mesh)
        return dataclasses.replace(state, cache=cache), logits[0]

    def prefill_chunk(self, state: DecodeState, chunk_ids, page_row, slot: int,
                      start_pos: int) -> Tuple[DecodeState, jax.Array]:
        """Host wrapper: pad the chunk to a bucket, run the jitted chunk.

        chunk_ids: the token ids of this chunk (<= prefill_chunk of them);
        page_row: (max_pages_per_slot,) int32 block-table row for the slot.
        Returns (state, last-position logits (V,)) — callers sample from the
        logits only on the final chunk.
        """
        n = len(chunk_ids)
        S = next(b for b in self.buckets if n <= b)
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = chunk_ids
        return self._chunk_fn(
            state, self.params, self.adapters, jnp.asarray(padded),
            jnp.asarray(page_row, jnp.int32), jnp.int32(slot),
            jnp.int32(start_pos), jnp.int32(n))

    # ---------------------------------------------- long-context prefill

    @property
    def supports_long_prefill(self) -> bool:
        """Sequence-parallel whole-prompt prefill needs a mesh with a
        "seq" axis (the LONGCTX configuration)."""
        return (self.mesh is not None and "seq" in self.mesh.axis_names
                and int(self.mesh.shape["seq"]) > 1
                and self.model_cfg.sliding_window == 0)

    def prefill_long(self, state: DecodeState, prompt_ids, page_row,
                     slot: int) -> Tuple[DecodeState, jax.Array]:
        """Whole-prompt ring-attention prefill into the slot's pages —
        §5.7 long-context serving: one pass over the full prompt with the
        sequence sharded over mesh["seq"] instead of prefill_chunk-sized
        slices (kv_cache.prefill_seq_parallel). The caller allocates pages
        exactly as for chunked prefill; returns (state, last-position
        logits (V,)) ready for `sample` + `activate`."""
        if not self.supports_long_prefill:
            raise ValueError("prefill_long needs a mesh with a 'seq' axis "
                             "and a full-causal model")
        padded, n = self._pad_long(prompt_ids)
        toks = jax.device_put(
            jnp.asarray(padded),
            NamedSharding(self.mesh, P("data", "seq")))
        return self._long_fn(state, self.params, self.adapters, toks,
                             jnp.asarray(page_row, jnp.int32),
                             jnp.int32(slot), jnp.int32(n))

    def _prefill_long_impl(self, state: DecodeState, params, adapters,
                           tokens, page_row, slot, n_tokens):
        logits, cache = kv_cache.prefill_seq_parallel(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            n_tokens, self.num_pages, self.mesh, adapters=adapters)
        return dataclasses.replace(state, cache=cache), logits[0]

    def _pad_long(self, prompt_ids) -> Tuple[np.ndarray, int]:
        n = len(prompt_ids)
        seq_n = int(self.mesh.shape["seq"])
        import math as _math

        # power-of-two bucket ladder over the alignment unit: without it
        # every distinct rounded prompt length is a fresh XLA compile on
        # the serving path (the chunked path buckets for the same reason);
        # cap: largest align-multiple that fits the block-table row (the
        # ring needs S % seq == 0 AND the page write S % page == 0)
        align = _math.lcm(self.page_size, seq_n)
        cap = (self.max_pages_per_slot * self.page_size // align) * align
        S = align
        while S < n:
            S *= 2
        S = min(S, cap)
        if S < n:
            raise ValueError(f"prompt of {n} tokens exceeds the long-"
                             f"prefill capacity ({cap} aligned tokens)")
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = prompt_ids
        return padded, n

    def prefill_long_last(self, state: DecodeState, prompt_ids, page_row,
                          slot: int, generated: int, max_gen: int,
                          temperature: float, top_k: int, top_p: float
                          ) -> Tuple[DecodeState, jax.Array]:
        """Whole-prompt sequence-parallel prefill FUSED with first-token
        sampling and slot activation (the scheduler's long-prompt
        admission path — same no-host-round-trip contract as
        `prefill_chunk_last`)."""
        if not self.supports_long_prefill:
            raise ValueError("prefill_long needs a mesh with a 'seq' axis "
                             "and a full-causal model")
        padded, n = self._pad_long(prompt_ids)
        toks = jax.device_put(
            jnp.asarray(padded), NamedSharding(self.mesh, P("data", "seq")))
        return self._long_last_fn(
            state, self.params, self.adapters, toks,
            jnp.asarray(page_row, jnp.int32), jnp.int32(slot),
            jnp.int32(n), jnp.int32(generated), jnp.int32(max_gen),
            jnp.float32(temperature), jnp.int32(top_k), jnp.float32(top_p))

    def _prefill_long_last_impl(self, state: DecodeState, params, adapters,
                                tokens, page_row, slot, n_tokens, generated,
                                max_gen, temperature, top_k, top_p):
        logits, cache = kv_cache.prefill_seq_parallel(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            n_tokens, self.num_pages, self.mesh, adapters=adapters)
        return self._activate_sampled(state, cache, logits, slot, generated,
                                      max_gen, temperature, top_k, top_p)

    def _sample_impl(self, logits, rng, temperature, top_k, top_p):
        return sample_logits_dynamic(rng, logits[None], temperature[None],
                                     top_k[None], top_p[None])[0]

    def sample(self, logits: jax.Array, rng: jax.Array, temperature: float,
               top_k: int, top_p: float) -> int:
        """Sample one token from final-chunk logits (host sync point: TTFT)."""
        tok = self._sample_fn(logits, rng, jnp.float32(temperature),
                              jnp.int32(top_k), jnp.float32(top_p))
        return int(jax.device_get(tok))

    def _activate_sampled(self, state: DecodeState, cache, logits, slot,
                          generated, max_gen, temperature, top_k, top_p
                          ) -> Tuple[DecodeState, jnp.ndarray]:
        """Shared tail of the fused prefill programs: sample the first token
        from last-position logits and activate the slot, all on-device.
        An immediate eos or an exhausted budget leaves the slot inactive
        (the host resolves the outcome from the returned token at the next
        decode sync)."""
        rng, sub = jax.random.split(state.rng)
        tok = sample_logits_dynamic(sub, logits, temperature[None],
                                    top_k[None], top_p[None])[0]
        alive = (tok != self.eos_id) & (generated < max_gen)
        upd = lambda arr, val: arr.at[slot].set(val)
        new_state = dataclasses.replace(
            state,
            cache=cache,
            tokens=upd(state.tokens, tok),
            active=upd(state.active, alive),
            generated=upd(state.generated, generated),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
            rng=rng,
        )
        return new_state, tok

    def _chunk_last_impl(self, state: DecodeState, params, adapters, tokens,
                         page_row, slot, start_pos, chunk_len, generated,
                         max_gen, temperature, top_k, top_p
                         ) -> Tuple[DecodeState, jnp.ndarray]:
        """Final chunk fused with first-token sampling and slot activation —
        admission never blocks on a host round-trip; the first token's value
        reaches the host batched into the next decode sync."""
        logits, cache = kv_cache.prefill_chunk(
            params, self.model_cfg, tokens, state.cache, page_row, slot,
            start_pos, chunk_len, self.num_pages, adapters=adapters,
            mesh=self.mesh)
        return self._activate_sampled(state, cache, logits, slot, generated,
                                      max_gen, temperature, top_k, top_p)

    def prefill_chunk_last(self, state: DecodeState, chunk_ids, page_row,
                           slot: int, start_pos: int, generated: int,
                           max_gen: int, temperature: float, top_k: int,
                           top_p: float) -> Tuple[DecodeState, jax.Array]:
        """Final-chunk host wrapper: returns (state, first-token device
        scalar). ``generated`` counts tokens produced including this one."""
        n = len(chunk_ids)
        S = next(b for b in self.buckets if n <= b)
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = chunk_ids
        return self._chunk_last_fn(
            state, self.params, self.adapters, jnp.asarray(padded),
            jnp.asarray(page_row, jnp.int32), jnp.int32(slot),
            jnp.int32(start_pos), jnp.int32(n), jnp.int32(generated),
            jnp.int32(max_gen), jnp.float32(temperature), jnp.int32(top_k),
            jnp.float32(top_p))

    # --------------------------------------------------------- slot lifecycle

    def _activate_impl(self, state: DecodeState, slot, token, generated,
                       max_gen, temperature, top_k, top_p) -> DecodeState:
        upd = lambda arr, val: arr.at[slot].set(val)
        return dataclasses.replace(
            state,
            tokens=upd(state.tokens, token),
            active=upd(state.active, True),
            generated=upd(state.generated, generated),
            max_gen=upd(state.max_gen, max_gen),
            temperature=upd(state.temperature, temperature),
            top_k=upd(state.top_k, top_k),
            top_p=upd(state.top_p, top_p),
        )

    def activate(self, state: DecodeState, slot: int, token: int,
                 generated: int, max_gen: int, temperature: float, top_k: int,
                 top_p: float) -> DecodeState:
        """Start decoding a prefilled slot (its lengths were set by the last
        chunk; ``generated`` counts tokens already produced, >=1)."""
        return self._activate_fn(
            state, jnp.int32(slot), jnp.int32(token), jnp.int32(generated),
            jnp.int32(max_gen), jnp.float32(temperature), jnp.int32(top_k),
            jnp.float32(top_p))

    def _release_impl(self, state: DecodeState, slot) -> DecodeState:
        return dataclasses.replace(state,
                                   active=state.active.at[slot].set(False))

    def release(self, state: DecodeState, slot: int) -> DecodeState:
        """Deactivate a slot (preemption); its pages may be reused at once —
        subsequent decode writes for the slot go to the null page."""
        return self._release_fn(state, jnp.int32(slot))

    # ----------------------------------------------------------------- decode

    def _decode_impl(self, state: DecodeState, params, adapters, page_table,
                     steps: int) -> Tuple[DecodeState, Dict[str, Any]]:
        def step(state, _):
            logits, cache = kv_cache.decode_step(
                params, self.model_cfg, state.tokens, state.cache,
                page_table, state.active, self.num_pages, adapters=adapters,
                mesh=self.mesh)
            rng, sub = jax.random.split(state.rng)
            # inactive slots' stale temperatures must not defeat the
            # all-greedy fast path inside the sampler
            live_temp = jnp.where(state.active, state.temperature, 0.0)
            sampled = sample_logits_dynamic(sub, logits, live_temp,
                                            state.top_k, state.top_p)
            generated = state.generated + state.active.astype(jnp.int32)
            hit_eos = sampled == self.eos_id
            out_of_budget = generated >= state.max_gen
            out_of_cache = cache.lengths >= self.max_seq - 1
            done = state.active & (hit_eos | out_of_budget | out_of_cache)
            active = state.active & ~done
            # inactive slots keep their old lengths so cache positions stay
            lengths = jnp.where(state.active, cache.lengths,
                                state.cache.lengths)
            new_state = dataclasses.replace(
                state,
                cache=PagedKVCache(k=cache.k, v=cache.v, lengths=lengths),
                tokens=jnp.where(state.active, sampled, state.tokens),
                active=active,
                generated=generated,
                rng=rng,
            )
            out = {"sampled": sampled, "emitted": state.active, "done": done,
                   "hit_eos": hit_eos, "input_tokens": state.tokens}
            return new_state, out

        # K fused steps per dispatch: the host syncs once per K tokens/slot,
        # which is what makes decode dispatch-latency-proof (SURVEY hard-part
        # #3; essential over the tunneled single-chip dev setup, still a win
        # on local PCIe/ICI-attached hosts). outs arrays are (K, B).
        state, outs = jax.lax.scan(step, state, None, length=steps)
        # one contiguous int32 block so the host fetches the whole dispatch
        # result in a single transfer (a pytree device_get pays one round
        # trip PER LEAF — 5x the latency on a remote-attached chip)
        outs["packed"] = jnp.stack(
            [outs[k].astype(jnp.int32) for k in _PACKED_FIELDS])
        return state, outs

    def decode(self, state: DecodeState, page_table: jax.Array,
               steps: int = 1) -> Tuple[DecodeState, Dict[str, Any]]:
        """Run ``steps`` fused decode steps over all slots; ``page_table``
        from `put_table`. Out arrays are stacked (steps, B); ``input_tokens``
        carries each step's input so a just-activated slot's first token (not
        host-synced at admission) is recoverable from the same sync."""
        return self._decode_fn(state, self.params, self.adapters, page_table,
                               steps)
