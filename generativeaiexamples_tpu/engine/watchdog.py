"""Engine watchdog: detect a hung dispatch or a stalled scheduler tick and
take the worker out of rotation BEFORE clients time out into it.

The scheduler's driver loop already fails loudly on exceptions
(engine/scheduler._loop), but two failure shapes produce no exception at
all: a device dispatch that never completes (wedged runtime, dead tunnel
to a remote-attached chip — the fetch future just never resolves) and a
driver thread stuck inside one tick (a pathological compile, a blocked
host call). Both leave ``/health`` green while every stream hangs — the
exact "dead component with /health green" failure mode the rest of the
stack is built to avoid.

The watchdog is a daemon thread polling two heartbeats:

  * **tick heartbeat** — the driver stamps ``scheduler.last_tick_mono``
    every loop iteration; a gap beyond ``APP_WATCHDOG_TICK_STALL_S``
    (default 30 s; the idle loop ticks every 50 ms) while the scheduler
    is running trips ``tick_stall``.
  * **oldest in-flight dispatch** — every decode dispatch rides its
    issue timestamp; the completion bound is MODEL-INFORMED where the
    chip is known: a K-step decode dispatch is weight-read-bound, so its
    expected device time is ``K × param_bytes / peak_hbm_bw``
    (core/perfmodel.py — the same arithmetic the devtime gauges use),
    and the trip bound is ``APP_WATCHDOG_DISPATCH_FACTOR`` (default 200)
    times that, floored at 2 s. On unknown chips (CPU, simulators) the
    absolute ``APP_WATCHDOG_DISPATCH_S`` bound (default 60 s) applies —
    an unknown denominator must never disable the watchdog.

A trip: counts ``engine_watchdog_trips_total{kind}``, records a flight-
recorder event, raises a ``watchdog_<kind>`` hazard through the SLO
pressure plane (observability/slo.py — routers see warn pressure on the
next probe), logs at error, and flips :attr:`healthy` False — the engine's
``/health`` answers 503 while unhealthy, so the routing frontend
(server/failover.py) circuit-breaks the worker away from live traffic.
Recovery is condition-based: when ticks resume and the stuck dispatch
clears, ``healthy`` returns True (each NEW trip is edge-counted).

**Graceful drain** rides the same switch: ``drain()`` (POST /debug/drain)
answers 503 on /health without touching serving — in-flight streams
finish, the router routes new work away, and ``undrain()`` (or
``?off=1``) re-admits the worker. That is the operator's zero-drop
worker-rotation primitive.

Gate: ``APP_WATCHDOG`` = on (default) | off. The thread costs one
monotonic read and two attribute peeks per poll (0.5 s) — nothing rides
the scheduler's hot path.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from generativeaiexamples_tpu.core.config import env_float as _env_float
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability.flight import FLIGHT

logger = logging.getLogger(__name__)


def watchdog_enabled() -> bool:
    return (os.environ.get("APP_WATCHDOG", "").strip().lower()
            or "on") != "off"


class EngineWatchdog:
    """Health arbiter for one scheduler (see module doc)."""

    def __init__(self, scheduler: Any,
                 tick_stall_s: Optional[float] = None,
                 dispatch_bound_s: Optional[float] = None,
                 dispatch_factor: Optional[float] = None,
                 poll_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.scheduler = scheduler
        self.tick_stall_s = (tick_stall_s if tick_stall_s is not None
                             else _env_float("APP_WATCHDOG_TICK_STALL_S",
                                             30.0))
        self.dispatch_bound_s = (
            dispatch_bound_s if dispatch_bound_s is not None
            else _env_float("APP_WATCHDOG_DISPATCH_S", 60.0))
        self.dispatch_factor = (
            dispatch_factor if dispatch_factor is not None
            else _env_float("APP_WATCHDOG_DISPATCH_FACTOR", 200.0))
        self.poll_s = poll_s
        self._clock = clock
        self.healthy = True
        self.draining = False
        # live-migration on trip (APP_WATCHDOG_EVACUATE, default on): a
        # trip queues a NON-blocking full evacuation — if/when the driver
        # can still tick, every live slot's mid-decode snapshot parks for
        # the router to resume on peers (scheduler.request_evacuation)
        # instead of stranding in-flight KV on a sick worker. A wedged
        # driver simply never serves the request, and the router's
        # re-prefill fallback owns recovery (the hard-death path).
        self.evacuate_on_trip = (os.environ.get(
            "APP_WATCHDOG_EVACUATE", "").strip().lower() or "on") != "off"
        self._tripped: Dict[str, bool] = {}    # kind -> currently tripped
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # the family exists (0-valued) from startup so a scrape before the
        # first trip still sees the catalog
        REGISTRY.counter("engine_watchdog_trips_total",
                         labels={"kind": "tick_stall"})
        REGISTRY.counter("engine_watchdog_trips_total",
                         labels={"kind": "hung_dispatch"})

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="engine-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)

    def drain(self) -> None:
        """Graceful drain: /health goes 503 (router routes away) while
        serving continues — in-flight streams finish normally."""
        if not self.draining:
            logger.warning("engine drain requested: /health now answers "
                           "503; in-flight streams keep serving")
            REGISTRY.gauge("engine_draining").set(1)
            FLIGHT.event("drain", action="start")
        self.draining = True

    def undrain(self) -> None:
        if self.draining:
            logger.info("engine drain lifted: /health serving again")
            REGISTRY.gauge("engine_draining").set(0)
            FLIGHT.event("drain", action="stop")
        self.draining = False

    # ------------------------------------------------------------- checking

    def _expected_dispatch_s(self, steps: int) -> Optional[float]:
        """Model-informed expected device seconds for a ``steps``-deep
        decode dispatch: decode is weight-read-bound, so steps full
        weight reads at peak HBM bandwidth (core/perfmodel.py) is the
        fastest it can possibly complete."""
        perf = getattr(self.scheduler.core, "perf_model", None)
        if perf is None or not getattr(perf, "peak_bw", None):
            return None
        try:
            return perf.weight_read_bytes(max(1, steps)) / perf.peak_bw
        except Exception as exc:
            logger.debug("watchdog perf bound unavailable: %s", exc)
            return None

    def dispatch_bound(self, steps: int) -> float:
        expected = self._expected_dispatch_s(steps)
        if expected is None:
            return self.dispatch_bound_s
        return max(2.0, self.dispatch_factor * expected)

    def _trip(self, kind: str, detail: Dict[str, Any]) -> None:
        if not self._tripped.get(kind):
            # edge-counted: one trip per continuous incident, not per poll
            self._tripped[kind] = True
            REGISTRY.counter("engine_watchdog_trips_total",
                             labels={"kind": kind}).inc()
            FLIGHT.event("watchdog_trip", kind=kind, **detail)
            slo_mod.SLO.note_hazard(f"watchdog_{kind}", detail)
            logger.error("engine watchdog tripped: %s %s — /health now "
                         "answers 503 until the condition clears",
                         kind, detail)
            if self.evacuate_on_trip and hasattr(self.scheduler,
                                                 "request_evacuation"):
                try:
                    # guard: the DRIVER re-evaluates the conditions at the
                    # instant it can act. A tick_stall trip is stale BY
                    # CONSTRUCTION once the driver is ticking again (it
                    # just stamped the heartbeat), and a transient
                    # hung_dispatch that drained meanwhile must not kill
                    # every live stream on a now-healthy worker.
                    self.scheduler.request_evacuation(
                        wait_s=0.0, reason=f"watchdog_{kind}",
                        guard=self.condition_still_true)
                except Exception as exc:
                    logger.warning("trip evacuation request failed: %s", exc)
        self.healthy = False

    def _clear(self, kind: str) -> None:
        if self._tripped.get(kind):
            self._tripped[kind] = False
            logger.warning("engine watchdog: %s condition cleared", kind)

    def check(self) -> bool:
        """One evaluation pass (the poll loop's body; tests call it
        directly with a fake clock). Returns the resulting health."""
        sched = self.scheduler
        now = self._clock()
        # tick heartbeat
        last_tick = getattr(sched, "last_tick_mono", None)
        running = bool(getattr(sched, "_running", False))
        if running and last_tick is not None \
                and now - last_tick > self.tick_stall_s:
            self._trip("tick_stall",
                       {"stalled_s": round(now - last_tick, 3),
                        "bound_s": self.tick_stall_s})
        else:
            self._clear("tick_stall")
        # oldest in-flight dispatch (racy peek from another thread: the
        # deque may mutate underneath — IndexError just means the pipeline
        # drained, which is the healthy answer)
        hung = False
        try:
            inflight = getattr(sched, "_inflight", None)
            if inflight:
                head = inflight[0]
                issued_at, steps = head[4]
                age = now - issued_at
                bound = self.dispatch_bound(steps)
                if age > bound:
                    hung = True
                    self._trip("hung_dispatch",
                               {"age_s": round(age, 3),
                                "bound_s": round(bound, 3),
                                "steps": int(steps)})
        except (IndexError, TypeError):
            pass
        if not hung:
            self._clear("hung_dispatch")
        self.healthy = not any(self._tripped.values())
        return self.healthy

    def condition_still_true(self) -> bool:
        """Side-effect-free re-evaluation of the trip conditions (no
        trip/clear/counter mutation — safe to call from the scheduler's
        driver thread concurrently with the poll loop): is a tick stall
        or hung dispatch true RIGHT NOW? Guards queued trip-evacuations
        so a condition that cleared while the request waited cancels the
        sweep instead of evacuating a healthy worker."""
        sched = self.scheduler
        now = self._clock()
        last_tick = getattr(sched, "last_tick_mono", None)
        if bool(getattr(sched, "_running", False)) and last_tick is not None \
                and now - last_tick > self.tick_stall_s:
            return True
        try:
            inflight = getattr(sched, "_inflight", None)
            if inflight:
                issued_at, steps = inflight[0][4]
                if now - issued_at > self.dispatch_bound(steps):
                    return True
        except (IndexError, TypeError):
            pass
        return False

    def status(self) -> Dict[str, Any]:
        """The /health body's watchdog block."""
        return {
            "healthy": self.healthy,
            "draining": self.draining,
            "tripped": sorted(k for k, v in self._tripped.items() if v),
            "bounds": {"tick_stall_s": self.tick_stall_s,
                       "dispatch_s": self.dispatch_bound_s,
                       "dispatch_factor": self.dispatch_factor},
        }

    def serving_ok(self) -> bool:
        """Should /health answer 200? False while tripped OR draining."""
        return self.healthy and not self.draining

    # ----------------------------------------------------------------- loop

    def _loop(self) -> None:
        logger.info("engine watchdog started (tick_stall=%.0fs "
                    "dispatch_bound=%.0fs factor=%.0f)",
                    self.tick_stall_s, self.dispatch_bound_s,
                    self.dispatch_factor)
        while self._running:
            try:
                self.check()
            except Exception:
                logger.exception("watchdog check failed")
            time.sleep(self.poll_s)
