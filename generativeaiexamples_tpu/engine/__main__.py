"""CLI entry: run the OpenAI-compatible TPU model server.

    python -m generativeaiexamples_tpu.engine [--tiny] [--port 8000]

`--tiny` serves the deterministic test-scale model with the byte tokenizer
(the hostless fake backend, SURVEY §4) — used by tests, local dev, and the
chain-server compose parity flow. Without `--tiny`, the model/config come
from AppConfig (APP_ENGINE_* env), loading an orbax checkpoint when
`APP_ENGINE_CHECKPOINT_DIR` is set and random weights otherwise.
"""

from __future__ import annotations

import argparse
import logging

import jax

from generativeaiexamples_tpu.core.config import get_config
from generativeaiexamples_tpu.engine.engine import EngineCore
from generativeaiexamples_tpu.engine.scheduler import Scheduler
from generativeaiexamples_tpu.engine.server import run_server
from generativeaiexamples_tpu.engine.tokenizer import get_tokenizer
from generativeaiexamples_tpu.models import llama


def build_scheduler(tiny: bool = False) -> tuple:
    cfg = get_config()
    if tiny:
        model_cfg = llama.LlamaConfig.tiny(vocab_size=300)
        tokenizer = get_tokenizer("")
        params = llama.init_params(jax.random.PRNGKey(5), model_cfg)
        model_name = "tiny-llama-test"
    else:
        from generativeaiexamples_tpu.models import model_configs

        # the served ARCHITECTURE follows APP_ENGINE_MODEL_FAMILY (same
        # names as the train CLI, so a fine-tuned checkpoint serves under
        # the family it trained under); APP_LLM_MODEL_NAME remains the
        # cosmetic OpenAI model id and never selects weights
        configs = model_configs()
        family = cfg.engine.model_family
        if family not in configs:
            raise SystemExit(
                f"unknown APP_ENGINE_MODEL_FAMILY {family!r}; "
                f"valid: {sorted(configs)}")
        model_cfg = configs[family]()
        tokenizer = get_tokenizer(cfg.engine.checkpoint_dir)
        if cfg.engine.checkpoint_dir:
            from generativeaiexamples_tpu.models.hf_loader import (
                is_hf_dir, load_hf_dir)
            if is_hf_dir(cfg.engine.checkpoint_dir):
                # a local HuggingFace checkpoint serves directly (the
                # NIM-parity path: real weights from a model directory,
                # config derived from config.json — no conversion step)
                model_cfg, params = load_hf_dir(cfg.engine.checkpoint_dir)
                logging.info("serving HF checkpoint %s (%s layers, dim %s)",
                             cfg.engine.checkpoint_dir,
                             model_cfg.n_layers, model_cfg.dim)
            else:
                from generativeaiexamples_tpu.train.checkpoints import (
                    load_params)
                params = load_params(cfg.engine.checkpoint_dir, model_cfg)
        else:
            logging.warning("no checkpoint_dir set — serving RANDOM weights")
            params = llama.init_params(jax.random.PRNGKey(0), model_cfg)
        model_name = cfg.llm.model_name
    # Tensor-parallel serving by config (ref INFERENCE_GPU_COUNT parity).
    # Default (empty mesh_shape): the largest tensor degree that divides both
    # head counts, remaining devices on "data" — so any device count boots
    # (v5e-8 + 8 kv heads ⇒ pure tp=8). Tiny mode stays single-device unless
    # a mesh is explicitly configured.
    mesh = None
    if cfg.engine.mesh_shape or (jax.device_count() > 1 and not tiny):
        from generativeaiexamples_tpu.parallel import mesh as pmesh
        if cfg.engine.mesh_shape:
            mesh_cfg = pmesh.parse_mesh_shape(cfg.engine.mesh_shape,
                                              pmesh.INFER_AXES)
        else:
            n = jax.device_count()
            tp = max(t for t in range(1, n + 1)
                     if n % t == 0 and model_cfg.n_heads % t == 0
                     and model_cfg.n_kv_heads % t == 0)
            mesh_cfg = pmesh.MeshConfig(axes=pmesh.INFER_AXES,
                                        shape=(n // tp, tp))
        mesh = pmesh.create_mesh(mesh_cfg)
        logging.info("serving over mesh %s", dict(mesh.shape))
    core = EngineCore(model_cfg, cfg.engine, params, eos_id=tokenizer.eos_id,
                      mesh=mesh)
    # per-request LoRA adapters: APP_ENGINE_ADAPTERS="name=dir,name2=dir2"
    # (dirs written by train/lora.py save_adapters). Registered BEFORE
    # warmup so the stacked-adapter programs compile once, up front.
    import os
    spec = os.environ.get("APP_ENGINE_ADAPTERS", "")
    if spec:
        from generativeaiexamples_tpu.train.lora import load_adapters
        for entry in spec.split(","):
            name, _, path = entry.strip().partition("=")
            if not name or not path:
                raise SystemExit(f"bad APP_ENGINE_ADAPTERS entry {entry!r} "
                                 "(want name=dir,...)")
            core.register_adapter(name, load_adapters(path, model_cfg))
            logging.info("registered adapter %r from %s", name, path)
    if not tiny:
        # compile the whole serving program grid before the first request —
        # lazy compiles (~20-40 s each over a tunneled chip) would stall
        # live traffic (the scheduler creates the real state afterwards);
        # tokenizer included so the constrained-decoding variants warm too
        logging.info("compiling serving programs (grouped prefill buckets "
                     "%s, decode depths, grammar variants)...",
                     core.group_buckets)
        core.warmup(tokenizer=tokenizer)
    return Scheduler(core, tokenizer), model_name


def main() -> None:
    from generativeaiexamples_tpu.core.debug import install as _debug_install
    _debug_install()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tiny", action="store_true", help="serve the tiny test model")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    scheduler, model_name = build_scheduler(tiny=args.tiny)
    run_server(scheduler, model_name, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
