"""OpenAI-compatible model server over the TPU engine (aiohttp + SSE).

API parity with the surface the reference's clients consume
(`ChatNVIDIA(base_url=...)` speaks OpenAI `/v1`; ref RAG/src/chain_server/
utils.py:366-399 and docker-compose-nim-ms.yaml:2-28):

  * POST /v1/chat/completions   — messages → chat template → streamed or whole;
                                  `tools`/`tool_choice` → `tool_calls`,
                                  `response_format` json modes (engine/tools.py —
                                  the NIM tool-calling surface the reference's
                                  agent notebooks consume)
  * POST /v1/completions        — raw prompt completion
  * GET  /v1/models             — served model card
  * GET  /health                — liveness (compose healthcheck parity,
                                  ref docker-compose-nim-ms.yaml:23-28)
  * GET  /metrics               — engine metrics (req/s, TTFT, tok/s)

Streaming uses `text/event-stream` with `data: {chunk}\n\n` frames and a
final `data: [DONE]`, matching the OpenAI SSE contract the reference's
LangChain clients parse. Tool requests stream incremental `tool_calls`
deltas (name first, then argument fragments — tools.ToolCallStreamer);
grammar-constrained JSON mode streams plain content deltas (validity is
token-level guaranteed, engine/grammar.py); only un-grammared JSON mode
still buffers for its extract-and-rewrite step.
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
import os
import re
import time
import uuid
from typing import Any, Dict, List, Optional

from aiohttp import web

from generativeaiexamples_tpu.core import kv_wire as kv_wire_mod
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.engine import grammar as grammar_mod
from generativeaiexamples_tpu.engine import kv_cache as kv_cache_mod
from generativeaiexamples_tpu.engine import tools as tools_mod
from generativeaiexamples_tpu.engine.engine import TOP_LP
from generativeaiexamples_tpu.engine.scheduler import Request, Scheduler
from generativeaiexamples_tpu.engine.watchdog import (
    EngineWatchdog, watchdog_enabled)
from generativeaiexamples_tpu.observability import chaos as chaos_mod
from generativeaiexamples_tpu.observability import flight as flight_mod
from generativeaiexamples_tpu.observability import otel
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability import usage as usage_mod
from generativeaiexamples_tpu.server.common import (
    MAX_TOKENS_CAP, StreamDrain, add_debug_routes, metrics_handler,
    parse_stop, sse_done, sse_write,
)


@functools.lru_cache(maxsize=64)
def _grammar_for(kind: str, payload: str) -> Optional[object]:
    """Compile-once cache of constrained-decoding grammars (engine/
    grammar.py): schemas and tool sets repeat across requests, DFA
    compilation doesn't need to. Returns None for unsupported schemas —
    the request then runs prompt+parse only, as before round 4."""
    try:
        if kind == "schema":
            return grammar_mod.Grammar.from_schema(json.loads(payload))
        if kind == "json":
            return grammar_mod.Grammar.json_value()
        if kind == "tools":
            spec = json.loads(payload)
            return grammar_mod.Grammar.for_tools(spec["tools"],
                                                 forced=spec["forced"])
    except grammar_mod.UnsupportedSchema as exc:
        logging.getLogger(__name__).info(
            "schema outside the DFA-regular subset (%s); serving with "
            "prompt+parse only", exc)
    return None


_RID_SAFE = re.compile(r"[^A-Za-z0-9_.:\-]")


def inbound_request_id(headers) -> str:
    """Adopt a caller-supplied ``X-Request-Id`` (the failover router stamps
    one id on every dispatch of a logical request, including the
    prefill→handoff pair) so ``/debug/requests/<id>`` correlates the same
    request across workers. Sanitized and length-capped — the id is a log/
    URL key, never trusted further."""
    raw = (headers.get("X-Request-Id") or "").strip()
    return _RID_SAFE.sub("", raw)[:64]


def _finish_reason(req, default: str = "stop") -> str:
    """OpenAI finish_reason from the scheduler's recorded finish cause:
    "length" must be distinguishable from a stop-string / EOS end (the
    OpenAI contract clients use to detect budget truncation). ``default``
    carries caller overrides like "tool_calls". "evacuated" passes
    through verbatim — the routing frontend keys its snapshot-resume
    recovery on exactly that marker (a masked "stop" would end the
    client's stream mid-generation, silently truncated)."""
    if getattr(req, "error", None):
        return "error"
    if default == "tool_calls":
        return default   # a parsed tool call is complete regardless of cause
    if getattr(req, "finish_reason", None) in ("length", "evacuated"):
        return req.finish_reason
    return default


def _chunk(model: str, rid: str, delta: Dict[str, Any],
           finish_reason: Optional[str] = None, index: int = 0,
           logprobs: Optional[Dict[str, Any]] = None) -> str:
    choice: Dict[str, Any] = {"index": index, "delta": delta,
                              "finish_reason": finish_reason}
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return json.dumps({
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
    })


class ModelServer:
    def __init__(self, scheduler: Scheduler, model_name: str,
                 watchdog: Optional[EngineWatchdog] = None) -> None:
        self.scheduler = scheduler
        self.model_name = model_name
        # health arbiter (engine/watchdog.py): while it reports not-
        # serving (tripped or draining), /health answers 503 so the
        # routing frontend circuit-breaks this worker away; None = no
        # watchdog (APP_WATCHDOG=off), health is liveness-only as before
        self.watchdog = watchdog
        self.app = web.Application(client_max_size=1024 ** 3)
        self.app.add_routes([
            # role-aware health: the engine's own handler rides the
            # scheduler's load surface on the liveness body, so the
            # routing frontend (server/failover.py) discovers roles and
            # queue depth with the probes it already makes
            web.get("/health", self.health),
            web.get("/metrics", metrics_handler),
            web.get("/v1/models", self.models),
            web.post("/v1/chat/completions", self.chat_completions),
            web.post("/v1/completions", self.completions),
            # KV-page handoff between engine roles (disaggregated
            # serving): prefill exports, handoff imports + streams
            web.post("/v1/kv/prefill", self.kv_prefill),
            web.post("/v1/kv/handoff", self.kv_handoff),
            # on-demand device profiling around LIVE serving (observability/
            # profiling.profile_trace was bench-only before): capture N
            # seconds of trace, return the directory to load in
            # TensorBoard/Perfetto — no profiler-server tooling needed
            web.post("/debug/profile", self.debug_profile),
            # graceful drain (engine/watchdog.py): 503 on /health while
            # in-flight streams finish; ?off=1 re-admits the worker;
            # ?evacuate=1 additionally snapshots every live decode slot
            # so streams MOVE to peers instead of finishing here
            web.post("/debug/drain", self.debug_drain),
            # live-migration pull: a mid-decode snapshot parked by an
            # evacuation (drain/SIGTERM/watchdog trip), or exported on
            # demand for a still-live stream whose consumer died — the
            # router resumes it token-identically on a peer replica
            web.get("/v1/kv/evacuation/{rid}", self.kv_evacuation),
        ])
        self._profiling = False
        # /debug/flight + /debug/requests[/<id>] — the engine process is
        # where the scheduler lives, so these answer with live data here
        # (drain=False: this server's watchdog-arbitrated /debug/drain,
        # registered above, owns the path)
        add_debug_routes(self.app, drain=False)

    # ------------------------------------------------------------- endpoints

    @property
    def role(self) -> str:
        """This worker's serving role (core/config.py APP_ENGINE_ROLE)."""
        core = getattr(self.scheduler, "core", None)
        return str(getattr(core, "role", "unified") or "unified")

    async def health(self, request: web.Request) -> web.Response:
        """Liveness + the routing surface: engine_role, queue depth, slot
        fill, and slo_pressure ride the probe the pool client already
        makes (server/failover.py scores least-loaded dispatch from
        exactly these fields). A tripped watchdog (hung dispatch, stalled
        driver tick) or an operator drain answers 503 — the router
        circuit-breaks this worker away while in-flight streams keep
        serving, and re-admits it once the condition clears."""
        stats: Dict[str, Any] = {}
        try:
            stats = self.scheduler.load_stats()
        except Exception as exc:
            # health must answer even if the scheduler is mid-reset
            logging.getLogger(__name__).debug("load_stats failed: %s", exc)
        body = {"message": "Service is up.",
                "slo_pressure": slo_mod.SLO.pressure(),
                # KV-wire capability advert: the routing frontend reads
                # this off the probes it already makes and never sends a
                # binary frame to a worker that would 400 it (old engines
                # carry no field → JSON wire, the PR 6 behavior)
                "kv_wire": ["binary", "json"],
                **stats}
        # fleet usage plane (observability/usage.py): the per-tenant
        # rollup and chip-utilization card piggyback on the probe cycle
        # the routing frontend already runs — /debug/fleet on the router
        # aggregates exactly these fields across workers. Both are
        # bounded (tenant cardinality cap; fixed-size card).
        body["usage_by_tenant"] = usage_mod.USAGE.rollup()
        body["perf"] = usage_mod.worker_perf_card()
        # burn-rate alert summary piggybacks the same probe cycle (the
        # usage-plane pattern): the router surfaces worker alerts without
        # a second scrape. Off-mode cost is one attribute read.
        from generativeaiexamples_tpu.observability.forensics import (
            FORENSICS)
        if FORENSICS.enabled:
            from generativeaiexamples_tpu.observability.alerts import ALERTS
            body["alerts_active"] = ALERTS.active()
        if self.watchdog is not None:
            body["watchdog"] = self.watchdog.status()
            if not self.watchdog.serving_ok():
                body["message"] = ("Service is draining."
                                   if self.watchdog.draining
                                   else "Service is unhealthy "
                                        "(watchdog tripped).")
                return web.json_response(body, status=503)
        return web.json_response(body)

    async def debug_drain(self, request: web.Request) -> web.Response:
        """``POST /debug/drain`` starts a graceful drain (health 503, new
        traffic routes away, in-flight streams finish); ``?off=1`` lifts
        it. ``?evacuate=1`` additionally exports every live decode slot's
        mid-decode snapshot (scheduler.request_evacuation): each stream
        ends with finish_reason "evacuated" and its snapshot parks at
        ``/v1/kv/evacuation/<rid>`` for the router to resume on a peer —
        zero-re-prefill worker rotation. 409 when no watchdog is attached
        (APP_WATCHDOG=off)."""
        if self.watchdog is None:
            raise web.HTTPConflict(text=json.dumps(
                {"error": "no watchdog attached (APP_WATCHDOG=off); "
                          "drain needs the health arbiter"}))
        if request.query.get("off", "").strip() in ("1", "true", "on"):
            self.watchdog.undrain()
            return web.json_response(self.watchdog.status())
        self.watchdog.drain()
        body: Dict[str, Any] = dict(self.watchdog.status())
        if request.query.get("evacuate", "").strip() in ("1", "true", "on") \
                and hasattr(self.scheduler, "request_evacuation"):
            loop = asyncio.get_running_loop()
            # the export runs on the DRIVER thread; this waits off the
            # event loop so other streams (and the snapshot pulls the
            # router makes right after) keep pumping
            body["evacuation"] = await loop.run_in_executor(
                None, functools.partial(self.scheduler.request_evacuation,
                                        reason="drain"))
        return web.json_response(body)

    async def kv_evacuation(self, request: web.Request) -> web.Response:
        """``GET /v1/kv/evacuation/{rid}``: hand out one request's
        mid-decode snapshot on the negotiated KV wire. Serves the parked
        outbox entry from a prior evacuation, or — the hard-failover
        case, where the router's stream died but this worker is still
        alive — exports the still-live slot on demand (a single-rid
        evacuation through the driver). Each snapshot is served ONCE
        (the resume consumes the generation position; serving it twice
        would fork the stream). 404 when the request is unknown or was
        never snapshotable — the router falls back to re-prefill."""
        rid = _RID_SAFE.sub("", str(request.match_info.get("rid", "")))[:64]
        if not rid:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "missing request id"}))
        sched = self.scheduler
        loop = asyncio.get_running_loop()
        payload = (sched.take_evacuated(rid)
                   if hasattr(sched, "take_evacuated") else None)
        if payload is None and hasattr(sched, "request_evacuation"):
            await loop.run_in_executor(
                None, functools.partial(sched.request_evacuation,
                                        rids={rid}, wait_s=15.0,
                                        reason="pull"))
            payload = sched.take_evacuated(rid)
        if payload is None:
            raise web.HTTPNotFound(text=json.dumps(
                {"error": f"no evacuable state for request {rid!r} "
                          f"(finished, never snapshotable, or already "
                          f"pulled) — resume via re-prefill"}))
        binary = self._wants_kv_frames(request)
        body, ctype = await loop.run_in_executor(
            None, kv_wire_mod.encode_for_wire, payload, binary)
        return web.Response(body=body, content_type=ctype,
                            headers={"X-Request-Id": rid})

    async def _chaos_gate(self, site: str) -> None:
        """Server-side chaos injection (observability/chaos.py) at the
        HTTP seam: an injected delay await-sleeps (never blocks the
        loop), an injected 5xx answers 503 — the router's retry policy
        must absorb both. APP_CHAOS=off is one attribute read."""
        if not chaos_mod.CHAOS.enabled:
            return
        action = chaos_mod.CHAOS.server_fault(site)
        if action is None:
            return
        kind, param = action
        if kind == "delay":
            await asyncio.sleep(param)
        elif kind == "error":
            raise web.HTTPServiceUnavailable(text=json.dumps(
                {"error": f"chaos: injected 5xx at {site}"}))

    def _require_decode_capable(self) -> None:
        if self.role == "prefill":
            raise web.HTTPConflict(text=json.dumps(
                {"error": "this worker serves APP_ENGINE_ROLE=prefill: it "
                          "only runs chunked prefill (/v1/kv/prefill) and "
                          "never decodes — route generation to a decode or "
                          "unified worker (server/failover.py does this "
                          "from /health role discovery)"}))

    async def debug_profile(self, request: web.Request) -> web.Response:
        """``POST /debug/profile?seconds=N``: capture a device trace around
        live serving (observability/profiling.profile_trace) and return the
        trace directory. One capture at a time (jax has one global
        profiler); duration is clamped to [0.05, 60] s so a typo'd query
        cannot wedge the profiler for an hour. 503 when the profiler is
        unavailable (stripped builds) — never a silent empty capture."""
        from generativeaiexamples_tpu.observability import profiling
        try:
            seconds = float(request.query.get("seconds", "") or 2.0)
        except ValueError:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "seconds must be a number"}))
        seconds = min(max(seconds, 0.05), 60.0)
        log_dir = (request.query.get("dir", "").strip()
                   or os.environ.get("APP_PROFILE_DIR", "")
                   or "/tmp/gaie_tpu_profiles")
        if self._profiling:
            raise web.HTTPConflict(text=json.dumps(
                {"error": "a profile capture is already running (jax has "
                          "one global profiler); retry when it returns"}))
        self._profiling = True
        try:
            with profiling.profile_trace(log_dir) as run_dir:
                if run_dir is not None:
                    # only hold the capture window when a trace is actually
                    # recording — an unavailable profiler answers 503 NOW,
                    # not after sleeping the full requested duration
                    await asyncio.sleep(seconds)
        finally:
            self._profiling = False
        if run_dir is None:
            raise web.HTTPServiceUnavailable(text=json.dumps(
                {"error": "device profiler unavailable on this build"}))
        return web.json_response({"trace_dir": run_dir,
                                  "seconds": seconds,
                                  "hint": "load in TensorBoard's profile "
                                          "plugin or Perfetto"})

    async def models(self, request: web.Request) -> web.Response:
        cards = [{"id": self.model_name, "object": "model",
                  "owned_by": "generativeaiexamples_tpu"}]
        # registered LoRA adapters serve as first-class model ids (the
        # multi-LoRA convention OpenAI-compatible servers follow): a
        # request whose `model` names one routes to that adapter's slot
        for name in self._adapter_names():
            cards.append({"id": name, "object": "model",
                          "owned_by": "generativeaiexamples_tpu",
                          "parent": self.model_name})
        return web.json_response({"object": "list", "data": cards})

    def _adapter_names(self) -> list:
        core = getattr(self.scheduler, "core", None)
        return list(getattr(core, "adapter_names", []) or [])

    def _adapter_for(self, body: Dict[str, Any]) -> str:
        """Route the OpenAI `model` field: a registered adapter name
        selects that adapter; the base model id (or an absent field)
        serves base weights. Once adapters exist, any OTHER id is a loud
        404 — a typo'd fine-tune name must never silently serve base
        weights (mirrors the scheduler's unknown-adapter guard)."""
        model_id = str(body.get("model") or "")
        names = self._adapter_names()
        if model_id in names:
            return model_id
        if names and model_id and model_id != self.model_name:
            raise web.HTTPNotFound(text=json.dumps(
                {"error": f"unknown model {model_id!r}; served: "
                          f"{[self.model_name] + names}"}))
        return ""

    def _parse_sampling(self, body: Dict[str, Any]) -> Dict[str, Any]:
        def get(key, default, cast):
            value = body.get(key)
            return default if value is None else cast(value)  # JSON null = default

        top_lp = get("top_logprobs", 0, int)
        return {
            "max_tokens": min(get("max_tokens", 128, int), MAX_TOKENS_CAP),
            "temperature": get("temperature", 0.7, float),
            "top_p": get("top_p", 1.0, float),
            "top_k": get("top_k", 0, int),
            "stop": parse_stop(body.get("stop")),
            "seed": (int(body["seed"]) if body.get("seed") is not None
                     else None),
            "logprobs": bool(get("logprobs", False, bool) or top_lp),
            "top_logprobs": max(0, min(top_lp, TOP_LP)),
        }

    @staticmethod
    def _parse_slo(request: web.Request) -> Dict[str, Any]:
        """SLO admission fields from the propagated headers (observability/
        slo.py; the chain server — or any client — sends class + REMAINING
        deadline budget in ms). An unknown class is a loud 400: silently
        downgrading a caller's objective would falsify every attainment
        number downstream. The W3C trace id (same ``traceparent`` the span
        envelope consumes) rides along so SLO histograms/breach records
        link to the request's trace."""
        try:
            cls, deadline_s = slo_mod.parse_inbound(request.headers)
        except ValueError as exc:
            raise web.HTTPBadRequest(text=json.dumps({"error": str(exc)}))
        parent = otel.extract_traceparent(dict(request.headers))
        return {"slo_class": cls or "", "deadline_s": deadline_s,
                "trace_id": parent.trace_id if parent else ""}

    def _format_logprobs(self, req) -> Dict[str, Any]:
        """OpenAI chat `logprobs` object from the scheduler's raw
        (token_id, logprob, top) tuples. The first (fused-prefill) token's
        top_logprobs lists only itself — its alternatives never leave the
        device (documented engine limitation)."""
        tok = self.scheduler.tokenizer
        content = []
        for tid, lp, top in req.logprob_data:
            s = tok.decode([tid])
            entry: Dict[str, Any] = {
                "token": s, "logprob": lp,
                "bytes": list(s.encode("utf-8"))}
            if req.top_logprobs:
                alts = top if top else ([(tid, lp)] if lp is not None else [])
                entry["top_logprobs"] = [
                    {"token": tok.decode([i]), "logprob": l,
                     "bytes": list(tok.decode([i]).encode("utf-8"))}
                    for i, l in alts[:req.top_logprobs]]
            content.append(entry)
        return {"content": content}

    def _prepare_chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """The ONE message-preparation pipeline for every chat-shaped
        entrypoint (/v1/chat/completions and /v1/kv/prefill): thinking
        toggle, forced-tool validation, message normalization, tool/JSON
        prompt-contract injection. Shared so the two endpoints cannot
        drift — the prompt a handoff route prefills must be exactly the
        prompt a unified route would have served."""
        messages = body.get("messages", [])
        if not messages:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "messages must be non-empty"}))
        thinking = body.get("thinking")
        if thinking is not None:
            # nemotron detailed-thinking toggle (ref: nemotron/
            # llama_3.3_nemotron_super_49B/README.md — the model family is
            # steered by a literal "detailed thinking on|off" system line)
            messages = ([{"role": "system",
                          "content": "detailed thinking "
                                     + ("on" if thinking else "off")}]
                        + list(messages))
        tools = body.get("tools") or []
        tool_choice = body.get("tool_choice", "auto" if tools else "none")
        response_format = body.get("response_format") or {}
        json_mode = response_format.get("type") in ("json_object",
                                                    "json_schema")
        name = tools_mod.forced_name(tool_choice)
        if name and name not in tools_mod.tool_names(tools):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": f"tool_choice names unknown tool {name!r}"}))
        messages = tools_mod.normalize_messages(messages)
        use_tools = bool(tools) and tool_choice != "none"
        if use_tools:
            messages = tools_mod.inject_tool_prompt(messages, tools,
                                                    tool_choice)
        if json_mode:
            # with tools, the JSON constraint scopes to non-tool replies
            messages = tools_mod.inject_json_prompt(
                messages, response_format, with_tools=use_tools)
        return {"messages": messages, "tools": tools,
                "tool_choice": tool_choice,
                "response_format": response_format, "json_mode": json_mode,
                "use_tools": use_tools, "forced_name": name}

    @staticmethod
    def _grammar_for_prep(prep: Dict[str, Any]):
        """On-device constrained decoding whenever the output contract is
        unambiguous: a forced/required tool call, or JSON mode without
        tools (tool_choice "auto" may legally answer in prose, so it
        stays prompt+parse). The prompt contract is ALWAYS also injected
        — the mask guarantees validity, the prompt guides content.

        Returns ``(grammar, (kind, payload) | None)`` — the spec is the
        grammar's constructor arguments, compact enough to ride the KV
        handoff's scalar passthrough so a decode replica can recompile
        the SAME grammar through its own ``_grammar_for`` cache. One
        copy of this decision, shared by /v1/chat/completions and
        /v1/kv/prefill, so unified and disaggregated routes cannot
        drift on WHEN enforcement applies."""
        tools = prep["tools"]
        name = prep["forced_name"]
        spec = None
        if prep["use_tools"] and (prep["tool_choice"] == "required" or name):
            spec = ("tools", json.dumps({"tools": tools, "forced": name}))
        elif prep["json_mode"] and not prep["use_tools"]:
            if prep["response_format"].get("type") == "json_schema":
                schema = prep["response_format"].get(
                    "json_schema", {}).get("schema", {})
                # NOT sort_keys: property order is part of the enforced
                # language (fixed-order members) and must match the order
                # the prompt shows the model
                spec = ("schema", json.dumps(schema))
            else:
                spec = ("json", "")
        if spec is None:
            return None, None
        grammar = _grammar_for(*spec)
        return grammar, (spec if grammar is not None else None)

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        self._require_decode_capable()
        await self._chaos_gate("engine.chat")
        body = await request.json()
        prep = self._prepare_chat(body)
        messages = prep["messages"]
        tools = prep["tools"]
        json_mode = prep["json_mode"]
        use_tools = prep["use_tools"]
        grammar, _gspec = self._grammar_for_prep(prep)
        prompt_ids = self.scheduler.tokenizer.apply_chat_template(messages)
        cont = str(body.get("continue_text") or "")
        if cont:
            # mid-stream failover resume (server/failover.py): continue an
            # assistant turn already partially streamed by ANOTHER engine
            # worker — the template renders here, the emitted prefix
            # appends after it, generation proceeds from that context
            # (the same prompt+generated resume shape the scheduler uses
            # for preemptions). An active grammar resumes from the state
            # reached after the prefix (Request.grammar_prefix).
            prompt_ids = prompt_ids + self.scheduler.tokenizer.encode(cont)
        return await self._run(request, body, prompt_ids, chat=True,
                               tools=tools if use_tools else [],
                               json_mode=json_mode, grammar=grammar,
                               grammar_prefix=cont)

    async def completions(self, request: web.Request) -> web.StreamResponse:
        self._require_decode_capable()
        await self._chaos_gate("engine.completions")
        body = await request.json()
        prompt = body.get("prompt", "")
        prompt_ids = self.scheduler.tokenizer.encode(prompt, add_bos=True)
        return await self._run(request, body, prompt_ids, chat=False)

    # ------------------------------------------- KV handoff (disaggregation)

    def _prompt_ids_from_body(self, body: Dict[str, Any]) -> tuple:
        """Render a /v1/kv/prefill request body to ``(prompt_ids, grammar,
        grammar_spec, continue_text)``: chat messages run the SAME
        preparation pipeline as /v1/chat/completions (`_prepare_chat` +
        `_grammar_for_prep` — one copy each, so the endpoints cannot
        drift); a raw ``prompt`` is encoded directly (no grammar).
        ``continue_text`` appends an emitted prefix for mid-stream
        failover resumes, exactly as the unified resume path does — the
        grammar walks it before the first masked sample, and the walked
        state later rides the handoff."""
        grammar = gspec = None
        if body.get("messages"):
            prep = self._prepare_chat(body)
            prompt_ids = self.scheduler.tokenizer.apply_chat_template(
                prep["messages"])
            grammar, gspec = self._grammar_for_prep(prep)
        else:
            prompt_ids = self.scheduler.tokenizer.encode(
                str(body.get("prompt", "")), add_bos=True)
        cont = str(body.get("continue_text") or "")
        if cont:
            prompt_ids = prompt_ids + self.scheduler.tokenizer.encode(cont)
        return prompt_ids, grammar, gspec, cont

    @staticmethod
    def _wants_kv_frames(request: web.Request) -> bool:
        """Content negotiation for /v1/kv/prefill: the binary frame is
        served only to clients whose Accept names it — an old router that
        sends no Accept (or ``application/json``) keeps getting the JSON
        base64 wire, byte-compatible with PR 6."""
        return (kv_cache_mod.KV_FRAMES_CONTENT_TYPE
                in request.headers.get("Accept", ""))

    async def kv_prefill(self, request: web.Request) -> web.Response:
        """Run chunked prefill for a request and return the exported KV
        pages + sampling state as a handoff payload — the prefill half of
        disaggregated serving. Any role can serve this (a unified worker
        is a valid prefill source); the payload POSTs to a decode worker's
        /v1/kv/handoff, which imports it and streams the completion.

        The wire is content-negotiated: ``Accept:
        application/x-kv-frames`` gets the binary zero-copy frame
        (core/kv_wire.py — raw array segments, no base64 inflation, crc32
        per segment); everything else gets the JSON base64 compat form.
        Constrained-decoding grammars now ride the payload's scalar
        passthrough (kind + payload spec + walked state semantics in
        scheduler._export_handoff), so disaggregated routes keep
        token-level enforcement instead of degrading to prompt+parse."""
        await self._chaos_gate("engine.kv_prefill")
        body = await request.json()
        parent = otel.extract_traceparent(dict(request.headers))
        with otel.use_parent(parent):
            with otel.get_tracer("engine").span(
                    "engine:kv_prefill",
                    attributes={"http.path": str(request.path)}) as span:
                prompt_ids, grammar, gspec, cont = \
                    self._prompt_ids_from_body(body)
                sampling = self._parse_sampling(body)
                sampling.pop("logprobs", None)
                sampling.pop("top_logprobs", None)
                slo_fields = self._parse_slo(request)
                rid_in = inbound_request_id(request.headers)
                if rid_in:
                    slo_fields["request_id"] = rid_in
                req = Request(prompt_ids=list(prompt_ids), prefill_only=True,
                              grammar=grammar, grammar_spec=gspec,
                              grammar_prefix=cont,
                              tenant=usage_mod.tenant_from_headers(
                                  request.headers),
                              **slo_fields, **sampling)
                request["engine_request"] = req
                self.scheduler.submit(req)
                await StreamDrain(self.scheduler.iter_text(req)).join_text()
                if req.error or not req.handoff:
                    raise web.HTTPServiceUnavailable(text=json.dumps(
                        {"error": req.error
                         or "prefill produced no handoff"}))
                handoff = req.handoff
                if chaos_mod.CHAOS.enabled:
                    # chaos KV corruption (truncated rows / garbled
                    # geometry): the DECODE side must 409 this loudly at
                    # import validation — the fault class exists to prove
                    # corrupt payloads can never become served garbage KV
                    handoff = chaos_mod.CHAOS.corrupt_kv(
                        handoff, site="engine.kv_prefill")
                binary = self._wants_kv_frames(request)
                t_fetch = time.perf_counter()
                # the encode materializes the device-native export (THE
                # one host copy-out of a remotely-handed-off request) and
                # walks megabytes — run it off the event loop so other
                # streams keep pumping
                loop = asyncio.get_running_loop()
                payload_body, ctype = await loop.run_in_executor(
                    None, kv_wire_mod.encode_for_wire, handoff, binary)
                fetch_s = time.perf_counter() - t_fetch
                REGISTRY.histogram("kv_fetch_s").observe(fetch_s)
                if chaos_mod.CHAOS.enabled and binary:
                    # wire-level corruption (truncated / bit-garbled BINARY
                    # bodies): the decode side must 400 these at frame
                    # validation (crc32/length) BEFORE validate_handoff —
                    # raw segments would otherwise still be shape-valid
                    payload_body = chaos_mod.CHAOS.corrupt_wire(
                        payload_body, site="engine.kv_prefill.wire")
                if otel.tracing_enabled():
                    # the disagg-route trace's prefill leg: how big the KV
                    # payload is ON THE NEGOTIATED WIRE, how many pages
                    # move, the export dispatch + host materialize costs,
                    # and the queue-vs-device split from the timeline
                    span.set_attribute("kv.payload_bytes", len(payload_body))
                    span.set_attribute("kv.wire",
                                       "binary" if binary else "json-b64")
                    span.set_attribute("kv.pages",
                                       int(req.handoff.get("n_pages", 0)))
                    span.set_attribute(
                        "kv.export_device_s",
                        float(req.handoff.get("export_s", 0.0)))
                    span.set_attribute("kv.fetch_s", round(fetch_s, 6))
                    for key, value in flight_mod.timeline_attributes(
                            req).items():
                        span.set_attribute(key, value)
                headers = {"X-Request-Id": req.request_id}
                pk = getattr(self.scheduler, "prefix_key_hex", None)
                h0 = pk(prompt_ids) if pk else ""
                if h0:
                    # disagg routes learn the prefix identity too — the
                    # router's promote routing is wire-agnostic
                    headers["X-KV-Prefix"] = h0
                return web.Response(
                    body=payload_body,
                    content_type=ctype,
                    headers=headers)

    async def kv_handoff(self, request: web.Request) -> web.StreamResponse:
        """Import a /v1/kv/prefill payload into this worker's pool and
        stream the completion (SSE, same framing as /v1/chat/completions)
        — the decode half of disaggregated serving. Pool-geometry or
        dtype mismatches are a loud 409: prefill and decode workers must
        serve the same model + kv_quant."""
        self._require_decode_capable()
        await self._chaos_gate("engine.kv_handoff")
        raw = await request.read()
        body: Dict[str, Any] = {}
        if kv_wire_mod.is_kv_frames(raw, request.content_type or ""):
            # binary zero-copy wire: frame bounds + per-segment crc32
            # verify BEFORE anything reaches the pool — a truncated or
            # bit-garbled body is a loud 400 here, never scattered KV
            # (raw segments are shape-valid garbage; the JSON wire got
            # this check for free from the b64/JSON parse)
            try:
                payload = kv_wire_mod.decode_kv_frames(raw)
            except kv_wire_mod.KVWireError as exc:
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": f"undecodable handoff frame: {exc}"}))
        else:
            try:
                body = json.loads(raw)
                payload = kv_cache_mod.decode_kv_payload(body)
            except Exception as exc:
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": f"undecodable handoff payload: {exc}"}))
        parent = otel.extract_traceparent(dict(request.headers))
        with otel.use_parent(parent):
            with otel.get_tracer("engine").span(
                    "engine:kv_handoff",
                    attributes={"http.path": str(request.path)}) as span:
                slo_fields = self._parse_slo(request)
                rid_in = inbound_request_id(request.headers)
                if rid_in:
                    slo_fields["request_id"] = rid_in
                # one tenant across the disagg route: explicit header →
                # payload tenant → key hash (usage.handoff_tenant owns
                # the precedence and its rationale)
                tenant = usage_mod.handoff_tenant(request.headers, payload)
                if payload.get("resume"):
                    # snapshot resume: the router stamps how many chars it
                    # already delivered to the client — the scheduler
                    # re-emits only the gap (a hard-death pull can lag the
                    # exporting worker's emitted tokens; absent header =
                    # clean drain, everything was delivered)
                    raw_chars = request.headers.get("X-Resume-Chars")
                    if raw_chars is not None:
                        try:
                            payload["resume_chars"] = int(raw_chars)
                        except ValueError:
                            raise web.HTTPBadRequest(text=json.dumps(
                                {"error": "X-Resume-Chars must be an int"}))
                # grammar continuation: the payload's scalar passthrough
                # carries the grammar's constructor spec — recompile it
                # through the same compile-once cache the chat endpoint
                # uses; the scheduler walks prefix + first token and
                # activates the slot at that DFA state (no prompt+parse
                # degradation on disaggregated routes anymore). ONLY when
                # the prefill leg actually enforced it (grammar_attached):
                # a degraded prefill sampled its first token UNCONSTRAINED,
                # and attaching from token 2 here would launder that into
                # a token-level guarantee the stream never had — the whole
                # request stays prompt+parse, as the unified degrade does.
                grammar = None
                gram_kind = str(payload.get("grammar_kind") or "")
                if gram_kind and payload.get("grammar_attached"):
                    grammar = _grammar_for(
                        gram_kind, str(payload.get("grammar_payload") or ""))
                req = Request(
                    tenant=tenant,
                    prompt_ids=[int(t)
                                for t in payload.get("prompt_ids", [])],
                    max_tokens=int(payload.get("max_tokens", 128)),
                    temperature=float(payload.get("temperature", 0.7)),
                    top_k=int(payload.get("top_k", 0)),
                    top_p=float(payload.get("top_p", 1.0)),
                    stop=parse_stop(payload.get("stop")),
                    seed=int(payload.get("seed", 0)),
                    grammar=grammar,
                    grammar_prefix=str(payload.get("grammar_prefix") or ""),
                    **slo_fields)
                try:
                    self.scheduler.submit_prefilled(req, payload)
                except ValueError as exc:
                    raise web.HTTPConflict(text=json.dumps(
                        {"error": str(exc)}))
                request["engine_request"] = req
                model = str(payload.get("model") or body.get("model")
                            or self.model_name)
                rid = f"chatcmpl-{uuid.uuid4().hex[:16]}"
                resp = await self._sse_response(request)
                await sse_write(resp, _chunk(model, rid,
                                             {"role": "assistant"}))
                async for delta in StreamDrain(self.scheduler.iter_text(req)):
                    await sse_write(resp, _chunk(model, rid,
                                                 {"content": delta}))
                final = json.loads(_chunk(model, rid, {},
                                          _finish_reason(req)))
                if req.error:
                    final["error"] = req.error
                await sse_write(resp, json.dumps(final))
                await sse_done(resp)
                if otel.tracing_enabled():
                    # the trace's decode leg: payload size in, pages
                    # imported, import cost at admission, and the timeline
                    # attrs (queue wait vs prefill→first-token = the
                    # queue-vs-device split of this worker)
                    span.set_attribute("kv.payload_bytes", len(raw))
                    span.set_attribute(
                        "kv.wire", "binary" if not body else "json-b64")
                    span.set_attribute("kv.pages",
                                       int(payload.get("n_pages", 0)))
                    if req.kv_import_s is not None:
                        span.set_attribute("kv.import_s",
                                           float(req.kv_import_s))
                    for key, value in flight_mod.timeline_attributes(
                            req).items():
                        span.set_attribute(key, value)
                return resp

    # --------------------------------------------------------------- serving

    async def _run(self, request: web.Request, body: Dict[str, Any],
                   prompt_ids, chat: bool,
                   tools: Optional[List[Dict[str, Any]]] = None,
                   json_mode: bool = False,
                   grammar: Optional[object] = None,
                   grammar_prefix: str = "") -> web.StreamResponse:
        """Span envelope around ``_serve``: by the time the response (stream
        included) is written, the scheduler has stamped the request's full
        timeline, so the span carries queue-wait/TTFT/preemption attributes
        — per-request spans and ``/debug/requests/<id>`` agree by
        construction. ``_serve`` stashes its primary Request on the aiohttp
        request so this wrapper (and ``_sse_response``) can reach it."""
        parent = otel.extract_traceparent(dict(request.headers))
        with otel.use_parent(parent):
            with otel.get_tracer("engine").span(
                    "engine:completion",
                    attributes={"http.path": str(request.path)}) as span:
                try:
                    return await self._serve(request, body, prompt_ids, chat,
                                             tools, json_mode, grammar,
                                             grammar_prefix)
                finally:
                    req = request.get("engine_request")
                    if req is not None and otel.tracing_enabled():
                        for key, value in flight_mod.timeline_attributes(
                                req).items():
                            span.set_attribute(key, value)

    async def _serve(self, request: web.Request, body: Dict[str, Any],
                     prompt_ids, chat: bool,
                     tools: Optional[List[Dict[str, Any]]] = None,
                     json_mode: bool = False,
                     grammar: Optional[object] = None,
                     grammar_prefix: str = "") -> web.StreamResponse:
        sampling = self._parse_sampling(body)
        n = max(1, min(int(body.get("n") or 1), 4))
        if n > 1 and (tools or json_mode):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "n > 1 is not supported with tools or "
                          "response_format"}))

        adapter = self._adapter_for(body)
        # responses echo the REQUESTED model id (adapter traffic must not
        # be attributed to the base model by client-side accounting)
        model = adapter or self.model_name
        slo_fields = self._parse_slo(request)
        tenant = usage_mod.tenant_from_headers(request.headers)

        rid_in = inbound_request_id(request.headers)

        def make_req(i: int) -> Request:
            kw = dict(sampling)
            if i and kw["seed"] is not None:
                kw["seed"] = kw["seed"] + i   # distinct, still reproducible
            if rid_in:
                # the router's id becomes THIS worker's request id, so the
                # /debug/requests timelines of every worker that touched
                # the request share one key; secondary n>1 choices get a
                # derived suffix (ids must stay log-unique per process)
                kw["request_id"] = rid_in if i == 0 else f"{rid_in}.{i}"
            return Request(prompt_ids=list(prompt_ids), grammar=grammar,
                           grammar_prefix=grammar_prefix, adapter=adapter,
                           tenant=tenant, **slo_fields, **kw)

        reqs = [make_req(i) for i in range(n)]
        req = reqs[0]
        # the scheduler id is the /debug/requests/<id> lookup key; expose it
        # on every response as X-Request-Id (span envelope reads it too)
        request["engine_request"] = req
        # fleet prefix-tier identity (engine/kv_tier.py): the opening-page
        # chain hash rides the response as X-KV-Prefix so the router can
        # learn which token-hash prefix this conversation maps to and
        # route its next turn to a replica advertising it ("" = tier off)
        pk = getattr(self.scheduler, "prefix_key_hex", None)
        request["kv_prefix_h0"] = pk(prompt_ids, adapter) if pk else ""
        rid = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        stream = bool(body.get("stream", False))
        for r in reqs:
            self.scheduler.submit(r)
        drain = StreamDrain(self.scheduler.iter_text(req))

        if stream and tools and not json_mode:
            # OpenAI-semantics incremental tool_calls deltas: commit to a
            # call as soon as the envelope prefix parses, then stream the
            # argument text in fragments (tools_mod.ToolCallStreamer) —
            # long argument generations no longer sit silent
            return await self._stream_tools(request, rid, req, drain, tools,
                                            model)
        if stream and json_mode and grammar is not None and not tools:
            # the token-level grammar GUARANTEES valid JSON, so json-mode
            # output can stream as plain content deltas — but only when
            # the grammar actually ATTACHED (slots can be pinned at
            # admission); _stream_json peeks the first delta, checks
            # req.grammar_attached, and falls back to the buffered
            # extract path when enforcement degraded
            return await self._stream_json(request, rid, req, drain, model)
        if not stream or tools or json_mode:
            # JSON-mode requests WITHOUT a grammar (and non-streamed
            # tools) still buffer: the extracted JSON value is rewritten
            # canonically, so the output shape isn't known until the
            # generation parses
            text = await drain.join_text()
            if req.error:
                if not stream:
                    raise web.HTTPServiceUnavailable(
                        text=json.dumps({"error": req.error}))
                return await self._stream_error(request, rid, req.error, model)
            tool_calls = (tools_mod.parse_tool_calls(text, tools)
                          if tools else None)
            if json_mode and not tool_calls:
                found = tools_mod.extract_json_value(text)
                if found is not None:
                    text = json.dumps(found[0])
            finish = "tool_calls" if tool_calls else _finish_reason(req)
            message: Dict[str, Any] = {"role": "assistant",
                                       "content": None if tool_calls else text}
            if tool_calls:
                message["tool_calls"] = tool_calls
            if stream:
                return await self._stream_buffered(request, rid, message,
                                                   finish, model)
            texts = [text] + [
                await StreamDrain(self.scheduler.iter_text(r)).join_text()
                for r in reqs[1:]]
            choices: List[Dict[str, Any]] = []
            for i, (r, t) in enumerate(zip(reqs, texts)):
                # a secondary choice's engine failure must not pass off its
                # truncated text as a clean stop
                fin = _finish_reason(r, finish if i == 0 else "stop")
                choice: Dict[str, Any] = {"index": i, "finish_reason": fin}
                msg = message if i == 0 else {"role": "assistant",
                                              "content": t}
                if chat:
                    choice["message"] = msg
                else:
                    choice["text"] = t if i else text
                if r.logprobs:
                    choice["logprobs"] = self._format_logprobs(r)
                choices.append(choice)
            done_toks = sum(r.completion_tokens for r in reqs)
            payload = {
                "id": rid, "object": "chat.completion" if chat else "text_completion",
                "created": int(time.time()), "model": model,
                "choices": choices,
                "usage": {"prompt_tokens": len(prompt_ids),
                          "completion_tokens": done_toks,
                          "total_tokens": len(prompt_ids) + done_toks},
            }
            errs = [r.error for r in reqs if r.error]
            if errs:
                payload["error"] = "; ".join(errs)
            headers = {"X-Request-Id": req.request_id}
            if request.get("kv_prefix_h0"):
                headers["X-KV-Prefix"] = request["kv_prefix_h0"]
            return web.json_response(payload, headers=headers)

        resp = await self._sse_response(request)
        if chat:
            for i in range(n):
                await sse_write(resp, _chunk(model, rid,
                                             {"role": "assistant"}, index=i))
        if n == 1:
            async for delta in drain:
                await sse_write(resp, _chunk(model, rid,
                                             {"content": delta}))
        else:
            # n-way merged stream: one pump per choice, deltas interleave
            # with their choice index (the OpenAI multi-choice contract)
            import asyncio
            q: "asyncio.Queue" = asyncio.Queue()
            drains = [drain] + [StreamDrain(self.scheduler.iter_text(r))
                                for r in reqs[1:]]

            async def pump(i: int, d: StreamDrain) -> None:
                async for delta in d:
                    await q.put((i, delta))
                await q.put((i, None))

            tasks = [asyncio.ensure_future(pump(i, d))
                     for i, d in enumerate(drains)]
            live = n
            while live:
                i, delta = await q.get()
                if delta is None:
                    live -= 1
                    continue
                await sse_write(resp, _chunk(model, rid,
                                             {"content": delta}, index=i))
            for t in tasks:
                t.cancel()
        # an engine failure mid-stream must not masquerade as a clean stop;
        # the error rides inside a schema-shaped chunk so conforming clients
        # (chunk["choices"][0]) keep parsing
        for i, r in enumerate(reqs):
            finish = _finish_reason(r)
            lps = self._format_logprobs(r) if r.logprobs else None
            final = json.loads(_chunk(model, rid, {}, finish,
                                      index=i, logprobs=lps))
            if r.error:
                final["error"] = r.error
            await sse_write(resp, json.dumps(final))
        await sse_done(resp)
        return resp

    async def _stream_json(self, request: web.Request, rid: str, req,
                           drain: StreamDrain,
                           model: str) -> web.StreamResponse:
        """Stream a grammar-constrained JSON-mode generation. Enforcement
        can degrade at admission (all GRAM_SLOTS pinned, schema rejected at
        registration) — the scheduler records the decision on
        Request.grammar_attached by the time the first token exists, so
        peek one delta, then either stream plain content deltas (grammar
        active: validity is token-level guaranteed) or fall back to the
        buffered extract-and-rewrite path clients were promised."""
        # headers + role chunk go out BEFORE the first-token wait so
        # client/proxy response timeouts see bytes during long prefills
        resp = await self._sse_response(request)
        await sse_write(resp, _chunk(model, rid,
                                     {"role": "assistant"}))
        it = drain.__aiter__()
        try:
            first = await it.__anext__()
        except StopAsyncIteration:
            first = None
        error: Optional[str] = None
        if req.grammar_attached and first is not None and not req.error:
            await sse_write(resp, _chunk(model, rid,
                                         {"content": first}))
            async for delta in it:
                if req.grammar_attached is False:
                    # a preemption resume failed to re-attach the grammar
                    # (slots pinned): everything from here is unconstrained
                    # — stop emitting rather than pass it off as
                    # token-level guaranteed; keep draining so the job
                    # finishes cleanly
                    error = ("constrained decoding lost on preemption "
                             "resume; retry the request")
                    continue
                await sse_write(resp, _chunk(model, rid,
                                             {"content": delta}))
        else:
            parts = [] if first is None else [first]
            async for delta in it:
                parts.append(delta)
            if not req.error:
                text = "".join(parts)
                # a failover continuation's client already holds the
                # stream prefix — rewriting the suffix alone would corrupt
                # the composed document, so only standalone generations
                # get the canonical extract-and-rewrite
                if not req.grammar_prefix:
                    found = tools_mod.extract_json_value(text)
                    if found is not None:
                        text = json.dumps(found[0])
                await sse_write(resp, _chunk(model, rid,
                                             {"content": text}))
        error = req.error or error
        finish = "error" if error else _finish_reason(req)
        final = json.loads(_chunk(model, rid, {}, finish))
        if error:
            final["error"] = error
        await sse_write(resp, json.dumps(final))
        await sse_done(resp)
        return resp

    async def _stream_tools(self, request: web.Request, rid: str, req,
                            drain: StreamDrain,
                            tools: List[Dict[str, Any]],
                            model: str) -> web.StreamResponse:
        resp = await self._sse_response(request)
        await sse_write(resp, _chunk(model, rid,
                                     {"role": "assistant"}))
        streamer = tools_mod.ToolCallStreamer(tools)

        async def emit(events) -> None:
            for ev in events:
                if ev[0] == "content":
                    delta: Dict[str, Any] = {"content": ev[1]}
                elif ev[0] == "tool_start":
                    delta = {"tool_calls": [{
                        "index": ev[1], "id": f"call_{uuid.uuid4().hex[:12]}",
                        "type": "function",
                        "function": {"name": ev[2], "arguments": ""}}]}
                else:   # tool_args
                    delta = {"tool_calls": [{
                        "index": ev[1], "function": {"arguments": ev[2]}}]}
                await sse_write(resp, _chunk(model, rid, delta))

        async for text in drain:
            await emit(streamer.feed(text))
        await emit(streamer.finish())
        finish = ("error" if req.error
                  else "tool_calls" if streamer.committed
                  else _finish_reason(req))
        final = json.loads(_chunk(model, rid, {}, finish))
        if req.error:
            final["error"] = req.error
        await sse_write(resp, json.dumps(final))
        await sse_done(resp)
        return resp

    @staticmethod
    async def _sse_response(request: web.Request) -> web.StreamResponse:
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
        }
        req = request.get("engine_request")
        if req is not None:
            headers["X-Request-Id"] = req.request_id
        if request.get("kv_prefix_h0"):
            # the router learns conversation -> prefix-hash from this
            # (server/failover.py promote routing, engine/kv_tier.py)
            headers["X-KV-Prefix"] = request["kv_prefix_h0"]
        resp = web.StreamResponse(headers=headers)
        await resp.prepare(request)
        return resp

    async def _stream_buffered(self, request: web.Request, rid: str,
                               message: Dict[str, Any],
                               finish: str, model: str) -> web.StreamResponse:
        """Replay a buffered tool/JSON result as a conforming SSE stream:
        role chunk, one delta carrying the whole content / tool_calls
        (OpenAI clients accumulate deltas, so a single full delta decodes
        identically), then the finish chunk."""
        resp = await self._sse_response(request)
        await sse_write(resp, _chunk(model, rid, {"role": "assistant"}))
        delta: Dict[str, Any] = {}
        if message.get("tool_calls"):
            delta["tool_calls"] = [
                {"index": i, **call}
                for i, call in enumerate(message["tool_calls"])]
        else:
            delta["content"] = message.get("content") or ""
        await sse_write(resp, _chunk(model, rid, delta))
        await sse_write(resp, _chunk(model, rid, {}, finish))
        await sse_done(resp)
        return resp

    async def _stream_error(self, request: web.Request, rid: str,
                            error: str, model: str) -> web.StreamResponse:
        resp = await self._sse_response(request)
        final = json.loads(_chunk(model, rid, {}, "error"))
        final["error"] = error
        await sse_write(resp, json.dumps(final))
        await sse_done(resp)
        return resp


def install_sigterm_drain(scheduler: Scheduler,
                          watchdog: Optional[EngineWatchdog],
                          grace_s: Optional[float] = None,
                          exit_fn=None):
    """SIGTERM → graceful drain + evacuation (the k8s/ supervisor
    rotation path — before this, only SIGUSR1's flight dump was
    installed and a TERM killed every live stream mid-token). The
    handler flags the drain (health 503 → router routes away), queues a
    NON-blocking full evacuation (the driver exports every live slot;
    streams end "evacuated" and the router pulls their snapshots from
    /v1/kv/evacuation while this process keeps serving HTTP), then exits
    after ``APP_DRAIN_GRACE_S`` (default 10 s) — long enough for the
    pulls, bounded so a rotation never hangs. Returns the handler (tests
    drive it directly; ``exit_fn`` injects the exit)."""
    import signal
    import threading as _threading

    if grace_s is None:
        try:
            grace_s = float(os.environ.get("APP_DRAIN_GRACE_S", "") or 10.0)
        except ValueError:
            grace_s = 10.0
    exit_fn = exit_fn if exit_fn is not None else (lambda: os._exit(0))
    log = logging.getLogger(__name__)
    fired = {"done": False}

    def _handler(signum, frame):   # pragma: no cover - exercised via tests calling it directly
        if fired["done"]:
            return   # a second TERM during the grace window is a no-op
        fired["done"] = True
        log.warning("SIGTERM: draining (+evacuating live streams); "
                    "exiting in %.1fs", grace_s)
        if watchdog is not None:
            watchdog.drain()
        if hasattr(scheduler, "request_evacuation"):
            # non-blocking: the handler runs on the event-loop thread —
            # blocking here would stall exactly the HTTP serving the
            # router needs to PULL the snapshots
            scheduler.request_evacuation(wait_s=0.0, reason="sigterm")

        def _exit_after_grace():
            time.sleep(grace_s)
            log.warning("drain grace elapsed; exiting")
            # os._exit skips atexit: push the event-trace tail to its
            # file sink first, or a rotation loses the last <128 records
            try:
                from generativeaiexamples_tpu.observability.trace import (
                    TRACE)
                TRACE.flush()
            except Exception:   # tpulint: disable=except-swallow -- a failed best-effort flush must not block the drain exit; the write-error counter inside _write already accounts sink failures
                pass
            exit_fn()

        _threading.Thread(target=_exit_after_grace, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        # not the main thread (embedded servers, tests): the caller can
        # still invoke the returned handler explicitly
        log.debug("not on the main thread; SIGTERM handler not installed")
    return _handler


def run_server(scheduler: Scheduler, model_name: str, host: str = "0.0.0.0",
               port: int = 8000) -> None:
    from generativeaiexamples_tpu.observability.bootstrap import (
        init_observability)
    init_observability("engine")
    watchdog = None
    if watchdog_enabled():
        watchdog = EngineWatchdog(scheduler)
        watchdog.start()
    server = ModelServer(scheduler, model_name, watchdog=watchdog)
    scheduler.start()
    # graceful rotation: SIGTERM drains + evacuates instead of killing
    # live streams (SIGUSR1's flight dump is installed by
    # init_observability above)
    install_sigterm_drain(scheduler, watchdog)
    web.run_app(server.app, host=host, port=port, print=None,
                handle_signals=False)
