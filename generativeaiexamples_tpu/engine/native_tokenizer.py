"""ctypes bindings for the native byte-level BPE core (native/bpe_tokenizer.cpp).

The reference keeps tokenization in the Rust `tokenizers` runtime inside its
model containers; this is the in-tree native equivalent for the serving and
ingest hot paths (prompt encode, document tokenization for the splitter).

Split of labor: Python does everything cold — parse ``tokenizer.json``,
invert the GPT-2 byte<->unicode alphabet so the C++ side sees raw bytes,
resolve merge rules to id triples, build \\p{L} / \\p{N} bitsets from
unicodedata, handle added special tokens — and C++ does everything hot
(UTF-8 scan, GPT-2 pre-tokenization, the BPE merge loop).

`NativeBPETokenizer` implements the same `Tokenizer` protocol as
`HFTokenizer` (engine/tokenizer.py) and is preferred by `get_tokenizer`
when the shared library is available; everything degrades to the Python
path when the toolchain or the JSON shape doesn't cooperate.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import re
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libgenx_native.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "bpe_tokenizer.cpp")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_MAX_CP = 0x110000
_BITS_LEN = _MAX_CP // 8


def _build_lib() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # compile to a per-process temp path and rename into place: concurrent
    # first-use builds (multiple server/ingest processes) must never dlopen
    # a half-linked .so from a shared output path
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC_PATH,
           "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as exc:
        logger.warning("native tokenizer build failed to run: %s", exc)
        return False
    if proc.returncode != 0:
        logger.warning("native tokenizer build failed:\n%s", proc.stderr)
        return False
    try:
        os.replace(tmp, _LIB_PATH)
    except OSError as exc:
        logger.warning("native tokenizer install failed: %s", exc)
        return False
    return True


def load_native_lib() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native library; None = unavailable."""
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        stale = (not os.path.exists(_LIB_PATH) or
                 (os.path.exists(_SRC_PATH) and
                  os.path.getmtime(_SRC_PATH) > os.path.getmtime(_LIB_PATH)))
        # tpulint: disable=deep-lock -- one-time init: concurrent first
        # users must WAIT for the single build (then dlopen the result),
        # not race a second g++ against a half-linked .so
        if stale and not _build_lib():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as exc:
            logger.warning("native tokenizer load failed: %s", exc)
            _lib_failed = True
            return None
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_create.argtypes = [
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32, ctypes.c_int32]
        lib.bpe_free.restype = None
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_encode.restype = ctypes.c_int32
        lib.bpe_encode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.bpe_decode.restype = ctypes.c_int32
        lib.bpe_decode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32]
        _lib = lib
        return _lib


def _bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 byte → printable-codepoint alphabet (every byte-level BPE
    vocab is written in it)."""
    bs = (list(range(ord("!"), ord("~") + 1)) +
          list(range(ord("¡"), ord("¬") + 1)) +
          list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


_B2U = _bytes_to_unicode()
_U2B = {u: b for b, u in _B2U.items()}


def _token_to_bytes(token: str) -> Optional[bytes]:
    """Vocab entry (byte-alphabet domain) → raw bytes; None if it contains
    characters outside the alphabet (e.g. an added special in the vocab)."""
    out = bytearray()
    for ch in token:
        b = _U2B.get(ch)
        if b is None:
            return None
        out.append(b)
    return bytes(out)


# The two pre-tokenization patterns the native scanner implements. Anything
# else must raise so get_tokenizer falls back to the Python path — silently
# applying the wrong split would encode ids the model was never trained on.
_GPT2_MODE, _LLAMA3_MODE = 0, 1
_LLAMA3_PATTERN = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+"
                   r"|\p{N}{1,3}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+"
                   r"|\s+(?!\S)|\s+")
_GPT2_PATTERN = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
                 r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")


def _normalizer_is_noop(norm: dict) -> bool:
    """True only for normalizer configs that provably change nothing: an
    empty Sequence, or a Sequence of empty Sequences. Real normalizers
    (NFC/NFD/Replace/...) must make the caller raise so get_tokenizer falls
    back to HFTokenizer, which applies them."""
    if norm.get("type") == "Sequence":
        return all(_normalizer_is_noop(n)
                   for n in norm.get("normalizers", []) or [])
    return False


def _detect_pre_tokenizer(pre: dict) -> tuple:
    """Map a tokenizer.json pre_tokenizer config onto a native scanner mode.

    Supported shapes:
      * ByteLevel with its built-in regex (use_regex != false) → GPT-2 mode;
      * Sequence([Split(known pattern), ByteLevel(use_regex=false)]) →
        the pattern decides (Llama-3 checkpoints ship exactly this shape).
    Returns (mode, add_prefix_space); raises ValueError otherwise.
    """
    pres = (pre.get("pretokenizers", []) if pre.get("type") == "Sequence"
            else [pre])
    byte_levels = [p for p in pres if p.get("type") == "ByteLevel"]
    if not byte_levels:
        raise ValueError("only ByteLevel pre-tokenization is supported")
    aps = bool(byte_levels[0].get("add_prefix_space", False))
    splits = [p for p in pres if p.get("type") == "Split"]
    others = [p for p in pres if p.get("type") not in ("ByteLevel", "Split")]
    if others:
        raise ValueError(
            f"unsupported pre-tokenizers: {[p.get('type') for p in others]}")
    if splits:
        if len(splits) > 1 or byte_levels[0].get("use_regex", True):
            raise ValueError("unsupported Split/ByteLevel combination")
        pattern = splits[0].get("pattern", {})
        pattern = pattern.get("Regex") if isinstance(pattern, dict) else None
        if pattern == _LLAMA3_PATTERN:
            return _LLAMA3_MODE, aps
        if pattern == _GPT2_PATTERN:
            return _GPT2_MODE, aps
        raise ValueError(f"unrecognized split pattern {pattern!r}")
    if byte_levels[0].get("use_regex", True) is False:
        raise ValueError("ByteLevel without a split regex is unsupported")
    return _GPT2_MODE, aps


_bitsets_cache: Optional[tuple] = None


def _unicode_bitsets() -> tuple:
    """(letter_bits, number_bits) — 1 bit per codepoint, \\p{L} and \\p{N}
    per unicodedata. Built once per process (~1 s), cached to disk beside
    the shared library so later processes mmap-read it."""
    global _bitsets_cache
    if _bitsets_cache is not None:
        return _bitsets_cache
    import unicodedata
    cache = os.path.join(
        _BUILD_DIR, f"unicode_bits_{unicodedata.unidata_version}.bin")
    if os.path.exists(cache):
        with open(cache, "rb") as fh:
            blob = fh.read()
        if len(blob) == 2 * _BITS_LEN:
            _bitsets_cache = (blob[:_BITS_LEN], blob[_BITS_LEN:])
            return _bitsets_cache
    letters = bytearray(_BITS_LEN)
    numbers = bytearray(_BITS_LEN)
    for cp in range(_MAX_CP):
        cat = unicodedata.category(chr(cp))
        if cat[0] == "L":
            letters[cp >> 3] |= 1 << (cp & 7)
        elif cat[0] == "N":
            numbers[cp >> 3] |= 1 << (cp & 7)
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        with open(cache, "wb") as fh:
            fh.write(bytes(letters) + bytes(numbers))
    except OSError:
        pass
    _bitsets_cache = (bytes(letters), bytes(numbers))
    return _bitsets_cache


class NativeBPETokenizer:
    """Byte-level BPE over the native core; `Tokenizer` protocol."""

    def __init__(self, path: str) -> None:
        lib = load_native_lib()
        if lib is None:
            raise RuntimeError("native tokenizer library unavailable")
        self._lib = lib
        with open(path, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
        model = spec.get("model", {})
        if model.get("type") != "BPE":
            raise ValueError(f"unsupported tokenizer model {model.get('type')!r}")
        norm = spec.get("normalizer")
        if norm is not None and not _normalizer_is_noop(norm):
            # Qwen-style configs pair ByteLevel BPE with an NFC normalizer;
            # encoding without it would silently diverge from HF ids, so
            # refuse and let get_tokenizer fall back to HFTokenizer
            raise ValueError(
                f"unsupported normalizer {norm.get('type')!r}")
        pre = spec.get("pre_tokenizer") or {}
        self._mode, self._add_prefix_space = _detect_pre_tokenizer(pre)

        vocab: Dict[str, int] = model["vocab"]
        self.vocab_size = max(vocab.values()) + 1

        # added/special tokens: handled Python-side (split before encode,
        # skipped in decode)
        self._special_ids: Dict[str, int] = {}
        for tok in spec.get("added_tokens", []):
            self._special_ids[tok["content"]] = tok["id"]
            self.vocab_size = max(self.vocab_size, tok["id"] + 1)
        self._id_is_special = set(self._special_ids.values())
        self._special_re = (re.compile("|".join(
            re.escape(s) for s in sorted(self._special_ids, key=len,
                                         reverse=True)))
            if self._special_ids else None)

        self.bos_id = self._pick("<|begin_of_text|>", "<s>", "<bos>",
                                 "<|endoftext|>")
        self.eos_id = self._pick("<|eot_id|>", "</s>", "<eos>",
                                 "<|end_of_text|>", "<|endoftext|>")
        self.pad_id = self.eos_id

        # --- flatten vocab to raw-byte strings for the native core -------
        tok_bytes = [b""] * self.vocab_size
        for tok, tid in vocab.items():
            raw = _token_to_bytes(tok)
            if raw is not None:
                tok_bytes[tid] = raw
        lens = (ctypes.c_int32 * self.vocab_size)(
            *(len(b) for b in tok_bytes))
        blob = b"".join(tok_bytes)
        blob_arr = (ctypes.c_uint8 * max(len(blob), 1)).from_buffer_copy(
            blob or b"\0")

        # --- merges resolved to id triples --------------------------------
        merges = model.get("merges", [])
        keys, merged = [], []
        for rule in merges:
            a, b = rule.split(" ", 1) if isinstance(rule, str) else rule
            ia, ib, iab = vocab.get(a), vocab.get(b), vocab.get(a + b)
            if ia is None or ib is None or iab is None:
                continue
            keys.append((ia & 0xFFFFFFFF) << 32 | (ib & 0xFFFFFFFF))
            merged.append(iab)
        n_merges = len(keys)
        keys_arr = (ctypes.c_uint64 * max(n_merges, 1))(*(keys or [0]))
        merged_arr = (ctypes.c_int32 * max(n_merges, 1))(*(merged or [0]))

        # --- initial id per byte ------------------------------------------
        byte_init = []
        for b in range(256):
            tid = vocab.get(_B2U[b])
            if tid is None:
                raise ValueError(f"vocab lacks single-byte token for {b:#x}")
            byte_init.append(tid)
        init_arr = (ctypes.c_int32 * 256)(*byte_init)

        letters, numbers = _unicode_bitsets()
        lbits = (ctypes.c_uint8 * _BITS_LEN).from_buffer_copy(letters)
        nbits = (ctypes.c_uint8 * _BITS_LEN).from_buffer_copy(numbers)

        self._handle = lib.bpe_create(
            self.vocab_size, lens, blob_arr, n_merges, keys_arr, merged_arr,
            init_arr, lbits, nbits, _BITS_LEN, self._mode)
        if not self._handle:
            raise RuntimeError("bpe_create failed")

    def _pick(self, *names: str) -> int:
        for n in names:
            if n in self._special_ids:
                return self._special_ids[n]
        return 0

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.bpe_free(handle)
            self._handle = None

    # ------------------------------------------------------------- protocol

    def _encode_plain(self, text: str) -> List[int]:
        if not text:
            return []
        if self._add_prefix_space and not text.startswith(" "):
            text = " " + text
        data = text.encode("utf-8")
        cap = len(data) + 8
        out = (ctypes.c_int32 * cap)()
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        n = self._lib.bpe_encode(self._handle, buf, len(data), out, cap)
        if n > cap:   # can't happen (ids <= bytes) but honor the contract
            out = (ctypes.c_int32 * n)()
            n = self._lib.bpe_encode(self._handle, buf, len(data), out, n)
        return list(out[:n])

    def encode(self, text: str, add_bos: bool = False) -> List[int]:
        ids: List[int] = [self.bos_id] if add_bos else []
        if self._special_re is None:
            ids += self._encode_plain(text)
            return ids
        pos = 0
        for m in self._special_re.finditer(text):
            ids += self._encode_plain(text[pos:m.start()])
            ids.append(self._special_ids[m.group()])
            pos = m.end()
        ids += self._encode_plain(text[pos:])
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        plain = [i for i in ids if i not in self._id_is_special]
        if not plain:
            return ""
        arr = (ctypes.c_int32 * len(plain))(*plain)
        cap = 8 * len(plain)
        out = (ctypes.c_uint8 * cap)()
        n = self._lib.bpe_decode(self._handle, arr, len(plain), out, cap)
        if n > cap:
            out = (ctypes.c_uint8 * n)()
            n = self._lib.bpe_decode(self._handle, arr, len(plain), out, n)
        return bytes(out[:n]).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: Sequence[dict]) -> List[int]:
        # Llama-3 instruct convention (mirrors HFTokenizer)
        ids: List[int] = [self.bos_id]
        for m in messages:
            ids += self.encode(f"<|start_header_id|>{m.get('role', 'user')}"
                               f"<|end_header_id|>\n\n{m.get('content', '')}"
                               f"<|eot_id|>")
        ids += self.encode("<|start_header_id|>assistant<|end_header_id|>\n\n")
        return ids
