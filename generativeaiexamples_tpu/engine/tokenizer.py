"""Tokenizers for the serving engine.

The reference delegates tokenization to the model containers; in-tree we need
one. Two implementations behind one protocol:

  * `ByteTokenizer` — UTF-8 bytes + special tokens. Zero-dependency,
    deterministic, used by tests and the fake tiny model (the "fake inference
    backend" SURVEY §4 calls for).
  * `HFTokenizer`  — wraps a local `tokenizers` JSON file (Llama-3/Gemma
    vocabularies) when a checkpoint directory provides one. No network.

Chat formatting follows the Llama-3 instruct convention (header/eot special
tokens); the byte tokenizer uses readable tag strings so tests can assert on
the rendered prompt.
"""

from __future__ import annotations

import os
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, add_bos: bool = False) -> List[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat_template(self, messages: Sequence[dict]) -> List[int]: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are bytes; specials appended after."""

    def __init__(self) -> None:
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 259

    def encode(self, text: str, add_bos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: Sequence[dict]) -> List[int]:
        parts = []
        for m in messages:
            parts.append(f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}\n")
        parts.append("<|assistant|>\n")
        return self.encode("".join(parts), add_bos=True)


class HFTokenizer:
    """Wrapper over a local HuggingFace `tokenizers` JSON file."""

    def __init__(self, path: str) -> None:
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = self._special("<|begin_of_text|>", "<s>", "<bos>")
        self.eos_id = self._special("<|eot_id|>", "</s>", "<eos>", "<|end_of_text|>")
        self.pad_id = self.eos_id

    def _special(self, *names: str) -> int:
        vocab = self._tok.get_vocab()
        for n in names:
            if n in vocab:
                return vocab[n]
        return 0

    def encode(self, text: str, add_bos: bool = False) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat_template(self, messages: Sequence[dict]) -> List[int]:
        # Llama-3 instruct convention: header tokens around each role block.
        ids: List[int] = [self.bos_id]
        for m in messages:
            ids += self.encode(f"<|start_header_id|>{m.get('role', 'user')}"
                               f"<|end_header_id|>\n\n{m.get('content', '')}<|eot_id|>")
        ids += self.encode("<|start_header_id|>assistant<|end_header_id|>\n\n")
        return ids


class IncrementalDetokenizer:
    """Streaming detokenizer: feed ids, get printable text deltas.

    Holds back trailing bytes that form an incomplete UTF-8 sequence so SSE
    chunks never contain replacement characters mid-codepoint (the per-token
    stream hot loop, ref server.py:350-376 semantics).

    Runs on the single engine-driver thread, so per-push work must stay O(1):
    only the ids since the last *clean* decode are re-decoded. The pending
    buffer resets every time the decoded text ends on a codepoint boundary —
    which is nearly every token — so it never grows past a few ids in
    practice (a codepoint/BPE piece spans a handful of tokens at most).
    """

    def __init__(self, tokenizer: Tokenizer) -> None:
        self._tok = tokenizer
        self._pending: List[int] = []
        self._pending_emitted = 0  # chars of decode(_pending) already streamed

    def push(self, token_id: int) -> str:
        self._pending.append(token_id)
        text = self._tok.decode(self._pending)
        safe = len(text)
        while safe > 0 and text[safe - 1] == "�":  # partial UTF-8 tail
            safe -= 1
        delta = text[self._pending_emitted:safe]
        if safe == len(text):
            self._pending = []
            self._pending_emitted = 0
        else:
            self._pending_emitted = safe
        return delta

    def flush(self) -> str:
        text = self._tok.decode(self._pending)
        delta = text[self._pending_emitted:]
        self._pending = []
        self._pending_emitted = 0
        return delta


def get_tokenizer(checkpoint_dir: str = "") -> Tokenizer:
    """Native BPE core if it builds for this vocab, else the HF wrapper,
    else the byte fallback (engine/native_tokenizer.py for the split)."""
    if checkpoint_dir:
        p = os.path.join(checkpoint_dir, "tokenizer.json")
        if os.path.exists(p):
            try:
                from generativeaiexamples_tpu.engine.native_tokenizer import (
                    NativeBPETokenizer)
                return NativeBPETokenizer(p)
            except Exception as exc:  # unsupported shape / no toolchain
                import logging
                logging.getLogger(__name__).info(
                    "native tokenizer unavailable (%s); using Python path",
                    exc)
            return HFTokenizer(p)
        import logging
        logging.getLogger(__name__).warning(
            "checkpoint dir %s has no tokenizer.json — falling back to the "
            "259-id byte tokenizer, which will garble a real vocabulary",
            checkpoint_dir)
    return ByteTokenizer()
