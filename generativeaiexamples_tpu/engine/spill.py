"""Compatibility shim — the spill pool moved to ``engine/kv_tier.py``.

PR 14's bounded host-RAM spill pool (request-keyed) grew into the
prefix-addressed KV tier (ROADMAP item 2): ``kv_tier.KVSpillPool`` is
the identical request-keyed pool (``APP_KV_TIER=off``, the default),
``kv_tier.PrefixKVTier`` is the prefix-hash-keyed, refcounted,
value-priced store layered on top of it. This module keeps the old
import path alive for external callers; new code imports from
``generativeaiexamples_tpu.engine.kv_tier`` directly.
"""

from __future__ import annotations

from generativeaiexamples_tpu.engine.kv_tier import (  # noqa: F401
    KVSpillPool,
    PrefixKVTier,
    payload_nbytes,
    spill_budget_bytes,
    tier_disk_bytes,
    tier_mode,
)

__all__ = [
    "KVSpillPool",
    "PrefixKVTier",
    "payload_nbytes",
    "spill_budget_bytes",
    "tier_disk_bytes",
    "tier_mode",
]
