"""Bounded host-RAM spill pool for preempted KV pages (ROADMAP item 3).

Page-exhaust preemption used to be recompute-style: free the victim's
pages, re-queue prompt + generated tokens, and re-prefill the whole
context when pages free again. With a spill pool armed
(``APP_KV_SPILL_MB`` / ``EngineConfig.kv_spill_mb``), the scheduler
instead demotes the victim slot's live pages to host RAM (one
device→host transfer via ``export_slot_kv(fetch=True)``) and promotes
them back with ``import_slot_kv`` at re-admission — zero prefill
programs, token-identical by construction (the snapshot carries the
sampling seed + position, and the per-position ``fold_in`` keys make
resumed decode bit-equal to uninterrupted decode).

This pool is the accounting half: a byte-budgeted registry of spilled
payloads keyed by request id. The payload arrays themselves ride the
``_Job`` (the scheduler owns their lifecycle); the pool guarantees the
aggregate host footprint stays under the operator's bound — when it
would not, the preemption falls back to the recompute path, loudly
counted (``kv_spill_total{outcome="over_budget"}``). The live footprint
is the ``kv_spill_bytes`` gauge.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from generativeaiexamples_tpu.core.metrics import REGISTRY


def payload_nbytes(payload: Dict[str, Any]) -> int:
    """Host bytes a spilled handoff payload occupies (array segments;
    scalar passthrough is noise next to the KV pages)."""
    total = 0
    for key in ("k", "v", "k_s", "v_s"):
        arr = payload.get(key)
        if arr is not None:
            total += int(getattr(arr, "nbytes", 0))
    return total


def spill_budget_bytes(cfg: Any = None) -> int:
    """Resolve the spill budget: the bare env ``APP_KV_SPILL_MB`` wins
    (the knob the issue/docs name), else ``EngineConfig.kv_spill_mb``,
    else 0 (spill off — preemption recomputes, the pre-r07 behavior)."""
    raw = os.environ.get("APP_KV_SPILL_MB", "").strip()
    if raw:
        try:
            return max(0, int(float(raw))) * (1 << 20)
        except ValueError:
            pass
    mb = int(getattr(cfg, "kv_spill_mb", 0) or 0)
    return max(0, mb) * (1 << 20)


class KVSpillPool:
    """Byte-budgeted registry of spilled KV payloads (one per request)."""

    def __init__(self, budget_bytes: int) -> None:
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._bytes: Dict[str, int] = {}
        self._used = 0

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def __len__(self) -> int:
        with self._lock:
            return len(self._bytes)

    def _gauge(self) -> None:
        REGISTRY.gauge("kv_spill_bytes").set(self._used)

    def admit(self, rid: str, payload: Dict[str, Any]) -> bool:
        """Charge ``payload``'s bytes to the pool. False = over budget
        (the caller must take the recompute path instead)."""
        n = payload_nbytes(payload)
        with self._lock:
            if rid in self._bytes:
                # a re-spill of the same request replaces its charge
                self._used -= self._bytes.pop(rid)
            if self._used + n > self.budget_bytes:
                self._gauge()
                REGISTRY.counter("kv_spill_total",
                                 labels={"outcome": "over_budget"}).inc()
                return False
            self._bytes[rid] = n
            self._used += n
            self._gauge()
        REGISTRY.counter("kv_spill_total",
                         labels={"outcome": "spilled"}).inc()
        return True

    def release(self, rid: str, outcome: str = "promoted") -> Optional[int]:
        """Return a request's bytes to the budget (promotion back
        on-device, or the job dying while spilled). None = not held."""
        with self._lock:
            n = self._bytes.pop(rid, None)
            if n is None:
                return None
            self._used -= n
            self._gauge()
        REGISTRY.counter("kv_spill_total", labels={"outcome": outcome}).inc()
        return n
