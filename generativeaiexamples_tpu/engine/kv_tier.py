"""Prefix-addressed host KV tier over the spill pool (ROADMAP item 2).

PR 14's ``KVSpillPool`` keys host-RAM KV by REQUEST id: a spilled
payload exists only for the request that exported it, and dies with it.
That makes returning conversations and fleet-shared system prompts pay
full prefill even when a byte-identical KV run sits in host RAM —
``prefix_hit_frac`` stalls at whatever the device cache alone covers.

``PrefixKVTier`` re-keys the same pool by the token-level page-chain
blake2b hashes the device prefix cache already computes
(engine/prefix_cache.chain_hashes — engine and tier agree on page
identity by construction):

  * a spilled request CONTRIBUTES its full-page prefix run under those
    hashes; the rid registry pins the entry while the spill is live;
  * when the rid releases (promotion, finish, death, evacuation) the
    entry is RETAINED as refcounted cache — that retention is the
    returning-conversation hit;
  * admission PROBES the tier for the longest cached prefix of every
    incoming prompt (deepest hash first) and promotes the covered run
    with a partial page import — zero prefill programs over the span,
    prefill only the tail;
  * eviction is value-priced, not refuse-at-budget: value ≈ recompute
    cost (core/perfmodel's prefill estimate, token count when the chip's
    peaks are unknown) × recency × hit history, biased so entries whose
    contributors had little SLO slack are kept longest, divided by the
    contributing tenant's QoS overuse (the PR 15 victim-picker doctrine:
    whoever floods the pool pays for the pressure). An entry with
    ``refs > 0`` (or a live rid pin) is NEVER evicted;
  * an optional disk tier (``APP_KV_TIER_DISK_MB``) demotes RAM-evicted
    entries to crc32-framed files (core/kv_wire.py — corruption is a
    loud decode error, never served KV) via an async write-behind
    thread; file I/O never runs under the tier lock and never on the
    driver thread.

``APP_KV_TIER=off`` (the default) keeps the plain ``KVSpillPool`` —
byte-identical PR 14 behavior, zero tier code on any hot path (the
APP_CHAOS/APP_DEVTIME/APP_QOS zero-overhead pattern, test-enforced).

The fleet loop: ``Scheduler.load_stats`` advertises the tier's top-K
hottest h₀ hashes + occupancy on ``/health``; the failover router
matches them against per-conversation hashes learned from the
``X-KV-Prefix`` response header and routes a prefix miss to the replica
that can PROMOTE instead of recompute
(``router_prefix_route_total{outcome="promote"}``).
"""

from __future__ import annotations

import atexit
import logging
import os
import queue
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.core import kv_wire
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability.lockwatch import tracked_lock

logger = logging.getLogger("generativeaiexamples_tpu.kv_tier")

# slack values are clamped here (mirrors engine/qos.py's cap): an
# undated request is "maximally slack", never infinitely valuable
_SLACK_CAP_S = 600.0


def payload_nbytes(payload: Dict[str, Any]) -> int:
    """Host bytes a spilled handoff payload occupies. Charges EVERY
    ndarray-valued segment — a payload that grows a new buffer (adapter
    state, draft caches) must never ride the budget for free — plus the
    packed token lists (``prompt_ids`` at 4 bytes/token, exactly the
    kv_wire frame footprint); the remaining scalar passthrough is noise
    next to the KV pages."""
    total = 0
    for key, value in payload.items():
        n = getattr(value, "nbytes", None)
        if n is not None:
            total += int(n)
        elif key == "prompt_ids" and value is not None:
            total += 4 * len(value)
    return total


def spill_budget_bytes(cfg: Any = None) -> int:
    """Resolve the spill budget: the bare env ``APP_KV_SPILL_MB`` wins
    (the knob the issue/docs name), else ``EngineConfig.kv_spill_mb``,
    else 0 (spill off — preemption recomputes, the pre-r07 behavior)."""
    raw = os.environ.get("APP_KV_SPILL_MB", "").strip()
    if raw:
        try:
            return max(0, int(float(raw))) * (1 << 20)
        except ValueError:
            pass
    mb = int(getattr(cfg, "kv_spill_mb", 0) or 0)
    return max(0, mb) * (1 << 20)


def tier_mode(cfg: Any = None) -> str:
    """``off`` (default — plain request-keyed spill pool) or ``prefix``
    (prefix-addressed tier). The bare env ``APP_KV_TIER`` wins over
    ``EngineConfig.kv_tier``; unknown values are loudly treated as off
    rather than silently arming a cache the operator did not name."""
    raw = os.environ.get("APP_KV_TIER", "").strip().lower()
    if not raw:
        raw = str(getattr(cfg, "kv_tier", "off") or "off").strip().lower()
    if raw in ("off", "prefix"):
        return raw
    logger.warning("APP_KV_TIER=%r is not off|prefix; tier stays off", raw)
    return "off"


def tier_disk_bytes(cfg: Any = None) -> int:
    """Disk-tier byte budget: bare env ``APP_KV_TIER_DISK_MB`` wins,
    else ``EngineConfig.kv_tier_disk_mb``, else 0 (no disk tier)."""
    raw = os.environ.get("APP_KV_TIER_DISK_MB", "").strip()
    if raw:
        try:
            return max(0, int(float(raw))) * (1 << 20)
        except ValueError:
            pass
    mb = int(getattr(cfg, "kv_tier_disk_mb", 0) or 0)
    return max(0, mb) * (1 << 20)


def tier_hot_k() -> int:
    """How many hottest prefix hashes ride each /health advert."""
    try:
        return max(0, int(os.environ.get("APP_KV_TIER_HOT_K", "") or 8))
    except ValueError:
        return 8


class KVSpillPool:
    """Byte-budgeted registry of spilled KV payloads (one per request).

    The PR 14 accounting pool, unchanged: ``APP_KV_TIER=off`` serves
    exactly this class. The payload arrays themselves ride the ``_Job``
    (the scheduler owns their lifecycle); the pool guarantees the
    aggregate host footprint stays under the operator's bound — when it
    would not, the preemption falls back to the recompute path, loudly
    counted (``kv_spill_total{outcome="over_budget"}``)."""

    def __init__(self, budget_bytes: int) -> None:
        self.budget_bytes = int(budget_bytes)
        self._lock = tracked_lock("kv_tier._lock")
        self._bytes: Dict[str, int] = {}
        self._used = 0

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def __len__(self) -> int:
        with self._lock:
            return len(self._bytes)

    def _gauge(self) -> None:
        REGISTRY.gauge("kv_spill_bytes").set(self._used)

    def admit(self, rid: str, payload: Dict[str, Any]) -> bool:
        """Charge ``payload``'s bytes to the pool. False = over budget
        (the caller must take the recompute path instead)."""
        n = payload_nbytes(payload)
        with self._lock:
            if rid in self._bytes:
                # a re-spill of the same request replaces its charge
                self._used -= self._bytes.pop(rid)
            if self._used + n > self.budget_bytes:
                self._gauge()
                REGISTRY.counter("kv_spill_total",
                                 labels={"outcome": "over_budget"}).inc()
                return False
            self._bytes[rid] = n
            self._used += n
            self._gauge()
        REGISTRY.counter("kv_spill_total",
                         labels={"outcome": "spilled"}).inc()
        return True

    def release(self, rid: str, outcome: str = "promoted") -> Optional[int]:
        """Return a request's bytes to the budget (promotion back
        on-device, or the job dying while spilled). None = not held."""
        with self._lock:
            n = self._bytes.pop(rid, None)
            if n is None:
                return None
            self._used -= n
            self._gauge()
        REGISTRY.counter("kv_spill_total", labels={"outcome": outcome}).inc()
        return n

    def occupancy(self) -> Dict[str, Any]:
        """Point-in-time occupancy snapshot — what the flight dump and
        the trace plane's what-if baselines record (the gauges carry the
        same numbers but a crash-dump artifact must be self-contained)."""
        with self._lock:
            return {"kind": "spill",
                    "budget_bytes": self.budget_bytes,
                    "used_bytes": self._used,
                    "held": len(self._bytes)}


@dataclass
class _TierEntry:
    """One cached prefix run: the payload whose first ``depth`` pages
    are addressable by the chain hashes ``hashes[0..depth-1]``."""

    key: bytes                         # deepest chain hash == identity
    hashes: Tuple[bytes, ...]          # h_0 .. h_{depth-1}
    depth: int                         # full pages covered
    tokens: int                        # depth * page_size (pricing basis)
    payload: Optional[Dict[str, Any]]  # RAM copy; None = disk-resident only
    nbytes: int = 0                    # RAM charge while retained
    tenant: str = ""
    slack_s: float = _SLACK_CAP_S      # contributor's SLO slack
    linked_rid: str = ""               # live spill pinning this entry
    refs: int = 0                      # checkout pins (promote in flight)
    hits: int = 0
    last_use: float = field(default_factory=clock.mono)
    disk_path: str = ""
    disk_bytes: int = 0


class PrefixKVTier(KVSpillPool):
    """Prefix-addressed, refcounted, value-priced KV store (module doc).

    Accounting: the rid registry (inherited) charges live spill payloads;
    ``cached_bytes`` charges retained entries. ``used_bytes`` — the
    budget the operator set — covers BOTH: retaining an entry moves its
    charge from the rid row to the entry, it never doubles it."""

    def __init__(self, budget_bytes: int,
                 disk_budget_bytes: int = 0,
                 perf_model: Any = None,
                 disk_dir: Optional[str] = None,
                 half_life_s: float = 300.0) -> None:
        super().__init__(budget_bytes)
        self._entries: Dict[bytes, _TierEntry] = {}
        self._by_hash: Dict[bytes, Tuple[bytes, int]] = {}
        self._rid_link: Dict[str, bytes] = {}
        self._cached = 0
        self._perf = perf_model
        self._half_life_s = float(half_life_s)
        # QoS composition hook: tenant -> overuse seconds (virtual-time
        # lead). Entries from overusing tenants evict first.
        self._victim_bias: Optional[Callable[[str], float]] = None
        # disk tier (write-behind): ops drain on ONE background thread so
        # file I/O never blocks the driver and never runs under _lock
        self.disk_budget_bytes = int(disk_budget_bytes)
        self._disk_used = 0
        self._disk_dir = disk_dir
        self._disk_q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._disk_thread: Optional[threading.Thread] = None
        self._close_registered = False

    # ------------------------------------------------------------- accounting

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used + self._cached

    @property
    def cached_bytes(self) -> int:
        """RAM bytes held by RETAINED entries (refcount cache), excluding
        live rid-pinned spill payloads."""
        with self._lock:
            return self._cached

    @property
    def disk_used_bytes(self) -> int:
        with self._lock:
            return self._disk_used

    def entries(self) -> int:
        with self._lock:
            return len(self._entries)

    def live_refs(self) -> int:
        """Open pins: checkout refs + live rid links — the fuzz harness
        asserts this drains to zero (refcount conservation through
        preemptions, chaos, and driver resets)."""
        with self._lock:
            return (sum(e.refs for e in self._entries.values())
                    + len(self._rid_link))

    def set_victim_bias(self, fn: Optional[Callable[[str], float]]) -> None:
        self._victim_bias = fn

    def _gauge(self) -> None:
        REGISTRY.gauge("kv_spill_bytes").set(self._used)
        REGISTRY.gauge("kv_tier_bytes").set(self._cached)
        REGISTRY.gauge("kv_tier_entries").set(len(self._entries))
        if self.disk_budget_bytes:
            REGISTRY.gauge("kv_tier_disk_bytes").set(self._disk_used)

    # --------------------------------------------------------------- pricing

    def _recompute_cost(self, tokens: int) -> float:
        """What re-prefilling ``tokens`` would cost: core/perfmodel's
        prefill-seconds estimate when the chip's peaks are known, the
        token count itself otherwise (an unknown denominator must never
        make every entry worthless — relative ordering survives)."""
        if self._perf is not None:
            est = None
            fn = getattr(self._perf, "prefill_seconds", None)
            if fn is not None:
                est = fn(tokens)
            if est is not None:
                return float(est)
        return float(tokens)

    def _score_locked(self, e: _TierEntry, now: float) -> float:
        """Eviction value (lower evicts first): recompute cost × recency
        decay × hit history, kept longer when the contributor had little
        SLO slack, discounted by the contributing tenant's QoS overuse."""
        value = self._recompute_cost(e.tokens)
        age = max(0.0, now - e.last_use)
        value *= max(0.5 ** (age / self._half_life_s), 1e-3)
        value *= 1.0 + min(e.hits, 8)
        slack = min(max(e.slack_s, 0.0), _SLACK_CAP_S)
        value *= 1.0 + (_SLACK_CAP_S - slack) / _SLACK_CAP_S
        bias = self._victim_bias
        if bias is not None:
            try:
                value /= 1.0 + max(0.0, float(bias(e.tenant) or 0.0))
            except Exception:   # tpulint: disable=except-swallow -- a pricing hook must never break eviction; the unbiased score is always safe
                pass
        return value

    # -------------------------------------------------------------- eviction

    def _drop_entry_locked(self, e: _TierEntry, outcome: str) -> None:
        """Remove an entry's RAM presence; full removal when no disk copy
        survives. NEVER called on a pinned entry — the callers filter."""
        if e.payload is not None:
            self._cached -= e.nbytes
            e.payload = None
            e.nbytes = 0
        if e.disk_path and outcome != "evicted_disk":
            REGISTRY.counter("kv_tier_total",
                             labels={"outcome": "demoted"}).inc()
            return   # demoted: the disk copy keeps the entry addressable
        self._entries.pop(e.key, None)
        for h in e.hashes:
            ref = self._by_hash.get(h)
            if ref is not None and ref[0] == e.key:
                del self._by_hash[h]
        if e.disk_path:
            self._disk_used -= e.disk_bytes
            self._disk_q.put(("del", e.disk_path))
        REGISTRY.counter("kv_tier_total", labels={"outcome": outcome}).inc()

    def _evict_for_locked(self, need: int) -> None:
        """Value-priced eviction until ``need`` more bytes fit. Only
        unpinned RAM-resident entries are candidates; an entry with a
        checkout ref or a live rid link is untouchable by construction."""
        now = clock.mono()
        while self._used + self._cached + need > self.budget_bytes:
            cands = [e for e in self._entries.values()
                     if e.refs == 0 and not e.linked_rid
                     and e.payload is not None]
            if not cands:
                return
            victim = min(cands, key=lambda e: self._score_locked(e, now))
            self._drop_entry_locked(victim, "evicted")

    # ------------------------------------------------------------ rid plane

    def admit(self, rid: str, payload: Dict[str, Any]) -> bool:
        """Charge a spilled payload, evicting retained cache first when
        the budget demands it — live requests outrank history. False =
        over budget even with every unpinned entry gone (recompute
        fallback, same contract as the base pool)."""
        n = payload_nbytes(payload)
        stale_key: Optional[bytes] = None
        with self._lock:
            if rid in self._bytes:
                self._used -= self._bytes.pop(rid)
                # a re-spill replaces the payload the old entry shares —
                # drop the stale entry rather than serve old arrays
                stale_key = self._rid_link.pop(rid, None)
            if stale_key is not None:
                e = self._entries.get(stale_key)
                if e is not None and e.linked_rid == rid:
                    e.linked_rid = ""
                    self._drop_entry_locked(e, "replaced")
            self._evict_for_locked(n)
            if self._used + self._cached + n > self.budget_bytes:
                self._gauge()
                REGISTRY.counter("kv_spill_total",
                                 labels={"outcome": "over_budget"}).inc()
                return False
            self._bytes[rid] = n
            self._used += n
            self._gauge()
        REGISTRY.counter("kv_spill_total",
                         labels={"outcome": "spilled"}).inc()
        return True

    def release(self, rid: str, outcome: str = "promoted") -> Optional[int]:
        """Release a rid's charge. Unlike the base pool, a linked tier
        entry is RETAINED: its bytes move from the rid row to the cached
        plane (no net change against the budget) and the entry becomes an
        evictable, value-priced prefix — the returning-conversation hit."""
        retained: Optional[_TierEntry] = None
        with self._lock:
            n = self._bytes.pop(rid, None)
            if n is None:
                return None
            self._used -= n
            key = self._rid_link.pop(rid, None)
            if key is not None:
                e = self._entries.get(key)
                if e is not None and e.linked_rid == rid:
                    e.linked_rid = ""
                    if e.payload is not None:
                        e.nbytes = payload_nbytes(e.payload)
                        self._cached += e.nbytes
                        e.last_use = clock.mono()
                        retained = e
            self._gauge()
        REGISTRY.counter("kv_spill_total", labels={"outcome": outcome}).inc()
        if retained is not None:
            REGISTRY.counter("kv_tier_total",
                             labels={"outcome": "retained"}).inc()
            if self.disk_budget_bytes > 0 and not retained.disk_path:
                # write-behind: the disk copy is made AHEAD of eviction so
                # a later RAM demotion is instant and lossless
                self._ensure_disk_thread()
                self._disk_q.put(("write", retained.key, retained.payload))
        return n

    # ----------------------------------------------------------- tier plane

    def contribute(self, rid: str, hashes: Sequence[bytes],
                   payload: Dict[str, Any], tokens: int,
                   tenant: str = "",
                   slack_s: Optional[float] = None) -> bool:
        """Register a spilled payload's full-page prefix run under its
        chain hashes. The entry shares the rid's payload arrays (zero
        copy) and is pinned by the rid until :meth:`release`."""
        if not hashes:
            return False
        key = bytes(hashes[-1])
        with self._lock:
            if rid not in self._bytes:
                return False   # admit failed or raced a release
            prev = self._entries.get(key)
            if prev is not None:
                if prev.linked_rid and prev.linked_rid != rid:
                    return False   # pinned by another live spill
                if prev.refs > 0:
                    return False   # promote in flight reads its arrays
                self._drop_entry_locked(prev, "replaced")
            e = _TierEntry(
                key=key,
                hashes=tuple(bytes(h) for h in hashes),
                depth=len(hashes),
                tokens=int(tokens),
                payload=payload,
                tenant=str(tenant or ""),
                slack_s=(_SLACK_CAP_S if slack_s is None
                         else min(max(float(slack_s), 0.0), _SLACK_CAP_S)),
                linked_rid=rid,
            )
            self._entries[key] = e
            for i, h in enumerate(e.hashes):
                self._by_hash[h] = (key, i + 1)
            self._rid_link[rid] = key
            self._gauge()
        REGISTRY.counter("kv_tier_total",
                         labels={"outcome": "contributed"}).inc()
        return True

    def probe(self, hashes: Sequence[bytes]
              ) -> Optional[Tuple[bytes, int]]:
        """Longest cached prefix of a prompt's chain hashes, deepest
        first: ``(entry_key, covered_pages)`` or None. Read-only — the
        caller promotes via :meth:`checkout`/:meth:`checkin`."""
        if not hashes:
            return None
        with self._lock:
            for i in range(len(hashes) - 1, -1, -1):
                ref = self._by_hash.get(bytes(hashes[i]))
                if ref is None:
                    continue
                key, depth = ref
                e = self._entries.get(key)
                if e is None or (e.payload is None and not e.disk_path):
                    continue
                REGISTRY.counter("kv_tier_probe_total",
                                 labels={"outcome": "hit"}).inc()
                return key, depth
        REGISTRY.counter("kv_tier_probe_total",
                         labels={"outcome": "miss"}).inc()
        return None

    def checkout(self, key: bytes) -> Optional[Dict[str, Any]]:
        """Pin an entry for a promote and return its payload (RAM, or a
        one-shot disk load — the crc32-framed file either decodes exactly
        or fails loudly and the entry dies). None = evicted since the
        probe, or the disk copy is corrupt; the caller re-prefills. Pair
        every non-None return with :meth:`checkin`."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            e.refs += 1
            e.hits += 1
            e.last_use = clock.mono()
            payload = e.payload
            path = e.disk_path
        if payload is not None:
            return payload
        # disk load: blocking file I/O OUTSIDE the lock. The driver pays
        # one read per promote — comparable to the fetch=True export the
        # spill already does, and strictly cheaper than the re-prefill
        # this load avoids.
        try:
            with open(path, "rb") as f:
                data = f.read()
            payload = kv_wire.decode_kv_frames(data)
        except Exception as exc:
            # corruption is LOUD and terminal for the entry: a bad frame
            # must become a re-prefill, never served garbage KV
            logger.warning("kv tier disk entry %s unreadable (%s); "
                           "dropping", path, exc)
            REGISTRY.counter("kv_tier_total",
                             labels={"outcome": "disk_corrupt"}).inc()
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    e.refs = max(0, e.refs - 1)
                    if e.refs == 0 and not e.linked_rid:
                        self._drop_entry_locked(e, "evicted_disk")
                self._gauge()
            return None
        REGISTRY.counter("kv_tier_total",
                         labels={"outcome": "disk_load"}).inc()
        return payload

    def checkin(self, key: bytes) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.refs = max(0, e.refs - 1)

    # ---------------------------------------------------------- fleet advert

    def hot_stats(self, k: Optional[int] = None) -> Dict[str, Any]:
        """The /health piggyback: tier occupancy + the top-K hottest
        entries' h₀ hex digests (the shareable OPENING page — what a
        router-side conversation key can actually match)."""
        k = tier_hot_k() if k is None else int(k)
        with self._lock:
            now = clock.mono()
            live = [e for e in self._entries.values()
                    if e.payload is not None or e.disk_path]
            live.sort(key=lambda e: self._score_locked(e, now), reverse=True)
            hot: List[str] = []
            for e in live:
                h0 = e.hashes[0].hex()
                if h0 not in hot:
                    hot.append(h0)
                if len(hot) >= k:
                    break
            return {
                "kv_tier_bytes": self._cached,
                "kv_tier_entries": len(self._entries),
                "kv_tier_disk_bytes": self._disk_used,
                "kv_tier_hot": hot,
            }

    def occupancy(self) -> Dict[str, Any]:
        with self._lock:
            live_refs = sum(e.refs for e in self._entries.values())
            return {"kind": "prefix",
                    "budget_bytes": self.budget_bytes,
                    "used_bytes": self._used,
                    "cached_bytes": self._cached,
                    "held": len(self._bytes),
                    "entries": len(self._entries),
                    "live_refs": live_refs,
                    "disk_budget_bytes": self.disk_budget_bytes,
                    "disk_used_bytes": self._disk_used}

    # ------------------------------------------------------------- disk tier

    def _ensure_disk_thread(self) -> None:
        if self._disk_thread is not None and self._disk_thread.is_alive():
            return
        self._disk_thread = threading.Thread(target=self._disk_loop,
                                             name="kv-tier-disk",
                                             daemon=True)
        self._disk_thread.start()
        if not self._close_registered:
            # bounded-join shutdown: a daemon dies mid-os.replace at
            # interpreter exit, leaving a torn .tmp next to the store
            atexit.register(self.close)
            self._close_registered = True

    def close(self, timeout_s: float = 2.0) -> None:
        """Bounded shutdown of the write-behind thread: sentinel-stop,
        then join with a deadline (atexit and the scheduler's drain path
        both land here — shutdown must never hang on a slow disk)."""
        t = self._disk_thread
        if t is None or not t.is_alive():
            return
        self._disk_q.put(None)
        t.join(timeout_s)
        self._disk_thread = None

    def _disk_dir_path(self) -> str:
        if self._disk_dir is None:
            self._disk_dir = os.environ.get("APP_KV_TIER_DISK_DIR", "") or \
                os.path.join(tempfile.gettempdir(),
                             f"gaix_kv_tier_{os.getpid()}")
        os.makedirs(self._disk_dir, exist_ok=True)
        return self._disk_dir

    def _disk_loop(self) -> None:
        """Write-behind drain: encode + write crc32-framed files, then
        publish the path under the lock. All file I/O lives here — never
        under ``_lock``, never on the driver thread."""
        while True:
            op = self._disk_q.get()
            if op is None:
                return
            try:
                if op[0] == "del":
                    try:
                        os.remove(op[1])
                    except OSError:
                        pass
                    continue
                _, key, payload = op
                data = kv_wire.encode_kv_frames(payload)
                path = os.path.join(self._disk_dir_path(),
                                    f"{key.hex()}.kvw")
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
                dead: List[str] = []
                with self._lock:
                    e = self._entries.get(key)
                    if e is None:
                        dead.append(path)
                    else:
                        e.disk_path = path
                        e.disk_bytes = len(data)
                        self._disk_used += len(data)
                        dead = self._enforce_disk_budget_locked()
                    self._gauge()
                for p in dead:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                REGISTRY.counter("kv_tier_total",
                                 labels={"outcome": "disk_write"}).inc()
            except Exception:
                logger.exception("kv tier disk write-behind failed")

    def _enforce_disk_budget_locked(self) -> List[str]:
        """Delete lowest-value disk copies past the disk budget; returns
        the file paths for the CALLER to remove outside the lock."""
        dead: List[str] = []
        now = clock.mono()
        while self._disk_used > self.disk_budget_bytes:
            cands = [e for e in self._entries.values()
                     if e.disk_path and e.refs == 0 and not e.linked_rid]
            if not cands:
                break
            victim = min(cands, key=lambda e: self._score_locked(e, now))
            dead.append(victim.disk_path)
            self._disk_used -= victim.disk_bytes
            victim.disk_path = ""
            victim.disk_bytes = 0
            if victim.payload is None:
                self._drop_entry_locked(victim, "evicted_disk")
        return dead

    def drain_disk(self, timeout_s: float = 5.0) -> None:
        """Block until queued write-behind ops have drained (tests)."""
        deadline = clock.mono() + timeout_s
        while not self._disk_q.empty() and clock.mono() < deadline:
            time.sleep(0.01)


# ---------------------------------------------------------------------------
# process-level registry (flight dump / debug surfaces)
# ---------------------------------------------------------------------------

_POOL: Optional[KVSpillPool] = None


def register_pool(pool: Optional[KVSpillPool]) -> None:
    """Record the serving scheduler's spill pool / prefix tier so
    process-global dump surfaces (observability/flight.py ``dump()``) can
    embed its occupancy without holding a scheduler reference. Mirrors
    qos.register_policy: last-constructed wins (one serving scheduler per
    process; test schedulers overwrite freely)."""
    global _POOL
    _POOL = pool


def current_pool() -> Optional[KVSpillPool]:
    return _POOL


def occupancy_payload() -> Dict[str, Any]:
    pool = _POOL
    if pool is None:
        return {"enabled": False, "mode": tier_mode(),
                "hint": "set APP_KV_SPILL_MB / APP_KV_TIER=prefix on the "
                        "engine worker to arm the host tier"}
    return pool.occupancy()
