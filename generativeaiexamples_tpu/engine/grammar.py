"""Constrained decoding: JSON-schema → byte-level DFA → on-device logit masks.

The reference's NIM surface guarantees grammatical output for
``response_format.json_schema`` and tool calls at the token level (the
surface RAG/notebooks/langchain/NIM_tool_call_HumanInTheLoop_MultiAgents.ipynb
consumes); round 3 shipped prompt+parse instead, because a per-token host
round trip would break the engine's fused multi-step decode
(engine/tools.py:19-25). This module closes that gap WITHOUT unfusing:

  * A supported JSON schema compiles to a regular language over BYTES
    (objects with fixed properties, arrays with bounds, strings, numbers,
    enums, bounded-depth free JSON) — regex AST → Thompson NFA → subset-
    construction DFA. State 0 is the reject sink; accept states may emit
    EOS.
  * The DFA table is (S, 256) int32 — a few KB. The TOKEN-level transition
    is evaluated inside the decode program by walking each vocab token's
    byte string through the table (L chained gathers over (B, V), ops/
    sampling.py:grammar_mask) — no (S, V) dense table (hundreds of MB at a
    128k vocab), no host sync, and the 8/16-step dispatch fusion survives
    because the per-slot DFA state rides DecodeState like any other
    sampling parameter.
  * Token byte strings come from the tokenizer once per process
    (token_byte_table); specials and oversized tokens are permanently
    masked while a grammar is active.

Unsupported schema features (unbounded recursion via $ref, patternProperties,
anyOf of unbounded shapes) raise ``UnsupportedSchema`` — the serving layer
falls back to prompt+parse exactly as before, so the guarantee is strictly
additive.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

REJECT = 0          # DFA reject sink (row 0 of every table)


class UnsupportedSchema(ValueError):
    """Schema outside the regular subset — caller falls back to prompt+parse."""


# ---------------------------------------------------------------------------
# Regex AST (over byte classes) + combinators
# ---------------------------------------------------------------------------

def lit(data: bytes) -> tuple:
    return ("seq", [("cls", frozenset([b])) for b in data])


def cls(byte_values) -> tuple:
    return ("cls", frozenset(byte_values))


def seq(*parts) -> tuple:
    return ("seq", list(parts))


def alt(*parts) -> tuple:
    if not parts:
        raise UnsupportedSchema("empty alternation (e.g. empty enum)")
    return ("alt", list(parts))


def star(x) -> tuple:
    return ("star", x)


def opt(x) -> tuple:
    return ("opt", x)


def rep(x, lo: int, hi: Optional[int]) -> tuple:
    """x{lo,hi}; hi=None → unbounded."""
    parts = [x] * lo
    if hi is None:
        parts.append(star(x))
    else:
        parts += [opt(x)] * (hi - lo)
    return ("seq", parts)


# -- JSON building blocks ---------------------------------------------------

_WS = opt(cls([0x20]))          # one optional space (compact-ish JSON)

_STRING_CHAR = alt(
    cls(set(range(0x20, 0x7F)) - {0x22, 0x5C}),     # printable minus " \
    cls(range(0x80, 0x100)),                        # utf-8 continuation/lead
    seq(cls([0x5C]), cls(b'"\\/bfnrt')),            # escapes
    seq(cls([0x5C]), cls(b"u"), *([cls(b"0123456789abcdefABCDEF")] * 4)),
)

_DIGIT = cls(b"0123456789")
_INT = seq(opt(cls(b"-")), alt(cls(b"0"), seq(cls(b"123456789"),
                                              star(_DIGIT))))
_NUMBER = seq(_INT, opt(seq(cls(b"."), _DIGIT, star(_DIGIT))),
              opt(seq(cls(b"eE"), opt(cls(b"+-")), _DIGIT, star(_DIGIT))))


def _string_ast(s: Dict[str, Any]) -> tuple:
    if "enum" in s:
        return alt(*[lit(json.dumps(v).encode()) for v in s["enum"]])
    body = rep(_STRING_CHAR, int(s.get("minLength", 0)),
               int(s["maxLength"]) if "maxLength" in s else None)
    return seq(lit(b'"'), body, lit(b'"'))


def _free_json_object(depth: int) -> tuple:
    """Any JSON object (free-form keys and values), nesting bounded at
    ``depth`` — the language of ``{"type": "object"}`` with no declared
    properties."""
    inner = _free_json(depth - 1)
    key = seq(lit(b'"'), star(_STRING_CHAR), lit(b'"'))
    member = seq(key, _WS, lit(b":"), _WS, inner)
    return seq(lit(b"{"), _WS,
               opt(seq(member, star(seq(lit(b","), _WS, member)))),
               _WS, lit(b"}"))


def _free_json(depth: int) -> tuple:
    """Any JSON value, nesting bounded at ``depth`` (a DFA cannot count)."""
    scalar = alt(seq(lit(b'"'), star(_STRING_CHAR), lit(b'"')),
                 _NUMBER, lit(b"true"), lit(b"false"), lit(b"null"))
    if depth <= 0:
        return scalar
    inner = _free_json(depth - 1)
    arr = seq(lit(b"["), _WS,
              opt(seq(inner, star(seq(lit(b","), _WS, inner)))),
              _WS, lit(b"]"))
    return alt(scalar, arr, _free_json_object(depth))


_FREE_DEPTH = 3


def schema_ast(schema: Dict[str, Any], depth: int = 12) -> tuple:
    """Regex AST for a JSON-schema subset. Raises UnsupportedSchema beyond
    the regular fragment."""
    if depth <= 0:
        raise UnsupportedSchema("schema nests deeper than the DFA bound")
    if not isinstance(schema, dict):
        raise UnsupportedSchema(f"schema must be an object, got {schema!r}")
    if "$ref" in schema:
        raise UnsupportedSchema("$ref (potentially recursive)")
    if "const" in schema:
        return lit(json.dumps(schema["const"]).encode())
    if "enum" in schema:
        return alt(*[lit(json.dumps(v).encode()) for v in schema["enum"]])
    if "anyOf" in schema or "oneOf" in schema:
        options = schema.get("anyOf") or schema.get("oneOf")
        return alt(*[schema_ast(o, depth - 1) for o in options])
    t = schema.get("type")
    if isinstance(t, list):
        return alt(*[schema_ast({**schema, "type": one}, depth - 1)
                     for one in t])
    if t == "string":
        return _string_ast(schema)
    if t == "integer":
        return _INT
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return alt(lit(b"true"), lit(b"false"))
    if t == "null":
        return lit(b"null")
    if t == "array":
        items = schema.get("items")
        inner = (schema_ast(items, depth - 1) if isinstance(items, dict)
                 else _free_json(_FREE_DEPTH))
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        body = (opt(_items_seq(inner, 1, None)) if lo == 0 and hi is None
                else _items_seq(inner, max(lo, 1), hi) if lo > 0
                else opt(_items_seq(inner, 1, hi)))
        return seq(lit(b"["), _WS, body, _WS, lit(b"]"))
    if t == "object" or (t is None and "properties" in schema):
        props = schema.get("properties")
        if not props:
            # a bare {"type": "object"} admits ANY object (JSON Schema
            # semantics) — only an explicit additionalProperties:false
            # pins it to the empty object. A SCHEMA-valued
            # additionalProperties would make the free-object language a
            # superset of the schema's — refuse so the serving layer falls
            # back to prompt+parse instead of guaranteeing invalid output.
            ap = schema.get("additionalProperties")
            if isinstance(ap, dict):
                raise UnsupportedSchema(
                    "additionalProperties with a value schema")
            if ap is False:
                return seq(lit(b"{"), _WS, lit(b"}"))
            return (_free_json(_FREE_DEPTH) if t is None
                    else _free_json_object(_FREE_DEPTH))
        # JSON Schema semantics: absent "required" means NO property is
        # required (the prompt contract still asks the model for all of
        # them; the mask only guarantees validity)
        required = set(schema.get("required", ()))
        members = []
        for name, sub in props.items():
            m = seq(lit(json.dumps(name).encode()), _WS, lit(b":"), _WS,
                    schema_ast(sub, depth - 1))
            members.append((m, name in required))
        # fixed property order (the order models are prompted with); the
        # first emitted member needs no leading comma — build alternatives
        # over which required/optional members appear
        return seq(lit(b"{"), _WS, _members_seq(members), _WS, lit(b"}"))
    if t is None:
        return _free_json(_FREE_DEPTH)
    raise UnsupportedSchema(f"unsupported type {t!r}")


def _items_seq(inner: tuple, lo: int, hi: Optional[int]) -> tuple:
    first = inner
    more = seq(lit(b","), _WS, inner)
    return seq(first, rep(more, lo - 1, None if hi is None else hi - 1))


def _members_seq(members: List[Tuple[tuple, bool]]) -> tuple:
    """Members in fixed order; optional ones may be absent; commas separate
    exactly the PRESENT members. suffix_from(i) is the language of members
    i.. given that some member was already emitted (each present one is
    comma-prefixed); the head alternation picks which member appears first
    (no comma) — any optional member before the first required one may be
    it."""
    def suffix_from(i: int) -> tuple:
        a: tuple = ("seq", [])
        for m, req in reversed(members[i:]):
            e = seq(lit(b","), _WS, m, a)
            a = e if req else alt(e, a)
        return a

    options: List[tuple] = []
    for i, (m, req) in enumerate(members):
        options.append(seq(m, suffix_from(i + 1)))
        if req:
            break
    else:
        options.append(("seq", []))     # all optional: object may be empty
    return alt(*options)


# ---------------------------------------------------------------------------
# NFA construction + subset-construction DFA
# ---------------------------------------------------------------------------

class _NFA:
    def __init__(self) -> None:
        self.eps: List[List[int]] = []
        self.edges: List[Dict[int, List[int]]] = []   # state -> byte -> [to]

    def new(self) -> int:
        self.eps.append([])
        self.edges.append({})
        return len(self.eps) - 1


def _build(nfa: _NFA, ast: tuple, start: int, end: int) -> None:
    kind = ast[0]
    if kind == "cls":
        for b in ast[1]:
            nfa.edges[start].setdefault(b, []).append(end)
    elif kind == "seq":
        cur = start
        parts = ast[1]
        for i, p in enumerate(parts):
            nxt = end if i == len(parts) - 1 else nfa.new()
            _build(nfa, p, cur, nxt)
            cur = nxt
        if not parts:
            nfa.eps[start].append(end)
    elif kind == "alt":
        for p in ast[1]:
            s, e = nfa.new(), nfa.new()
            nfa.eps[start].append(s)
            nfa.eps[e].append(end)
            _build(nfa, p, s, e)
    elif kind == "star":
        s, e = nfa.new(), nfa.new()
        nfa.eps[start] += [s, end]
        nfa.eps[e] += [s, end]
        _build(nfa, ast[1], s, e)
    elif kind == "opt":
        nfa.eps[start].append(end)
        _build(nfa, ast[1], start, end)
    else:  # pragma: no cover
        raise AssertionError(kind)


def _closure(nfa: _NFA, states: frozenset) -> frozenset:
    stack, seen = list(states), set(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


DIST_INF = 1 << 30


@dataclass(frozen=True)
class ByteDFA:
    """table: (S, 256) int32, row 0 = reject sink; accept: (S,) bool;
    start: int; dist: (S,) int32 — fewest BYTES from the state to an accept
    state (DIST_INF for the sink). Because every byte has a single-byte
    token in serving vocabularies, dist also upper-bounds the TOKENS needed
    to finish — the runtime masks away tokens that would leave the
    automaton unfinishable within the request's remaining budget, so
    constrained generations complete instead of truncating mid-JSON."""

    table: np.ndarray
    accept: np.ndarray
    start: int
    dist: np.ndarray

    @property
    def n_states(self) -> int:
        return self.table.shape[0]

    def matches(self, data: bytes) -> bool:
        s = self.start
        for b in data:
            s = int(self.table[s, b])
            if s == REJECT:
                return False
        return bool(self.accept[s])


MAX_DFA_STATES = 20000


def compile_dfa(ast: tuple) -> ByteDFA:
    nfa = _NFA()
    s0, s1 = nfa.new(), nfa.new()
    _build(nfa, ast, s0, s1)

    start_set = _closure(nfa, frozenset([s0]))
    index: Dict[frozenset, int] = {start_set: 1}     # 0 reserved for reject
    order = [start_set]
    rows: List[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = np.zeros((256,), np.int32)
        moves: Dict[int, set] = {}
        for s in cur:
            for b, tos in nfa.edges[s].items():
                moves.setdefault(b, set()).update(tos)
        for b, tos in moves.items():
            nxt = _closure(nfa, frozenset(tos))
            if nxt not in index:
                if len(index) + 1 > MAX_DFA_STATES:
                    raise UnsupportedSchema(
                        f"DFA exceeds {MAX_DFA_STATES} states")
                index[nxt] = len(index) + 1
                order.append(nxt)
            row[b] = index[nxt]
        rows.append(row)
    table = np.zeros((len(order) + 1, 256), np.int32)
    for j, row in enumerate(rows):
        table[j + 1] = row
    accept = np.zeros((len(order) + 1,), bool)
    for st, j in index.items():
        accept[j] = s1 in st
    # reverse BFS: fewest bytes from each state to an accept state
    S = len(order) + 1
    preds: List[List[int]] = [[] for _ in range(S)]
    for s in range(1, S):
        for t in set(table[s].tolist()):
            if t != REJECT:
                preds[t].append(s)
    dist = np.full((S,), DIST_INF, np.int64)
    frontier = [s for s in range(1, S) if accept[s]]
    dist[frontier] = 0
    d = 0
    while frontier:
        d += 1
        nxt = []
        for t in frontier:
            for s in preds[t]:
                if dist[s] > d:
                    dist[s] = d
                    nxt.append(s)
        frontier = nxt
    return ByteDFA(table=table, accept=accept, start=1,
                   dist=np.minimum(dist, DIST_INF).astype(np.int32))


# ---------------------------------------------------------------------------
# Tokenizer byte table + compiled grammar handle
# ---------------------------------------------------------------------------

MAX_TOKEN_BYTES = 16


def token_byte_table(tokenizer, max_bytes: int = MAX_TOKEN_BYTES
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(V, max_bytes) byte ids + (V,) lengths; length -1 = token never
    allowed under a grammar (specials, empties, oversized)."""
    V = tokenizer.vocab_size
    out = np.zeros((V, max_bytes), np.int32)
    lens = np.full((V,), -1, np.int32)
    specials = {getattr(tokenizer, a, -1)
                for a in ("bos_id", "eos_id", "pad_id")}
    byte_ids = getattr(tokenizer, "eos_id", 0) >= 256  # ByteTokenizer shape
    for t in range(V):
        if t in specials:
            continue
        if byte_ids and t < 256:
            data = bytes([t])     # raw byte token (may be a utf-8 fragment)
        else:
            data = tokenizer.decode([t]).encode("utf-8")
            if "�" in data.decode("utf-8", errors="replace"):
                continue          # partial-utf8 piece we cannot byte-map
        if not data or len(data) > max_bytes:
            continue
        out[t, :len(data)] = list(data)
        lens[t] = len(data)
    return out, lens


@dataclass(frozen=True)
class Grammar:
    """A compiled grammar ready for the engine: the byte DFA + a cache key."""

    dfa: ByteDFA
    key: str

    @staticmethod
    def from_schema(schema: Dict[str, Any]) -> "Grammar":
        # NOT sort_keys: property order is part of the enforced language
        # (fixed-order members), so schemas differing only in property
        # order are different grammars and must not collide in engine caches
        return Grammar(dfa=compile_dfa(seq(schema_ast(schema), _WS)),
                       key="schema:" + json.dumps(schema, sort_keys=False))

    @staticmethod
    def json_value() -> "Grammar":
        """Generic json_object mode: any JSON value, nesting ≤ _FREE_DEPTH."""
        return Grammar(dfa=compile_dfa(seq(_free_json(_FREE_DEPTH), _WS)),
                       key="json_value")

    @staticmethod
    def for_tools_cached(tools: Sequence[Dict[str, Any]],
                         forced: Optional[str] = None) -> "Grammar":
        """Compile-once variant: tool sets repeat across agent-loop turns,
        DFA determinization doesn't need to (the /v1 server caches for the
        same reason)."""
        return _cached_tools_grammar(
            json.dumps({"tools": list(tools), "forced": forced},
                       sort_keys=False))

    @staticmethod
    def for_tools(tools: Sequence[Dict[str, Any]],
                  forced: Optional[str] = None) -> "Grammar":
        """The tool-call envelope: {"tool_calls": [{"name": <tool>,
        "arguments": <its parameter schema>}...]} — names constrained to the
        declared tools, arguments to each tool's own schema."""
        calls = []
        for t in tools:
            fn = t.get("function", t)
            name = fn.get("name", "")
            if forced and name != forced:
                continue
            params = fn.get("parameters") or {"type": "object"}
            calls.append(seq(lit(b'{"name":'), _WS,
                             lit(json.dumps(name).encode()), lit(b","), _WS,
                             lit(b'"arguments":'), _WS,
                             schema_ast(params), lit(b"}")))
        one = alt(*calls)
        env = seq(lit(b'{"tool_calls":'), _WS, lit(b"["), _WS,
                  one, star(seq(lit(b","), _WS, one)), _WS, lit(b"]"),
                  lit(b"}"), _WS)
        # the key must cover PARAMETER SCHEMAS, not just names — engines
        # dedup grammars by key, and two tool sets with identical names but
        # different parameters are different languages (NOT sort_keys:
        # property order is part of the enforced language)
        spec = json.dumps(
            [[t.get("function", t).get("name"),
              t.get("function", t).get("parameters")] for t in tools],
            sort_keys=False)
        digest = hashlib.sha256(spec.encode()).hexdigest()[:16]
        key = f"tools:{digest}:{forced}"
        return Grammar(dfa=compile_dfa(env), key=key)


import functools as _functools


@_functools.lru_cache(maxsize=64)
def _cached_tools_grammar(spec_json: str) -> "Grammar":
    spec = json.loads(spec_json)
    return Grammar.for_tools(spec["tools"], forced=spec["forced"])
