"""Block-table paged KV cache + paged prefill/decode passes.

The TPU-native counterpart of the paged inflight-batching KV management the
reference gets from its NIM/TRT-LLM container (ref: docs/architecture.md:49-61
— "paged attention", inflight batching). Design constraints that differ from
the GPU original:

  * **One physical pool, static shapes.** K/V live in a single
    ``(L, P, page, KV, HD)`` buffer; a request owns an ordered list of page
    ids (its row of the block table). Compiled programs never change shape —
    growing a sequence is a host-side page-id append, not a reallocation.
  * **Writes are scatters at page granularity; reads are gathers.** A prefill
    chunk is page-aligned (``prefill_chunk % page_size == 0``), so its KV
    scatters whole pages (`.at[pages].set`). Decode writes one (page, offset)
    row per slot. Attention reads gather the slot's pages into a dense view —
    XLA keeps the gather on-chip — and reuse the exact same flash/ragged
    kernels as the dense path (ops/pallas/attention.py), so the pallas DMA
    length-clamping still skips dead *blocks* within the gathered view.
  * **Page 0 is the null page.** Slots that are inactive during a decode step
    still execute the (unconditional, statically shaped) write; their write
    row is redirected to page 0, which no request ever owns. Freed pages can
    therefore be re-issued immediately without a device-side barrier.

HBM held by the cache is ``num_pages × page_size`` tokens — bounded by live
tokens (plus page-rounding), not ``max_batch × max_seq`` slot capacity.

The host-side :class:`PageAllocator` is a free-list; admission and decode in
engine/scheduler.py allocate/free against it and mirror the block table.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from functools import partial

from jax.sharding import PartitionSpec as P

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.ops import pallas as pallas_ops
from generativeaiexamples_tpu.ops.attention import mha_decode, mha_prefill
from generativeaiexamples_tpu.ops.layers import rotary_embedding


def _tp_degree(mesh) -> int:
    return int(mesh.shape.get("tensor", 1)) if mesh is not None else 1


@jax.tree_util.register_pytree_node_class
@dataclass
class PagedKVCache:
    """Paged KV pool in the decode kernel's native FLAT layout.

    k, v: (L*P, page_size, KV*HD) — layer l's physical page p lives at row
    ``l*P + p``. The flat layout is load-bearing: the pool is a multi-GB
    loop-carried buffer in decode/prefill, and any reshape or per-layer
    slice of a loop carry makes XLA materialize a full copy per layer
    (profiled at ~2 s per 8-step dispatch on a 3B model before this layout).
    All access is by computed row index: pallas index maps for attention
    reads, scatters for token writes. ``lengths``: (B,) live rows per slot.

    With ``kv_quant="int8"`` the pool stores int8 with per-token-per-head
    symmetric scales ``k_s``/``v_s`` (L*P, KV, page_size) — the TRT-LLM
    KV-cache-quantization capability brought in-tree: half the pool's HBM
    footprint AND measured +5% decode throughput on v5e (~3% scale
    overhead). The scale layout keeps heads on axis 1 so a (KV, page)
    block is a native f32 tile, and the paged kernel folds the dequant
    past its dots (scores and probabilities are row-scaled; K/V elements
    are never dequantized — docs/performance.md has the measured history).
    ``k_s is None`` ⇔ bf16 pool.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    lengths: jnp.ndarray
    k_s: Optional[jnp.ndarray] = None
    v_s: Optional[jnp.ndarray] = None

    def tree_flatten(self):
        return (self.k, self.v, self.lengths, self.k_s, self.v_s), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)

    @property
    def page_size(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_s is not None

    @staticmethod
    def create(cfg: llama.LlamaConfig, batch: int, num_pages: int,
               page_size: int, kv_sharding=None,
               aux_sharding=None, kv_quant: str = "none",
               scale_sharding=None) -> "PagedKVCache":
        """Allocate the pool; shardings (if given) apply at creation so the
        multi-GB k/v buffers are never materialized on a single chip.
        ``scale_sharding`` places the (rows, KV, page) scale pools — their
        HEAD axis is axis 1, unlike the kv pools' fused last axis."""
        shape = (cfg.n_layers * num_pages, page_size,
                 cfg.n_kv_heads * cfg.head_dim)
        if kv_quant == "int8":
            # scales are stored TRANSPOSED, (L*P, KV, page_size): a (KV, ps)
            # block is a native (8, 128) f32 tile, where (ps, KV) blocks
            # made degenerate 8-wide DMAs that cost more than the int8
            # saved (measured round 4); the kernel row-scales scores and
            # probabilities instead of dequantizing elements
            s_shape = (shape[0], cfg.n_kv_heads, page_size)
            return PagedKVCache(
                k=jnp.zeros(shape, jnp.int8, device=kv_sharding),
                v=jnp.zeros(shape, jnp.int8, device=kv_sharding),
                lengths=jnp.zeros((batch,), jnp.int32, device=aux_sharding),
                k_s=jnp.zeros(s_shape, jnp.float32, device=scale_sharding),
                v_s=jnp.zeros(s_shape, jnp.float32, device=scale_sharding))
        if kv_quant not in ("none", ""):
            raise ValueError(f"unknown kv_quant {kv_quant!r}")
        return PagedKVCache(
            k=jnp.zeros(shape, cfg.jdtype, device=kv_sharding),
            v=jnp.zeros(shape, cfg.jdtype, device=kv_sharding),
            lengths=jnp.zeros((batch,), jnp.int32, device=aux_sharding))


def _kv_quantize(x: jnp.ndarray, KV: int, HD: int):
    """(…, KV*HD) → int8 values + (…, KV) per-token-per-head scales."""
    shaped = x.reshape(x.shape[:-1] + (KV, HD)).astype(jnp.float32)
    s = jnp.max(jnp.abs(shaped), axis=-1) / 127.0
    safe = jnp.maximum(s, 1e-10)
    q = jnp.clip(jnp.round(shaped / safe[..., None]), -127, 127)
    return q.astype(jnp.int8).reshape(x.shape), s


def _kv_dequant_dense(q: jnp.ndarray, s: jnp.ndarray, KV: int, HD: int,
                      dtype) -> jnp.ndarray:
    """(B, T, KV*HD) int8 + (B, T, KV) scales → (B, T, KV, HD) dense."""
    B, T = q.shape[:2]
    return (q.reshape(B, T, KV, HD).astype(jnp.float32)
            * s[..., None]).astype(dtype)


def _scatter_pages(pools, flat_pages, k, v, G, C, n_cp, ps, KV, HD):
    """Scatter a page-aligned chunk's K/V into whole physical pages.

    k, v: (G, C, KV, HD) new chunk KV; flat_pages: (G*n_cp,) physical rows
    to scatter into. Quantizes per token/head when the pools carry scales.
    Returns pools'."""
    if len(pools) == 4:
        k_pool, v_pool, ks_pool, vs_pool = pools
        kq, ks = _kv_quantize(k.reshape(G, C, KV * HD), KV, HD)
        vq, vs = _kv_quantize(v.reshape(G, C, KV * HD), KV, HD)
        new_k = k_pool.at[flat_pages].set(kq.reshape(G * n_cp, ps, KV * HD))
        new_v = v_pool.at[flat_pages].set(vq.reshape(G * n_cp, ps, KV * HD))
        # pool layout is (rows, KV, ps): transpose the per-token scales in
        sT = lambda s: (s.reshape(G, n_cp, ps, KV)
                        .transpose(0, 1, 3, 2).reshape(G * n_cp, KV, ps))
        return (new_k, new_v, ks_pool.at[flat_pages].set(sT(ks)),
                vs_pool.at[flat_pages].set(sT(vs)))
    k_pool, v_pool = pools
    return (k_pool.at[flat_pages].set(
                k.astype(k_pool.dtype).reshape(G * n_cp, ps, KV * HD)),
            v_pool.at[flat_pages].set(
                v.astype(v_pool.dtype).reshape(G * n_cp, ps, KV * HD)))


def _gather_dense(pools, flat_rows, G, T, KV, HD, dtype):
    """Dense (G, T, KV, HD) attention view of the pool rows ``flat_rows``
    (G, maxp) — the XLA-fallback read path (the pallas kernels instead DMA
    pages in place). Dequantizes when the pools carry scales."""
    ps = pools[0].shape[1]
    if len(pools) == 4:
        k_pool, v_pool, ks_pool, vs_pool = pools
        dT = lambda sp: (sp[flat_rows].reshape(G, -1, KV, ps)
                         .transpose(0, 1, 3, 2).reshape(G, T, KV))
        k_dense = _kv_dequant_dense(k_pool[flat_rows].reshape(G, T, -1),
                                    dT(ks_pool), KV, HD, dtype)
        v_dense = _kv_dequant_dense(v_pool[flat_rows].reshape(G, T, -1),
                                    dT(vs_pool), KV, HD, dtype)
        return k_dense, v_dense
    return (pools[0][flat_rows].reshape(G, T, KV, HD),
            pools[1][flat_rows].reshape(G, T, KV, HD))


def _write_pages_dense(pools, flat_pages, flat_rows, k, v, G, C, n_cp, ps,
                       T, KV, HD, dtype):
    """Shared prefill page write + dense attention view, both pool modes.

    k, v: (G, C, KV, HD) new chunk KV; flat_pages: (G*n_cp,) physical rows
    to scatter whole pages into; flat_rows: (G, maxp) rows to gather the
    dense (G, T, KV, HD) attention view back out. Quantizes per token/head
    when the pools carry scales. Returns (k_dense, v_dense, pools')."""
    out_pools = _scatter_pages(pools, flat_pages, k, v, G, C, n_cp, ps, KV,
                               HD)
    k_dense, v_dense = _gather_dense(out_pools, flat_rows, G, T, KV, HD,
                                     dtype)
    return k_dense, v_dense, out_pools


# ---------------------------------------------------------------------------
# KV-page handoff (disaggregated prefill/decode serving)
# ---------------------------------------------------------------------------

def export_pages(cache: PagedKVCache, page_ids: jnp.ndarray,   # tpulint: hot-path
                 num_pages: int):
    """Gather a slot's live pages into a dense, dtype-preserving buffer.

    The prefill half of the KV handoff between engine roles: a prefill
    worker finishes a prompt, gathers the slot's physical pages into one
    contiguous buffer, and ships buffer + metadata to a decode worker whose
    :func:`import_pages` scatters it into freshly allocated pages of its
    own pool. The buffer preserves the pool dtype — an int8 pool exports
    int8 values plus the f32 per-token-per-head scales, never a dequantized
    copy (half the transfer, and the importing pool stores exactly what a
    local prefill would have written).

    page_ids: (n_p,) physical page ids covering the slot's first n_p
    logical pages (padding entries may carry 0 — the null page — whose
    exported rows are garbage the importer never reads). Returns
    (k, v, k_s, v_s): k/v are (L*n_p, page, KV*HD) in pool dtype with
    layer-major rows (layer l's j-th page at row ``l*n_p + j``); k_s/v_s
    are (L*n_p, KV, page) f32 for int8 pools, None otherwise.
    """
    L = cache.k.shape[0] // num_pages
    rows = (jnp.arange(L, dtype=jnp.int32)[:, None] * num_pages
            + page_ids[None, :].astype(jnp.int32)).reshape(-1)
    if cache.quantized:
        return cache.k[rows], cache.v[rows], cache.k_s[rows], cache.v_s[rows]
    return cache.k[rows], cache.v[rows], None, None


def import_pages(cache: PagedKVCache, page_ids: jnp.ndarray,   # tpulint: hot-path
                 num_pages: int, slot: jnp.ndarray, length: jnp.ndarray,
                 k: jnp.ndarray, v: jnp.ndarray,
                 k_s: Optional[jnp.ndarray] = None,
                 v_s: Optional[jnp.ndarray] = None) -> PagedKVCache:
    """Scatter an exported page buffer into this pool's pages and set the
    receiving slot's length — the decode half of the KV handoff.

    page_ids: (n_p,) freshly allocated physical pages on the RECEIVING
    pool (padding entries carry 0: their rows scatter into the null page,
    which no request owns). k/v (and scales) must match this pool's dtype
    and geometry — the engine validates before dispatching, because a
    silent int8↔bf16 or page-size mismatch would serve garbage KV.
    ``lengths[slot] = length`` exactly as a local prefill would have left
    it; the first decode step then writes the first generated token's KV
    at position ``length``.
    """
    L = cache.k.shape[0] // num_pages
    rows = (jnp.arange(L, dtype=jnp.int32)[:, None] * num_pages
            + page_ids[None, :].astype(jnp.int32)).reshape(-1)
    lengths = cache.lengths.at[slot].set(length)
    new_k = cache.k.at[rows].set(k.astype(cache.k.dtype))
    new_v = cache.v.at[rows].set(v.astype(cache.v.dtype))
    if cache.quantized:
        if k_s is None or v_s is None:
            raise ValueError("int8 pool import needs k_s/v_s scales")
        return PagedKVCache(k=new_k, v=new_v, lengths=lengths,
                            k_s=cache.k_s.at[rows].set(k_s),
                            v_s=cache.v_s.at[rows].set(v_s))
    return PagedKVCache(k=new_k, v=new_v, lengths=lengths)


def import_pages_partial(cache: PagedKVCache,   # tpulint: hot-path
                         page_ids: jnp.ndarray, num_pages: int,
                         k: jnp.ndarray, v: jnp.ndarray,
                         k_s: Optional[jnp.ndarray] = None,
                         v_s: Optional[jnp.ndarray] = None) -> PagedKVCache:
    """Scatter an exported page buffer WITHOUT touching any slot state —
    the prefix-tier promotion path (engine/kv_tier.py).

    Unlike :func:`import_pages`, the imported run covers only the
    PREFIX of a prompt still being admitted: the caller's chunked tail
    prefill owns ``lengths``/sampling state exactly as a fresh admission
    does, and starts at the covered boundary because the scheduler sets
    ``job.prefilled`` to the promoted span. Writing ``lengths`` here
    would corrupt whichever slot the caller hasn't activated yet.
    """
    L = cache.k.shape[0] // num_pages
    rows = (jnp.arange(L, dtype=jnp.int32)[:, None] * num_pages
            + page_ids[None, :].astype(jnp.int32)).reshape(-1)
    new_k = cache.k.at[rows].set(k.astype(cache.k.dtype))
    new_v = cache.v.at[rows].set(v.astype(cache.v.dtype))
    if cache.quantized:
        if k_s is None or v_s is None:
            raise ValueError("int8 pool import needs k_s/v_s scales")
        return PagedKVCache(k=new_k, v=new_v, lengths=cache.lengths,
                            k_s=cache.k_s.at[rows].set(k_s),
                            v_s=cache.v_s.at[rows].set(v_s))
    return PagedKVCache(k=new_k, v=new_v, lengths=cache.lengths)


# The wire codecs live in core/kv_wire.py (numpy-only, so the routing
# frontend can transcode without importing the engine stack): the binary
# zero-copy frame (encode/decode_kv_frames) is the serving wire, the JSON
# base64 form below is the compat fallback. Re-exported here because this
# module is the handoff's home and existing callers import from it. The
# scalar passthrough is a contract either way: sampling state, SLO class,
# grammar state, and the usage plane's ``tenant`` identity
# (observability/usage.py — the decode replica must bill the same tenant
# the prefill worker did) all ride the wire as plain scalar keys.
from generativeaiexamples_tpu.core.kv_wire import (  # noqa: F401
    KV_FRAMES_CONTENT_TYPE, KVWireError, decode_kv_frames, decode_kv_payload,
    encode_kv_frames, encode_kv_payload, is_kv_frames,
)


class PageAllocator:
    """Host-side free-list over physical pages 1..num_pages-1 (0 = null)."""

    def __init__(self, num_pages: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free: deque = deque(range(1, num_pages))

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n pages, or None (and no change) if fewer are free."""
        if n > len(self._free):
            return None
        return [self._free.popleft() for _ in range(n)]

    def free(self, pages: Iterable[int]) -> None:
        for p in pages:
            if not 1 <= p < self.num_pages:
                raise ValueError(f"freeing invalid page id {p}")
            self._free.append(p)


# ---------------------------------------------------------------------------
# Paged forward passes (jitted by engine/engine.py)
# ---------------------------------------------------------------------------

def prefill_chunk(params: llama.Params, cfg: llama.LlamaConfig,
                  tokens: jnp.ndarray, cache: PagedKVCache,
                  page_row: jnp.ndarray, slot: jnp.ndarray,
                  start_pos: jnp.ndarray, chunk_len: jnp.ndarray,
                  num_pages: int,
                  adapters: Optional[llama.Params] = None,
                  adapter_ix=None,
                  mesh=None,
                  ) -> Tuple[jnp.ndarray, PagedKVCache]:
    """One chunk of paged prompt processing for a single slot.

    tokens: (1, C) right-padded chunk, C page-aligned; page_row: (max_pages,)
    the slot's block-table row; start_pos: scalar absolute position of the
    chunk (a multiple of the engine's chunk size); chunk_len: scalar valid
    tokens in this chunk; num_pages: pages per layer in the flat pool.
    Returns logits at the last valid position (1, V) and the cache with the
    chunk's KV scattered into the slot's pages and
    ``lengths[slot] = start_pos + chunk_len``.
    """
    _, C = tokens.shape
    ps = cache.page_size
    if C % ps != 0:
        raise ValueError(f"chunk size {C} must be page-aligned (page={ps})")
    n_cp = C // ps
    maxp = page_row.shape[0]
    T = maxp * ps
    KV, HD = cfg.n_kv_heads, cfg.head_dim

    positions = start_pos + jnp.arange(C, dtype=jnp.int32)[None]    # (1, C)
    h = llama.embed_tokens(params, cfg, tokens)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
    valid_through = (start_pos + chunk_len)[None]                   # (1,)
    chunk_pages = jax.lax.dynamic_slice(page_row, (start_pos // ps,), (n_cp,))
    cache_positions = jnp.arange(T, dtype=jnp.int32)[None]          # (1, T)

    use_pallas = (cfg.attn_impl == "pallas" and cfg.sliding_window == 0
                  and pallas_ops.prefill_supported(C, T, HD))
    tp = _tp_degree(mesh)
    if use_pallas and tp > 1:
        # GSPMD cannot partition a pallas_call: under tensor parallelism
        # the kernel runs per-shard via shard_map, each shard attending
        # its local H/tp query and KV/tp key/value heads (GQA grouping is
        # preserved — H/KV is shard-invariant). This is what lets
        # `attention=pallas` stay on in the TP serving config instead of
        # silently degrading to the XLA path (round-2 weakness #3).
        _sharded_flash = partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(None, None, "tensor", None),
                      P(None, None, "tensor", None),
                      P(None, None, "tensor", None), P(None), P(None)),
            out_specs=P(None, None, "tensor", None), check_vma=False)(
            lambda q_, k_, v_, sp_, vt_: pallas_ops.flash_prefill(
                q_, k_, v_, start_pos=sp_, kv_valid_through=vt_))

    quant = cache.quantized

    def attn_and_update(q, k, v, pools, idx):
        flat_pages = idx * num_pages + chunk_pages
        flat_row = idx * num_pages + page_row
        k_dense, v_dense, out_pools = _write_pages_dense(
            pools, flat_pages, flat_row, k, v, 1, C, n_cp, ps, T, KV, HD,
            h.dtype)
        if use_pallas:
            if tp > 1:
                ctx = _sharded_flash(q, k_dense, v_dense, start_pos[None],
                                     valid_through)
            else:
                ctx = pallas_ops.flash_prefill(
                    q, k_dense, v_dense, start_pos=start_pos[None],
                    kv_valid_through=valid_through)
        else:
            ctx = mha_prefill(
                q, k_dense, v_dense, q_positions=positions,
                kv_positions=cache_positions,
                kv_mask=cache_positions < valid_through[:, None], causal=True,
                window=cfg.sliding_window)
        return ctx, out_pools

    pools_in = ((cache.k, cache.v, cache.k_s, cache.v_s) if quant
                else (cache.k, cache.v))
    h, pools = llama.scan_blocks_inplace(
        cfg, h, params, pools_in, cos, sin, attn_and_update, adapters,
        adapter_ix)
    h_last = jnp.take_along_axis(
        h, (chunk_len - 1)[None, None, None].astype(jnp.int32), axis=1)
    logits = llama._unembed(cfg, params, h_last)[:, 0]               # (1, V)
    new_lengths = cache.lengths.at[slot].set(start_pos + chunk_len)
    return logits, PagedKVCache(k=pools[0], v=pools[1], lengths=new_lengths,
                                k_s=pools[2] if quant else None,
                                v_s=pools[3] if quant else None)


def prefill_chunks(params: llama.Params, cfg: llama.LlamaConfig,
                   tokens: jnp.ndarray, cache: PagedKVCache,
                   page_rows: jnp.ndarray, slots: jnp.ndarray,
                   start_pos: jnp.ndarray, chunk_len: jnp.ndarray,
                   num_pages: int,
                   adapters: Optional[llama.Params] = None,
                   adapter_ix=None,
                   mesh=None,
                   ) -> Tuple[jnp.ndarray, PagedKVCache]:
    """One chunk each for G DISTINCT slots, in a single pass.

    The grouped generalization of :func:`prefill_chunk`: admission ramps and
    slot refills batch several prompts' chunks into one dispatch, amortizing
    the per-dispatch overhead that dominates a remote-attached chip (measured
    ~90 ms/dispatch regardless of size) — the reference's inflight batcher
    gets the same effect from enqueueing prefills into its execution batch.

    tokens: (G, C) right-padded chunks, C page-aligned; page_rows: (G,
    max_pages) block-table rows; slots: (G,) — used ONLY for the lengths
    scatter: entries carrying an out-of-range slot id (== batch size) drop
    it, which serves both group-bucket PADDING rows (whose page writes, via
    all-zero page_rows, land on the null page 0) and de-duplication when a
    group carries several consecutive chunks of the same prompt (scatter
    with duplicate indices is nondeterministic — the caller keeps the true
    slot id only on the row with the highest start_pos).

    Consecutive chunks of ONE prompt may share a group: each layer scatters
    every row's K/V into the pool BEFORE any row's attention gather, so a
    later chunk's attention (masked to valid_through = start_pos +
    chunk_len) reads the earlier chunks' pages written in this same
    program. start_pos / chunk_len: (G,). Returns logits at each chunk's
    last valid position (G, V) and the updated cache.
    """
    G, C = tokens.shape
    ps = cache.page_size
    if C % ps != 0:
        raise ValueError(f"chunk size {C} must be page-aligned (page={ps})")
    n_cp = C // ps
    maxp = page_rows.shape[1]
    T = maxp * ps
    KV, HD = cfg.n_kv_heads, cfg.head_dim

    positions = start_pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
    h = llama.embed_tokens(params, cfg, tokens)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
    valid_through = start_pos + chunk_len                           # (G,)
    chunk_pages = jax.vmap(
        lambda row, sp: jax.lax.dynamic_slice(row, (sp // ps,), (n_cp,)))(
        page_rows, start_pos)                                       # (G, n_cp)
    cache_positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (G, T))

    use_pallas = (cfg.attn_impl == "pallas" and cfg.sliding_window == 0
                  and pallas_ops.prefill_supported(C, T, HD))
    tp = _tp_degree(mesh)
    if use_pallas and tp > 1:
        _sharded_flash = partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(None, None, "tensor", None),
                      P(None, None, "tensor", None),
                      P(None, None, "tensor", None), P(None), P(None)),
            out_specs=P(None, None, "tensor", None), check_vma=False)(
            lambda q_, k_, v_, sp_, vt_: pallas_ops.flash_prefill(
                q_, k_, v_, start_pos=sp_, kv_valid_through=vt_))

    quant = cache.quantized

    def attn_and_update(q, k, v, pools, idx):
        flat_pages = (idx * num_pages + chunk_pages).reshape(-1)  # (G*n_cp,)
        # duplicate indices only occur among padding entries (all page 0 —
        # the null page); real groups hold disjoint pages
        flat_rows = idx * num_pages + page_rows                   # (G, maxp)
        k_dense, v_dense, out_pools = _write_pages_dense(
            pools, flat_pages, flat_rows, k, v, G, C, n_cp, ps, T, KV, HD,
            h.dtype)
        if use_pallas:
            if tp > 1:
                ctx = _sharded_flash(q, k_dense, v_dense, start_pos,
                                     valid_through)
            else:
                ctx = pallas_ops.flash_prefill(
                    q, k_dense, v_dense, start_pos=start_pos,
                    kv_valid_through=valid_through)
        else:
            ctx = mha_prefill(
                q, k_dense, v_dense, q_positions=positions,
                kv_positions=cache_positions,
                kv_mask=cache_positions < valid_through[:, None], causal=True,
                window=cfg.sliding_window)
        return ctx, out_pools

    pools_in = ((cache.k, cache.v, cache.k_s, cache.v_s) if quant
                else (cache.k, cache.v))
    h, pools = llama.scan_blocks_inplace(
        cfg, h, params, pools_in, cos, sin, attn_and_update, adapters,
        adapter_ix)
    last_ix = jnp.maximum(chunk_len - 1, 0)[:, None, None]        # (G, 1, 1)
    h_last = jnp.take_along_axis(h, last_ix.astype(jnp.int32), axis=1)
    logits = llama._unembed(cfg, params, h_last)[:, 0]            # (G, V)
    new_lengths = cache.lengths.at[slots].set(start_pos + chunk_len,
                                              mode="drop")
    return logits, PagedKVCache(k=pools[0], v=pools[1], lengths=new_lengths,
                                k_s=pools[2] if quant else None,
                                v_s=pools[3] if quant else None)


def decode_step(params: llama.Params, cfg: llama.LlamaConfig,
                tokens: jnp.ndarray, cache: PagedKVCache,
                page_table: jnp.ndarray, write_mask: jnp.ndarray,
                num_pages: int,
                adapters: Optional[llama.Params] = None,
                adapter_ix=None,
                mesh=None,
                ) -> Tuple[jnp.ndarray, PagedKVCache]:
    """One paged decode step for every slot in the batch — the Q == 1
    case of :func:`decode_step_wide` (single implementation: TP shard_map
    specs, quantized page writes, and the XLA fallback live there once).

    tokens: (B,) last sampled token per slot; page_table: (B, max_pages);
    write_mask: (B,) bool — slots allowed to append (inactive slots write to
    the null page instead); num_pages: pages per layer in the flat pool.
    Returns logits (B, V) and the cache with ``lengths + 1`` (the engine
    restores lengths of inactive slots).

    Re-issue contract (what both the K-step decode scan and the K·M
    multi-step scan rely on): this step is safe to chain inside a single
    device program with NO host barrier between iterations. Every write
    lands at the slot's own ``lengths`` position through the page table
    (masked slots hit the null page), reads cover exactly ``lengths``
    positions, and the returned cache carries the advanced lengths — so
    iteration N+1 reads iteration N's KV purely through the carried
    value. Nothing here consults host state, which is why the scheduler
    can defer the token fetch a whole K·M block without the cache and
    the emitted stream disagreeing.
    """
    logits, new_cache = decode_step_wide(
        params, cfg, tokens[:, None], cache, page_table, write_mask,
        num_pages, adapters=adapters, adapter_ix=adapter_ix, mesh=mesh)
    return logits[:, 0], PagedKVCache(
        k=new_cache.k, v=new_cache.v, lengths=cache.lengths + 1,
        k_s=new_cache.k_s, v_s=new_cache.v_s)


def decode_step_wide(params: llama.Params, cfg: llama.LlamaConfig,
                     tokens: jnp.ndarray, cache: PagedKVCache,
                     page_table: jnp.ndarray, write_mask: jnp.ndarray,
                     num_pages: int,
                     adapters: Optional[llama.Params] = None,
                     adapter_ix=None,
                     mesh=None,
                     ) -> Tuple[jnp.ndarray, PagedKVCache]:
    """Q-token speculative-VERIFY decode step (ops/speculative.py drafts).

    tokens: (B, Q) — each slot's current token followed by its Q-1 drafted
    continuations, occupying positions lengths[b]..lengths[b]+Q-1. All Q
    tokens' KV scatter into the slot's pages (rows past the block-table
    capacity, and all rows of masked-out slots, land on the null page);
    query qi attends positions < lengths[b]+qi+1 — per-query causal
    offsets, otherwise identical to :func:`decode_step`. Returns logits
    (B, Q, V) and the cache with ``lengths`` UNCHANGED: only the engine
    knows how many drafts were accepted, so it advances lengths by the
    accepted count (rejected positions' KV rows are dead until a future
    step overwrites them — attention masks by length, so they are never
    read). Q == 1 degenerates to exactly one normal decode step.
    """
    B, Q = tokens.shape
    ps = cache.page_size
    maxp = page_table.shape[1]
    T = maxp * ps
    KV, HD = cfg.n_kv_heads, cfg.head_dim

    L = cache.lengths                                        # (B,)
    positions = L[:, None] + jnp.arange(Q, dtype=jnp.int32)[None]   # (B, Q)
    h = llama.embed_tokens(params, cfg, tokens)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
    # rows valid for attention INCLUDE this step's Q writes. NOT clamped to
    # the pool capacity: the pallas kernel reconstructs query positions as
    # attn_len - Q + qi, so a clamp would shift every query's causal limit
    # down near the context cap (its page-index map clamps DMAs safely on
    # its own, and the XLA mask below only indexes real rows).
    attn_len = L + Q

    batch_ix = jnp.arange(B, dtype=jnp.int32)[:, None]
    ok = write_mask[:, None] & (positions < T)
    rows = jnp.where(ok, page_table[batch_ix, positions // ps], jnp.int32(0))
    offs = positions % ps                                    # (B, Q)

    use_pallas = (cfg.attn_impl == "pallas" and cfg.sliding_window == 0
                  and pallas_ops.paged_decode_supported(ps, HD))
    tp = _tp_degree(mesh)
    if use_pallas and tp > 1:
        if cache.quantized:
            _sharded_paged = partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P(None, None, "tensor", None),
                          P(None, None, "tensor"), P(None, None, "tensor"),
                          P(None, None), P(None), P(),
                          P(None, "tensor", None), P(None, "tensor", None)),
                out_specs=P(None, None, "tensor", None), check_vma=False)(
                lambda q_, kp_, vp_, pt_, ln_, ix_, ks_, vs_:
                pallas_ops.paged_decode(
                    q_, kp_, vp_, pt_, ln_, layer=ix_,
                    pages_per_layer=num_pages, k_scales=ks_, v_scales=vs_))
        else:
            _sharded_paged_raw = partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P(None, None, "tensor", None),
                          P(None, None, "tensor"), P(None, None, "tensor"),
                          P(None, None), P(None), P()),
                out_specs=P(None, None, "tensor", None), check_vma=False)(
                lambda q_, kp_, vp_, pt_, ln_, ix_: pallas_ops.paged_decode(
                    q_, kp_, vp_, pt_, ln_, layer=ix_,
                    pages_per_layer=num_pages))
            _sharded_paged = (lambda q_, kp_, vp_, pt_, ln_, ix_, ks_, vs_:
                              _sharded_paged_raw(q_, kp_, vp_, pt_, ln_, ix_))

    quant = cache.quantized
    cache_positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    def attn_and_update(q, k, v, pools, idx):
        flat_rows = idx * num_pages + rows                   # (B, Q)
        if quant:
            k_pool, v_pool, ks_pool, vs_pool = pools
            kq, ks = _kv_quantize(k.reshape(B, Q, KV * HD), KV, HD)
            vq, vs = _kv_quantize(v.reshape(B, Q, KV * HD), KV, HD)
            new_k = k_pool.at[flat_rows, offs].set(kq)
            new_v = v_pool.at[flat_rows, offs].set(vq)
            new_ks = ks_pool.at[flat_rows, :, offs].set(ks)
            new_vs = vs_pool.at[flat_rows, :, offs].set(vs)
            out_pools = (new_k, new_v, new_ks, new_vs)
        else:
            new_k = pools[0].at[flat_rows, offs].set(
                k.astype(pools[0].dtype).reshape(B, Q, KV * HD))
            new_v = pools[1].at[flat_rows, offs].set(
                v.astype(pools[1].dtype).reshape(B, Q, KV * HD))
            new_ks = new_vs = None
            out_pools = (new_k, new_v)
        if use_pallas:
            if tp > 1:
                ctx = _sharded_paged(q, new_k, new_v, page_table,
                                     attn_len, idx, new_ks, new_vs)
            else:
                ctx = pallas_ops.paged_decode(q, new_k, new_v, page_table,
                                              attn_len, layer=idx,
                                              pages_per_layer=num_pages,
                                              k_scales=new_ks,
                                              v_scales=new_vs)
        else:
            k_dense, v_dense = _gather_dense(
                out_pools, idx * num_pages + page_table, B, T, KV, HD,
                h.dtype)
            ctx = mha_prefill(
                q, k_dense, v_dense, q_positions=positions,
                kv_positions=cache_positions,
                kv_mask=cache_positions < attn_len[:, None], causal=True,
                window=cfg.sliding_window)
        return ctx, out_pools

    pools_in = ((cache.k, cache.v, cache.k_s, cache.v_s) if quant
                else (cache.k, cache.v))
    h, pools = llama.scan_blocks_inplace(
        cfg, h, params, pools_in, cos, sin, attn_and_update, adapters,
        adapter_ix)
    logits = llama._unembed(cfg, params, h)                  # (B, Q, V)
    return logits, PagedKVCache(k=pools[0], v=pools[1], lengths=cache.lengths,
                                k_s=pools[2] if quant else None,
                                v_s=pools[3] if quant else None)


def mixed_step(params: llama.Params, cfg: llama.LlamaConfig,   # tpulint: hot-path
               tokens: jnp.ndarray, cache: PagedKVCache,
               page_table: jnp.ndarray, write_mask: jnp.ndarray,
               num_pages: int, chunk_tokens: jnp.ndarray,
               chunk_page_rows: jnp.ndarray, chunk_start: jnp.ndarray,
               chunk_len: jnp.ndarray, mesh=None, q_block: int = 8,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, PagedKVCache]:
    """ONE mixed-phase forward: a Q-wide decode step for every slot PLUS up
    to G prefill chunks, fused into a single program — the ragged-paged-
    attention serving shape (ROADMAP item 2, arxiv 2604.15464). Prefill and
    decode stop being separate dispatches: the chunks' matmuls fatten the
    decode step's tiles instead of stalling the decode tick, which is the
    single-chip fix for prefill/decode interference (the r05 TTFT tail).

    tokens: (B, Q) decode inputs exactly as in :func:`decode_step_wide`;
    chunk_tokens: (G, C) right-padded page-aligned chunks, one per DISTINCT
    PREFILLING slot (every chunk's slot must be masked out of
    ``write_mask`` — it is not decoding yet); chunk_page_rows: (G,
    max_pages) their block-table rows; chunk_start / chunk_len: (G,) as in
    :func:`prefill_chunks`. Padding rows carry ``chunk_len == 0`` and
    all-zero page rows (their writes land on the null page, their ragged
    rows are skipped). G == 1 is exactly the round-5 single-chunk mixed
    dispatch.

    Under ``attn_impl == "pallas"`` all rows run as ONE
    ``ragged_paged_attention`` kernel per layer (decode slots are q_num=Q
    rows, each chunk C/q_block rows); otherwise the XLA fallback computes
    the same math over dense gathered views. Base weights only — per-row
    LoRA mixes cannot ride the fused (1, N) token axis, so EngineCore gates
    the mixed program off while adapters are resident — and single-chip
    (tp == 1; the TP meshes keep the two-dispatch path).

    Returns (decode logits (B, Q, V), per-chunk last-valid-position logits
    (G, V), cache) with ``lengths`` UNCHANGED: the engine advances decode
    lengths by accepted counts and sets each chunk slot's length, exactly
    as when :func:`decode_step_wide` and :func:`prefill_chunks` run
    separately (which this must — and tests do — match numerically).
    """
    B, Q = tokens.shape
    G, C = chunk_tokens.shape
    ps = cache.page_size
    if C % ps != 0:
        raise ValueError(f"chunk size {C} must be page-aligned (page={ps})")
    if C % q_block != 0 or q_block < 1:
        raise ValueError(f"chunk size {C} must be a multiple of the ragged "
                         f"q_block ({q_block})")
    if _tp_degree(mesh) > 1:
        raise ValueError("mixed_step is the single-chip serving path "
                         "(tp == 1); tensor-parallel meshes keep the "
                         "two-dispatch path")
    n_cp = C // ps
    maxp = page_table.shape[1]
    T = maxp * ps
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    n_ch_rows = C // q_block                                 # per chunk

    L = cache.lengths                                        # (B,)
    dec_pos = L[:, None] + jnp.arange(Q, dtype=jnp.int32)[None]     # (B, Q)
    ch_pos = (chunk_start[:, None]
              + jnp.arange(C, dtype=jnp.int32)[None])               # (G, C)
    positions = jnp.concatenate([dec_pos.reshape(1, B * Q),
                                 ch_pos.reshape(1, G * C)], axis=1)
    flat_tokens = jnp.concatenate([tokens.reshape(1, B * Q),
                                   chunk_tokens.reshape(1, G * C)],
                                  axis=1)                           # (1, N)
    h = llama.embed_tokens(params, cfg, flat_tokens)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

    # decode rows: same write/attention geometry as decode_step_wide
    attn_len = L + Q
    batch_ix = jnp.arange(B, dtype=jnp.int32)[:, None]
    ok = write_mask[:, None] & (dec_pos < T)
    rows = jnp.where(ok, page_table[batch_ix, dec_pos // ps], jnp.int32(0))
    offs = dec_pos % ps                                      # (B, Q)
    # chunk pages: same geometry as prefill_chunks
    chunk_pages = jax.vmap(
        lambda row, sp: jax.lax.dynamic_slice(row, (sp // ps,), (n_cp,)))(
        chunk_page_rows, chunk_start)                        # (G, n_cp)
    valid_through = chunk_start + chunk_len                  # (G,)

    use_pallas = (cfg.attn_impl == "pallas" and cfg.sliding_window == 0
                  and q_block >= Q
                  and pallas_ops.ragged_paged_supported(ps, HD, q_block))
    quant = cache.quantized

    if use_pallas:
        # per-row ragged metadata, shared by every layer's kernel call:
        # B decode rows first, then each chunk's C/q_block rows
        jr = jnp.arange(n_ch_rows, dtype=jnp.int32)
        row_tables = jnp.concatenate(
            [page_table, jnp.repeat(chunk_page_rows, n_ch_rows, axis=0)])
        q_num_ch = jnp.clip(chunk_len[:, None] - jr[None] * q_block,
                            0, q_block)                      # (G, n_ch_rows)
        # idle tail rows (q_num == 0) get kv_len 0, NOT the chunk's end:
        # the kernel skips their compute either way, but only a zero
        # length clamps their page-index map to one repeated block so the
        # K/V DMAs are elided too — otherwise every empty row of a short
        # final chunk would stream the whole prefix per layer for nothing
        kv_lens = jnp.concatenate(
            [attn_len, jnp.where(q_num_ch > 0, valid_through[:, None],
                                 0).reshape(-1)])
        q_pos0 = jnp.concatenate(
            [L, (chunk_start[:, None] + jr[None] * q_block).reshape(-1)])
        q_num = jnp.concatenate(
            [jnp.full((B,), Q, jnp.int32), q_num_ch.reshape(-1)])
    cache_positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    chunk_cache_positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (G, T))

    def attn_and_update(q, k, v, pools, idx):
        # q/k/v: (1, N, H|KV, HD) — B*Q decode rows, then the G*C chunk rows
        k_dec = k[:, :B * Q].reshape(B, Q, KV * HD)
        v_dec = v[:, :B * Q].reshape(B, Q, KV * HD)
        k_ch = k[:, B * Q:].reshape(G, C, KV, HD)
        v_ch = v[:, B * Q:].reshape(G, C, KV, HD)
        # chunk pages scatter first, then the decode rows — the page sets
        # are disjoint (every chunk's slot is write-masked out of decode;
        # duplicate indices only occur among padding rows, on the null page)
        flat_pages = (idx * num_pages + chunk_pages).reshape(-1)
        pools = _scatter_pages(pools, flat_pages, k_ch, v_ch, G, C, n_cp,
                               ps, KV, HD)
        flat_rows = idx * num_pages + rows                   # (B, Q)
        if quant:
            k_pool, v_pool, ks_pool, vs_pool = pools
            kq, ks = _kv_quantize(k_dec, KV, HD)
            vq, vs = _kv_quantize(v_dec, KV, HD)
            new_k = k_pool.at[flat_rows, offs].set(kq)
            new_v = v_pool.at[flat_rows, offs].set(vq)
            new_ks = ks_pool.at[flat_rows, :, offs].set(ks)
            new_vs = vs_pool.at[flat_rows, :, offs].set(vs)
            out_pools = (new_k, new_v, new_ks, new_vs)
        else:
            new_k = pools[0].at[flat_rows, offs].set(
                k_dec.astype(pools[0].dtype))
            new_v = pools[1].at[flat_rows, offs].set(
                v_dec.astype(pools[1].dtype))
            new_ks = new_vs = None
            out_pools = (new_k, new_v)
        q_dec = q[0, :B * Q].reshape(B, Q, H, HD)
        q_ch = q[0, B * Q:].reshape(G, C, H, HD)
        if use_pallas:
            pad = q_block - Q
            q_rows = q_dec if pad == 0 else jnp.pad(
                q_dec, ((0, 0), (0, pad), (0, 0), (0, 0)))
            q_rows = jnp.concatenate(
                [q_rows, q_ch.reshape(G * n_ch_rows, q_block, H, HD)])
            ctx_rows = pallas_ops.ragged_paged_attention(
                q_rows, new_k, new_v, row_tables, kv_lens, q_pos0, q_num,
                layer=idx, pages_per_layer=num_pages, k_scales=new_ks,
                v_scales=new_vs)
            ctx = jnp.concatenate(
                [ctx_rows[:B, :Q].reshape(1, B * Q, H, HD),
                 ctx_rows[B:].reshape(1, G * C, H, HD)], axis=1)
        else:
            # the two-dispatch math over dense gathered views, fused into
            # one program: decode rows then the chunks
            k_dense, v_dense = _gather_dense(
                out_pools, idx * num_pages + page_table, B, T, KV, HD,
                h.dtype)
            ctx_dec = mha_prefill(
                q_dec, k_dense, v_dense, q_positions=dec_pos,
                kv_positions=cache_positions,
                kv_mask=cache_positions < attn_len[:, None], causal=True,
                window=cfg.sliding_window)
            kc_dense, vc_dense = _gather_dense(
                out_pools, idx * num_pages + chunk_page_rows, G, T,
                KV, HD, h.dtype)
            ctx_ch = mha_prefill(
                q_ch, kc_dense, vc_dense, q_positions=ch_pos,
                kv_positions=chunk_cache_positions,
                kv_mask=chunk_cache_positions < valid_through[:, None],
                causal=True, window=cfg.sliding_window)
            ctx = jnp.concatenate([ctx_dec.reshape(1, B * Q, H, HD),
                                   ctx_ch.reshape(1, G * C, H, HD)], axis=1)
        return ctx, out_pools

    pools_in = ((cache.k, cache.v, cache.k_s, cache.v_s) if quant
                else (cache.k, cache.v))
    h, pools = llama.scan_blocks_inplace(
        cfg, h, params, pools_in, cos, sin, attn_and_update, None)
    # unembed only the rows anyone reads: every decode position + each
    # chunk's last valid position
    last_ix = (B * Q + jnp.arange(G, dtype=jnp.int32) * C
               + jnp.maximum(chunk_len - 1, 0))              # (G,)
    h_last = jnp.take_along_axis(
        h, last_ix[None, :, None].astype(jnp.int32), axis=1)  # (1, G, D)
    h_sel = jnp.concatenate([h[:, :B * Q], h_last], axis=1)
    logits = llama._unembed(cfg, params, h_sel)              # (1, B*Q+G, V)
    dec_logits = logits[0, :B * Q].reshape(B, Q, -1)
    chunk_logits = logits[0, B * Q:]                         # (G, V)
    return dec_logits, chunk_logits, PagedKVCache(
        k=pools[0], v=pools[1], lengths=cache.lengths,
        k_s=pools[2] if quant else None,
        v_s=pools[3] if quant else None)


def prefill_seq_parallel(params: llama.Params, cfg: llama.LlamaConfig,
                         tokens: jnp.ndarray, cache: PagedKVCache,
                         page_row: jnp.ndarray, slot: jnp.ndarray,
                         n_tokens: jnp.ndarray, num_pages: int, mesh,
                         adapters: Optional[llama.Params] = None,
                         impl: str = "ring",
                         ) -> Tuple[jnp.ndarray, PagedKVCache]:
    """Whole-prompt sequence-parallel prefill for one slot: ring attention
    over mesh["seq"] computes the prompt in one pass (llama.
    prefill_seq_parallel) and the collected per-layer K/V scatter into the
    slot's pages — the long-context serving path where a single chunked
    pass would be wall-clock-bound on one chip's attention.

    tokens: (1, S) right-padded, S page-aligned AND divisible by the seq
    axis; page_row: (max_pages,) block-table row; n_tokens: scalar valid
    length. Returns (last-valid-position logits (1, V), cache with
    lengths[slot] = n_tokens). Rows past n_tokens hold garbage K/V inside
    the covered pages — decode masks by length, exactly as with chunked
    prefill padding.
    """
    _, S = tokens.shape
    ps = cache.page_size
    if S % ps != 0:
        raise ValueError(f"padded prompt length {S} must be page-aligned "
                         f"(page={ps})")
    L, KV, HD = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    n_p = S // ps

    logits, k_stack, v_stack = llama.prefill_seq_parallel(
        params, cfg, tokens, mesh, seq_lens=n_tokens[None],
        adapters=adapters, impl=impl)
    # (L, 1, S, KV, HD) → page blocks (L * n_p, ps, KV*HD) in pool layout
    k_pages = k_stack[:, 0].reshape(L, n_p, ps, KV * HD)
    v_pages = v_stack[:, 0].reshape(L, n_p, ps, KV * HD)
    rows = (jnp.arange(L, dtype=jnp.int32)[:, None] * num_pages
            + page_row[None, :n_p]).reshape(-1)
    lengths = cache.lengths.at[slot].set(n_tokens)
    if cache.quantized:
        kq, ks = _kv_quantize(k_pages.reshape(L * n_p, ps, KV * HD), KV, HD)
        vq, vs = _kv_quantize(v_pages.reshape(L * n_p, ps, KV * HD), KV, HD)
        return logits, PagedKVCache(
            k=cache.k.at[rows].set(kq), v=cache.v.at[rows].set(vq),
            lengths=lengths,
            k_s=cache.k_s.at[rows].set(ks.transpose(0, 2, 1)),
            v_s=cache.v_s.at[rows].set(vs.transpose(0, 2, 1)))
    new_k = cache.k.at[rows].set(
        k_pages.reshape(L * n_p, ps, KV * HD).astype(cache.k.dtype))
    new_v = cache.v.at[rows].set(
        v_pages.reshape(L * n_p, ps, KV * HD).astype(cache.v.dtype))
    return logits, PagedKVCache(k=new_k, v=new_v, lengths=lengths)
