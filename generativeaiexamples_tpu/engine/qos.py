"""QoS admission plane: weighted-fair tenant queuing, deadline-aware
admission, and cost-modeled hedging (ROADMAP item 4's enforcement half).

Every signal this module acts on already existed — deadlines and burn
rates (PR 4), per-program device-time attribution (PR 9), retry budgets
and hedged dispatch (PR 10), per-tenant identity and resource vectors
(PR 12) — but ``Scheduler._admit`` stayed FIFO with bounded bypass, so
one antagonist tenant could starve the pool and a past-deadline request
still burned prefill programs before anyone noticed the breach.  RAGO
(arxiv 2503.14649) frames serving as a scheduling/placement search; this
is the policy layer that closes the loop:

  * **Weighted fair queuing with virtual time.**  Each tenant owns a
    virtual clock; admitting a request advances it by the request's
    estimated service COST divided by the tenant's weight
    (``APP_QOS_TENANT_WEIGHTS``, e.g. ``"acme=4,other=1,*=1"``).  Cost is
    the usage plane's resource vector basis: devtime-prorated
    device-seconds when the PR-9 ledger holds timed samples
    (``DEVTIME.phase_rates``), token counts otherwise — the same
    devtime-else-tokens fallback ``observability/usage.py`` bills with.
    The scheduler admits from the tenant with the LOWEST virtual time, so
    a flooding tenant's clock races ahead and obeying tenants keep their
    weighted share; a newly-backlogged tenant's clock floors at the
    global virtual time, so idling never banks credit.  Tenants past the
    cardinality cap fold into the usage plane's ``"other"`` bucket —
    metric labels stay bounded exactly as ``usage_*`` families do.

  * **Earliest-deadline-first within a tenant** plus **shed-before-
    prefill**: at admission, prefill+decode service time is estimated
    from ``core/perfmodel`` (measured phase rates when the ledger has
    them, the analytic envelope otherwise) and a sheddable request whose
    remaining deadline budget cannot cover it is shed LOUDLY
    (``slo_outcome="shed"``) before any prefill program is dispatched —
    the breach is declared for free instead of discovered after burning
    the chip.

  * **Slack-aware preemption**: page-pressure victim selection weighs
    tenant overuse (virtual-time lead) and SLO slack, not just slot age —
    overusing tenants spill/preempt first, and a stream about to miss its
    deadline is preempted last.

  * **Cost-modeled hedging** (:func:`hedge_delay`): the router's static
    ``APP_ROUTER_HEDGE_S`` scales with the candidate worker's advertised
    queue depth and the expected service time — a loaded-but-healthy
    primary is given the time its queue legitimately needs before a
    duplicate dispatch burns a second replica's cycles.

Gate: ``APP_QOS=off|fair`` (bare env wins over the ``APP_ENGINE_QOS``
config field).  ``off`` is the default and is BEHAVIOR-IDENTICAL to the
pre-QoS FIFO scheduler — the scheduler holds no policy object and makes
zero qos calls on the serving path (test-enforced with the APP_DEVTIME /
APP_CHAOS zero-overhead pattern).  Token-rate quotas come from
``APP_QOS_TOKENS_PER_S`` (same ``tenant=value`` map syntax; tenants
without an entry are unmetered).  Surfaces: ``qos_*`` metric families
and ``GET /debug/qos`` (server/common.py).  docs/scheduling.md is the
operator guide.
"""

from __future__ import annotations

import heapq
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.core.config import env_int
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import usage as usage_mod
from generativeaiexamples_tpu.observability.lockwatch import tracked_rlock

logger = logging.getLogger(__name__)

MODE_ENV = "APP_QOS"
WEIGHTS_ENV = "APP_QOS_TENANT_WEIGHTS"
TOKENS_PER_S_ENV = "APP_QOS_TOKENS_PER_S"

_MODES = ("off", "fair")

# EDF slack is clamped into this band before victim scoring so one
# deadline-free stream (slack = +inf) cannot erase the overuse signal
_SLACK_CAP_S = 600.0


def parse_tenant_map(raw: str, name: str = "") -> Tuple[Dict[str, float],
                                                        Optional[float]]:
    """Parse a ``tenant=value,tenant2=value2,*=default`` map (the
    ``APP_QOS_TENANT_WEIGHTS`` / ``APP_QOS_TOKENS_PER_S`` syntax) into
    ``(per_tenant, default)``.  Tenant keys are sanitized exactly like the
    usage plane's (one identity space); malformed entries warn and drop —
    a typo'd knob must never take the serving path down."""
    out: Dict[str, float] = {}
    default: Optional[float] = None
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        if not sep:
            logger.warning("ignoring malformed %s entry %r (want "
                           "tenant=value)", name or "tenant map", part)
            continue
        try:
            num = float(value.strip())
        except ValueError:
            logger.warning("ignoring non-numeric %s entry %r",
                           name or "tenant map", part)
            continue
        key = key.strip()
        if key == "*":
            default = num
            continue
        tenant = usage_mod.sanitize_tenant(key)
        if not tenant:
            logger.warning("ignoring empty tenant key in %s entry %r",
                           name or "tenant map", part)
            continue
        if num <= 0:
            # a zero/negative weight or rate would starve the tenant
            # forever — the no-starvation invariant the fuzz harness
            # asserts; drop loudly instead
            logger.warning("ignoring non-positive %s for tenant %r "
                           "(would starve it)", name or "value", tenant)
            continue
        out[tenant] = num
    return out, default


def request_remaining_s(req: Any, now: Optional[float] = None
                        ) -> Optional[float]:
    """Remaining deadline budget of a scheduler Request right now.
    ``Request.deadline_s`` is the REMAINING budget stamped at submit (the
    cross-process contract — never an absolute instant), so remaining =
    deadline_s - elapsed-since-submit, on the same perf_counter clock the
    timeline stamps use.  None = no deadline."""
    deadline = getattr(req, "deadline_s", None)
    if deadline is None:
        return None
    submitted = getattr(req, "submitted_at", None)
    if submitted is None:
        return float(deadline)
    now = clock.perf() if now is None else now
    return float(deadline) - (now - submitted)


# Cost-modeled hedge trigger — the ONE implementation lives in
# server/resilience.py (jax-free: the routing process consumes it without
# importing the engine package); re-exported here because this module is
# the QoS plane's documented surface.
from generativeaiexamples_tpu.server.resilience import hedge_delay  # noqa: E402,F401


def _mono_clock() -> float:
    """Default QosPolicy clock: the injected process clock (virtual under
    ops/simulate.py, time.monotonic live)."""
    return clock.mono()


class QosPolicy:
    """Per-process admission policy: WFQ virtual time + EDF + quotas.

    Thread-safety: consulted by the engine driver thread (ordering,
    charges, victim picks) and read by HTTP debug threads; one RLock
    guards the tenant tables.  ``clock`` must be monotonic (tests inject
    a fake — the quota buckets and nothing else read it; request-deadline
    math stays on the perf clock the Request stamps use). The default is
    the process's injected mono clock (core/clock.py), so a simulated
    policy runs on virtual time with no constructor plumbing."""

    def __init__(self,
                 weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0,
                 tokens_per_s: Optional[Dict[str, float]] = None,
                 perf_model: Optional[Any] = None,
                 batch_hint: int = 1,
                 max_tenants: Optional[int] = None,
                 clock=None) -> None:
        self._lock = tracked_rlock("qos._lock")
        self._clock = clock if clock is not None else _mono_clock
        self._weights = dict(weights or {})
        self._default_weight = max(1e-6, float(default_weight))
        self._quota_rate = dict(tokens_per_s or {})
        self._perf = perf_model
        self._batch_hint = max(1, int(batch_hint))
        # bounded identity space: configured tenants are always first-class;
        # the rest admit until the cap, then fold into "other" (the usage
        # plane's overflow bucket — metric labels stay bounded)
        self._max_tenants = max(
            len(self._weights) + len(self._quota_rate) + 2,
            max_tenants if max_tenants is not None
            else env_int("APP_USAGE_MAX_TENANTS", 64))
        self._known = set(self._weights) | set(self._quota_rate)
        # WFQ state: per-tenant virtual clocks + the global floor
        self._vtime: Dict[str, float] = {}
        self._global_v = 0.0
        # lock-free overuse snapshot (tenant_overuse_hint): rebuilt under
        # the lock at every charge/settle/order, read WITHOUT it — the KV
        # tier prices evictions under its own lock, and taking the QoS
        # lock there orders kv_tier._lock -> qos._lock (half a deadlock
        # cycle lockwatch exists to catch)
        self._overuse_snap: Tuple[frozenset, Dict[str, float]] = (
            frozenset(self._known), {})
        # token-bucket quotas: level per metered tenant (starts full at
        # the burst cap = 2 s of rate), last-refill stamp
        self._bucket: Dict[str, float] = {
            t: self._burst(t) for t in self._quota_rate}
        self._refilled_at: Optional[float] = None
        self._throttled_now: set = set()
        # admitted-but-unsettled reservations: request_id -> (tenant,
        # virtual cost charged, quota tokens reserved, the rate basis the
        # cost was computed in).  The fuzz harness asserts this drains to
        # empty through preemptions, evacuations, and driver resets —
        # quota conservation.
        self._outstanding: Dict[str, Tuple[str, float, int,
                                           Optional[Tuple[float,
                                                          float]]]] = {}
        self._depth_tenants: set = set()   # tenants with a nonzero gauge
        # estimate-rate cache (devtime phase_rates takes a lock and walks
        # the ledger; one read per ~250 ms is plenty for admission math)
        self._rates_cache: Tuple[float, Optional[float], Optional[float],
                                 str] = (-1.0, None, None, "none")
        self._est_override: Optional[Tuple[float, float]] = None

    # ------------------------------------------------------------ identity

    def canonical(self, tenant: Any) -> str:
        """The bounded label-safe key ``tenant`` schedules under (folds
        past the cap into ``"other"``, mirroring the usage ledger)."""
        t = usage_mod.sanitize_tenant(tenant) or usage_mod.DEFAULT_TENANT
        with self._lock:
            if (t in self._known or len(self._known) < self._max_tenants
                    or t in (usage_mod.OVERFLOW_TENANT,
                             usage_mod.DEFAULT_TENANT)):
                self._known.add(t)
                return t
        return usage_mod.OVERFLOW_TENANT

    def _weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self._default_weight)

    def _burst(self, tenant: str) -> float:
        """Quota bucket capacity: two seconds of the tenant's rate (so a
        paced client rides through scheduler tick jitter), never below
        one token (a positive rate must always make progress)."""
        return max(1.0, 2.0 * self._quota_rate.get(tenant, 0.0))

    # ----------------------------------------------------------- estimates

    def configure_estimate(self, prefill_s_per_tok: Optional[float],
                           decode_s_per_tok: Optional[float]) -> None:
        """Pin explicit service-time rates (tests, bench A/B arms) —
        overrides both the devtime measurement and the analytic model."""
        with self._lock:
            if prefill_s_per_tok is None or decode_s_per_tok is None:
                self._est_override = None
            else:
                self._est_override = (float(prefill_s_per_tok),
                                      float(decode_s_per_tok))
            self._rates_cache = (-1.0, None, None, "none")

    def _rates(self) -> Tuple[Optional[float], Optional[float], str]:
        """(prefill_s_per_tok, decode_s_per_tok, basis).  Preference:
        explicit override → devtime-measured phase rates (the PR-9 ledger,
        true amortized costs) → the analytic perfmodel envelope (prefill
        compute-bound at peak FLOPs; decode weight-read-bound, amortized
        over the configured batch) → (None, None, "none") when nothing
        can estimate (CPU fakes with APP_DEVTIME=off) — shedding then
        never fires, it only ever turns ON with a defensible number."""
        with self._lock:
            if self._est_override is not None:
                pf, dc = self._est_override
                return pf, dc, "override"
            stamp, pf, dc, basis = self._rates_cache
        now = self._clock()
        if stamp >= 0 and now - stamp < 0.25:
            return pf, dc, basis
        pf = dc = None
        basis = "none"
        try:
            from generativeaiexamples_tpu.observability.devtime import DEVTIME
            measured = DEVTIME.phase_rates()
            pf, dc = measured.get("prefill"), measured.get("decode")
            if pf is not None and dc is not None:
                basis = "devtime"
        except Exception:
            logger.debug("devtime phase rates unavailable", exc_info=True)
        if basis == "none" and self._perf is not None:
            peak_flops = getattr(self._perf, "peak_flops", None)
            peak_bw = getattr(self._perf, "peak_bw", None)
            if peak_flops and peak_bw:
                pf = 2.0 * self._perf.n_params / peak_flops
                dc = (self._perf.param_bytes / peak_bw) / self._batch_hint
                basis = "analytic"
        with self._lock:
            self._rates_cache = (now, pf, dc, basis)
        return pf, dc, basis

    def estimate_service_s(self, n_prompt: int,
                           max_tokens: int) -> Optional[float]:
        """Expected prefill+decode service seconds for a request, or None
        when no basis exists (shed-before-prefill stays off then)."""
        pf, dc, _basis = self._rates()
        if pf is None or dc is None:
            return None
        return pf * max(0, int(n_prompt)) + dc * max(0, int(max_tokens))

    def _charge_rates(self) -> Optional[Tuple[float, float]]:
        """The (prefill, decode) per-token rates a charge is costed with,
        or None for the token-count basis. Captured ONCE per admission and
        stored with the reservation, so the settle-side true-up always
        subtracts like units — a devtime basis arming mid-request must
        not mix token counts with device-seconds."""
        pf, dc, _basis = self._rates()
        if pf is not None and dc is not None:
            return (pf, dc)
        return None

    @staticmethod
    def _cost_with(rates: Optional[Tuple[float, float]], n_prompt: int,
                   n_out: int) -> float:
        if rates is None:
            return float(n_prompt + n_out)
        pf, dc = rates
        return pf * n_prompt + dc * n_out

    def _cost(self, req: Any) -> float:
        """Virtual-time service cost of one request: device-seconds when
        a rate basis exists, token counts otherwise (the usage plane's
        devtime-else-tokens billing basis)."""
        return self._cost_with(self._charge_rates(),
                               len(getattr(req, "prompt_ids", []) or []),
                               int(getattr(req, "max_tokens", 0) or 0))

    def _cost_actual(self, req: Any,
                     rates: Optional[Tuple[float, float]]) -> float:
        """Realized cost at settle time, in the SAME basis the charge
        used: actual completion tokens, and no prompt component for
        KV-handoff imports (their prefill billed on the prefill worker —
        mirrors usage.bill_request)."""
        imported = getattr(req, "kv_import_s", None) is not None
        n_prompt = 0 if imported else len(
            getattr(req, "prompt_ids", []) or [])
        out_toks = int(getattr(req, "completion_tokens", 0) or 0)
        return self._cost_with(rates, n_prompt, out_toks)

    # ------------------------------------------------------------ ordering

    def _refill_locked(self, now: float) -> None:
        if not self._quota_rate:
            return
        last = self._refilled_at
        self._refilled_at = now
        if last is None:
            return
        dt = max(0.0, now - last)
        for t, rate in self._quota_rate.items():
            self._bucket[t] = min(self._burst(t),
                                  self._bucket.get(t, 0.0) + rate * dt)

    def _edf_key(self, job: Any, now: float) -> Tuple[int, float, float]:
        """Within-tenant order: resumes first (they already streamed to a
        client and may pin spill/grammar state), then earliest remaining
        deadline, then arrival."""
        req = job.request
        resume = bool(getattr(job, "gen_ids", None)) \
            or getattr(job, "spill", None) is not None
        rem = request_remaining_s(req, now)
        return (0 if resume else 1,
                rem if rem is not None else float("inf"),
                getattr(req, "submitted_at", 0.0) or 0.0)

    def order(self, jobs: List[Any], limit: int) -> List[Any]:
        """Admission-priority prefix of the pending queue: per-tenant EDF
        merged by weighted-fair virtual time, quota-throttled tenants held
        back this pass (their jobs stay pending; the bucket refills on the
        injected clock, so every request still eventually dispatches).
        Returns at most ``limit`` jobs; the caller's page-fit /
        bounded-bypass machinery runs unchanged on top."""
        if not jobs:
            # the backlog drained: zero the depth gauges of tenants that
            # had one, or the surface reports a queue that no longer exists
            for t in self._depth_tenants:
                REGISTRY.gauge("qos_queue_depth", labels={"tenant": t}
                               ).set(0)
            self._depth_tenants = set()
            return []
        now_q = self._clock()
        now_req = clock.perf()
        limit = max(0, int(limit))
        buckets: Dict[str, List[Any]] = {}
        for job in jobs:
            buckets.setdefault(self.canonical(job.request.tenant),
                               []).append(job)
        depths = {t: len(js) for t, js in buckets.items()}
        for t, js in buckets.items():
            # only the merge's consumable prefix needs ordering: a flood
            # tenant backlogging thousands must not cost a full sort per
            # admission pass on the driver thread — nsmallest is
            # O(n log limit) and the merge below never reads past `limit`
            if len(js) > limit:
                buckets[t] = heapq.nsmallest(
                    limit, js, key=lambda j: self._edf_key(j, now_req))
            else:
                js.sort(key=lambda j: self._edf_key(j, now_req))
        out: List[Any] = []
        with self._lock:
            self._refill_locked(now_q)
            throttled = {t for t in buckets
                         if t in self._quota_rate
                         and self._bucket.get(t, 0.0) <= 0.0}
            for t in throttled - self._throttled_now:
                REGISTRY.counter("qos_quota_throttles_total",
                                 labels={"tenant": t}).inc()
            self._throttled_now = throttled
            live = [t for t in buckets if t not in throttled]
            vt = {t: max(self._vtime.get(t, self._global_v), self._global_v)
                  for t in buckets}
            if live:
                # the global clock tracks the busiest backlog's floor so
                # a tenant arriving later starts at "now", not at zero
                self._global_v = max(self._global_v,
                                     min(vt[t] for t in live))
            idx = {t: 0 for t in buckets}
            while len(out) < limit:
                cands = [t for t in live if idx[t] < len(buckets[t])]
                if not cands:
                    break
                t = min(cands, key=lambda name: (vt[name], name))
                job = buckets[t][idx[t]]
                idx[t] += 1
                out.append(job)
                vt[t] += self._cost(job.request) / self._weight(t)
            self._publish_overuse_locked()
        # gauges outside the lock (REGISTRY locks internally); tenants
        # whose backlog drained reset to 0 so the surface never lies
        # (depths captured pre-truncation — the gauge reports the real
        # backlog, not the merge's bounded prefix)
        seen = set(buckets)
        for t, depth in depths.items():
            REGISTRY.gauge("qos_queue_depth", labels={"tenant": t}
                           ).set(depth)
        for t in self._depth_tenants - seen:
            REGISTRY.gauge("qos_queue_depth", labels={"tenant": t}).set(0)
        self._depth_tenants = seen
        return out

    # ------------------------------------------------------------- charges

    def charge_admission(self, req: Any) -> None:
        """Charge a FIRST admission: advance the tenant's virtual clock by
        estimated cost / weight, reserve quota tokens (prompt + the full
        generation budget; settle refunds the unrun part), and record the
        reservation for conservation accounting.  Resumes re-admit without
        re-charging — preemption must not double-bill."""
        tenant = self.canonical(getattr(req, "tenant", ""))
        rates = self._charge_rates()
        est = self._cost_with(rates,
                              len(getattr(req, "prompt_ids", []) or []),
                              int(getattr(req, "max_tokens", 0) or 0))
        reserve = (len(getattr(req, "prompt_ids", []) or [])
                   + int(getattr(req, "max_tokens", 0) or 0))
        now = self._clock()
        with self._lock:
            self._refill_locked(now)
            if tenant in self._quota_rate:
                # dip-below-zero semantics: admission requires a positive
                # bucket, the charge may overdraw — a request larger than
                # the burst still makes progress instead of starving
                self._bucket[tenant] = self._bucket.get(tenant, 0.0) \
                    - reserve
            v = max(self._vtime.get(tenant, self._global_v),
                    self._global_v) + est / self._weight(tenant)
            self._vtime[tenant] = v
            rid = str(getattr(req, "request_id", "") or id(req))
            self._outstanding[rid] = (tenant, est, reserve, rates)
            self._publish_overuse_locked()
        REGISTRY.gauge("qos_virtual_time", labels={"tenant": tenant}
                       ).set(round(v, 6))
        REGISTRY.counter("qos_admissions_total",
                         labels={"tenant": tenant}).inc()

    def settle(self, req: Any) -> None:
        """Close a request's reservation at its terminal event (finish,
        failure, evacuation, driver reset): true the tenant's virtual
        clock up/down by actual-vs-estimated cost and refund the unused
        quota reservation.  Idempotent (the reservation pops once), and a
        never-admitted request (shed, oversized) is a no-op."""
        rid = str(getattr(req, "request_id", "") or id(req))
        with self._lock:
            entry = self._outstanding.pop(rid, None)
            if entry is None:
                return
            tenant, est, reserved, rates = entry
            # true-up in the CHARGE's units and through the tenant's
            # weight — the charge advanced the clock by est/weight, so
            # the correction is (actual-est)/weight, or a high-weight
            # tenant finishing under budget would claw back weight-times
            # what it was ever charged
            actual = self._cost_actual(req, rates)
            self._vtime[tenant] = max(
                0.0, self._vtime.get(tenant, 0.0)
                + (actual - est) / self._weight(tenant))
            if tenant in self._quota_rate:
                imported = getattr(req, "kv_import_s", None) is not None
                used = (0 if imported
                        else len(getattr(req, "prompt_ids", []) or [])) \
                    + int(getattr(req, "completion_tokens", 0) or 0)
                self._bucket[tenant] = min(
                    self._burst(tenant),
                    self._bucket.get(tenant, 0.0)
                    + max(0, reserved - used))
            v = self._vtime[tenant]
            self._publish_overuse_locked()
        REGISTRY.gauge("qos_virtual_time", labels={"tenant": tenant}
                       ).set(round(v, 6))

    # ----------------------------------------------------------- shedding

    def should_shed(self, req: Any, n_tokens: int,
                    now: Optional[float] = None) -> Optional[float]:
        """Shed-before-prefill check: the estimated prefill+decode service
        time when the request's remaining deadline budget cannot cover it
        (the caller sheds with that estimate in the error text), else
        None.  No estimate basis, no deadline → never shed here (the
        burn-rate shedder in observability/slo.py still applies)."""
        est = self.estimate_service_s(
            n_tokens, int(getattr(req, "max_tokens", 0) or 0))
        if est is None:
            return None
        rem = request_remaining_s(req, now)
        if rem is None or rem >= est:
            return None
        return est

    def note_shed(self, req: Any) -> None:
        REGISTRY.counter("qos_shed_before_prefill_total",
                         labels={"tenant": self.canonical(
                             getattr(req, "tenant", ""))}).inc()

    # ---------------------------------------------------------- preemption

    def pick_victim(self, jobs: List[Any]) -> Any:
        """Slack-aware page-pressure victim: prefer the job whose tenant
        is furthest AHEAD of the global virtual clock (overuse — the
        flooding tenant pays for the pool pressure it causes), then the
        job with the most SLO slack (it can absorb a spill/recompute
        without breaching), then the youngest admission (the FIFO
        tie-break, so equal-standing tenants behave exactly as before).
        The caller's spill path applies to whoever is picked — overusing
        tenants spill first by construction."""
        now = clock.perf()
        with self._lock:
            vt = dict(self._vtime)
            floor = self._global_v

        def score(job: Any) -> Tuple[float, float, int]:
            tenant = self.canonical(job.request.tenant)
            overuse = max(0.0, vt.get(tenant, floor) - floor)
            rem = request_remaining_s(job.request, now)
            if rem is None:
                slack = _SLACK_CAP_S
            else:
                left = max(0, int(job.request.max_tokens)
                           - len(getattr(job, "gen_ids", []) or []))
                est = self.estimate_service_s(0, left) or 0.0
                slack = min(max(rem - est, -_SLACK_CAP_S), _SLACK_CAP_S)
            return (round(overuse, 4), slack,
                    int(getattr(job, "admit_seq", 0)))

        return max(jobs, key=score)

    def tenant_overuse_s(self, tenant: str) -> float:
        """How far AHEAD of the global virtual clock a tenant is running
        (seconds of weighted service beyond its fair share; 0.0 for
        tenants at or behind the clock). The same overuse signal
        :meth:`pick_victim` ranks on, exported so the prefix KV tier's
        eviction pricing (engine/kv_tier.py) can compose with it: cached
        prefixes contributed by a flooding tenant evict first, exactly
        as that tenant's live jobs spill first."""
        t = self.canonical(tenant)
        with self._lock:
            return max(0.0, self._vtime.get(t, self._global_v)
                       - self._global_v)

    def _publish_overuse_locked(self) -> None:
        """Rebuild the lock-free overuse snapshot (one atomic whole-tuple
        rebind — readers never observe a mid-update dict).  Caller holds
        ``_lock``."""
        g = self._global_v
        snap = {t: v - g for t, v in self._vtime.items() if v > g}
        self._overuse_snap = (frozenset(self._known), snap)

    def tenant_overuse_hint(self, tenant: str) -> float:
        """:meth:`tenant_overuse_s` from the published snapshot, WITHOUT
        taking the QoS lock — the read the prefix KV tier's eviction
        pricing uses *under its own lock* (engine/kv_tier.py).  At most
        one charge/settle/order stale, which is fine for an eviction
        bias; never taking the lock is what keeps the static lock graph
        (and lockwatch's witness graph) free of a kv_tier->qos edge."""
        known, snap = self._overuse_snap
        t = usage_mod.sanitize_tenant(tenant) or usage_mod.DEFAULT_TENANT
        if t not in known:
            t = usage_mod.OVERFLOW_TENANT
        return snap.get(t, 0.0)

    # ----------------------------------------------------------- reporting

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /debug/qos`` body."""
        pf, dc, basis = self._rates()
        with self._lock:
            tenants = sorted(self._known
                             | set(self._vtime) | set(self._bucket))
            body = {
                t: {
                    "weight": self._weight(t),
                    "virtual_time": round(self._vtime.get(t, 0.0), 6),
                    "tokens_per_s": self._quota_rate.get(t),
                    "quota_bucket_tokens": (
                        round(self._bucket[t], 3)
                        if t in self._bucket else None),
                    "throttled": t in self._throttled_now,
                }
                for t in tenants
            }
            out = {
                "enabled": True,
                "mode": "fair",
                "default_weight": self._default_weight,
                "global_virtual_time": round(self._global_v, 6),
                "outstanding_admissions": len(self._outstanding),
                "max_tenants": self._max_tenants,
                "tenants": body,
            }
        out["estimate"] = {
            "basis": basis,
            "prefill_s_per_tok": (round(pf, 9) if pf is not None else None),
            "decode_s_per_tok": (round(dc, 9) if dc is not None else None),
        }
        return out

    # ------------------------------------------------- conservation (tests)

    def outstanding(self) -> int:
        """Open admission reservations — the fuzz harness asserts this
        drains to zero (quota conservation through preemptions,
        evacuations, and driver resets)."""
        with self._lock:
            return len(self._outstanding)


# ---------------------------------------------------------------------------
# process-global registration (the /debug/qos surface answers from here,
# like server/failover.register_router)
# ---------------------------------------------------------------------------

_POLICY: Optional[QosPolicy] = None


def register_policy(policy: Optional[QosPolicy]) -> None:
    global _POLICY
    _POLICY = policy


def current_policy() -> Optional[QosPolicy]:
    return _POLICY


def debug_payload() -> Dict[str, Any]:
    policy = _POLICY
    if policy is None:
        return {"enabled": False, "mode": qos_mode(),
                "hint": "set APP_QOS=fair (engine worker env) to enable "
                        "the admission plane; docs/scheduling.md"}
    return policy.snapshot()


def qos_mode(cfg: Any = None) -> str:
    """Resolve the plane's mode: the bare APP_QOS env wins (the
    APP_DEVTIME / APP_KV_SPILL_MB override convention), else the engine
    config field, else off.  Unknown values warn and fall back to off —
    a typo must never change admission behavior silently to 'sort of
    on'."""
    raw = (os.environ.get(MODE_ENV, "").strip().lower()
           or str(getattr(cfg, "qos", "") or "").strip().lower() or "off")
    if raw not in _MODES:
        logger.warning("unknown %s=%r; falling back to off (valid: %s)",
                       MODE_ENV, raw, "|".join(_MODES))
        return "off"
    return raw


def policy_from_env(cfg: Any = None, perf_model: Any = None,
                    batch_hint: int = 1) -> Optional[QosPolicy]:
    """The scheduler's construction seam: None unless APP_QOS=fair —
    off-mode schedulers hold NO policy object and the admission path
    stays byte-identical FIFO (one ``is not None`` check, the
    APP_CHAOS/APP_DEVTIME zero-overhead pattern)."""
    if qos_mode(cfg) != "fair":
        # an off-mode scheduler REPLACING a fair one must also replace
        # the registration (latest-built wins, like register_router) —
        # /debug/qos must never serve a dead policy's state as enabled
        register_policy(None)
        return None
    weights, w_default = parse_tenant_map(
        os.environ.get(WEIGHTS_ENV, ""), WEIGHTS_ENV)
    quotas, q_default = parse_tenant_map(
        os.environ.get(TOKENS_PER_S_ENV, ""), TOKENS_PER_S_ENV)
    if q_default is not None:
        logger.warning("%s: '*' default rates are not applied (unmetered "
                       "tenants stay unmetered — a universal rate would "
                       "throttle the anon bucket too); name tenants "
                       "explicitly", TOKENS_PER_S_ENV)
    policy = QosPolicy(weights=weights,
                       default_weight=(w_default if w_default is not None
                                       else 1.0),
                       tokens_per_s=quotas,
                       perf_model=perf_model,
                       batch_hint=batch_hint)
    register_policy(policy)
    return policy
