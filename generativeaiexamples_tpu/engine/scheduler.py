"""Continuous-batching scheduler: request queue → slots → token streams.

The host-side orchestrator around `EngineCore` — the in-tree stand-in for
TRT-LLM's inflight batcher (ref: NIM container, docker-compose-nim-ms.yaml:2-28).
One driver thread owns the device: it admits pending requests into free decode
slots (prefill + insert), then steps the whole slot batch, fanning sampled
tokens out to per-request queues. Callers (the aiohttp server or in-process
chains) block on those queues — a thread-safe iterator of text deltas.

Scheduling policy: prefill-priority admission (new requests are inserted as
soon as a slot frees, keeping batch occupancy high, which is what determines
tok/s on the MXU); decode runs whenever any slot is active. The device only
syncs on small (B,) arrays per step — KV stays resident.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence

import jax
import numpy as np

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.engine.engine import DecodeState, EngineCore
from generativeaiexamples_tpu.engine.tokenizer import IncrementalDetokenizer, Tokenizer

logger = logging.getLogger(__name__)

_STOP = object()


@dataclass
class Request:
    prompt_ids: List[int]
    max_tokens: int = 128
    temperature: float = 0.7
    top_k: int = 0
    top_p: float = 1.0
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    # filled by the scheduler:
    out_queue: "queue.Queue" = field(default_factory=queue.Queue)
    submitted_at: float = field(default_factory=time.perf_counter)
    first_token_at: Optional[float] = None
    completion_tokens: int = 0
    error: Optional[str] = None


@dataclass
class _SlotInfo:
    request: Request
    detok: IncrementalDetokenizer


class Scheduler:
    """Drives an EngineCore from a single background thread."""

    def __init__(self, core: EngineCore, tokenizer: Tokenizer) -> None:
        self.core = core
        self.tokenizer = tokenizer
        self._pending: "queue.Queue" = queue.Queue()
        self._slots: Dict[int, _SlotInfo] = {}
        self._free: List[int] = list(range(core.batch))
        self._state: DecodeState = core.init_state()
        self._rng = jax.random.PRNGKey(1234)
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, name="engine-driver",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                # Driver still mid-step (e.g. a long XLA compile): touching
                # _slots/_free concurrently would corrupt bookkeeping — leave
                # cleanup to the driver, which checks _running after the step.
                logger.warning("driver thread still busy at stop(); "
                               "skipping forced cleanup")
                return
        self._fail_all("scheduler stopped")

    def _fail_all(self, reason: str) -> None:
        """Unblock every queued and in-flight consumer (shutdown/crash path)."""
        while True:
            try:
                req: Request = self._pending.get_nowait()
            except queue.Empty:
                break
            req.error = reason
            req.out_queue.put(_STOP)
        for slot, info in list(self._slots.items()):
            info.request.error = reason
            info.request.out_queue.put(_STOP)
            del self._slots[slot]
            self._free.append(slot)

    def submit(self, request: Request) -> Request:
        """Enqueue; stream deltas via `iter_text(request)`."""
        self._pending.put(request)
        self._wake.set()
        REGISTRY.counter("requests_submitted").inc()
        return request

    def iter_text(self, request: Request) -> Iterator[str]:
        """Blocking iterator over the request's text deltas."""
        while True:
            item = request.out_queue.get()
            if item is _STOP:
                return
            yield item

    def generate(self, prompt_ids: Sequence[int], **kw) -> str:
        """Synchronous convenience: submit and join the full text."""
        req = Request(prompt_ids=list(prompt_ids), **kw)
        self.submit(req)
        return "".join(self.iter_text(req))

    # ------------------------------------------------------------- internals

    def _admit(self) -> None:
        """Prefill pending requests into free slots."""
        while self._free and not self._pending.empty():
            try:
                req: Request = self._pending.get_nowait()
            except queue.Empty:
                return
            if len(req.prompt_ids) >= self.core.buckets[-1]:
                # truncate from the left (keep the end of the prompt) to fit
                req.prompt_ids = req.prompt_ids[-(self.core.buckets[-1] - 1):]
            self._rng, sub = jax.random.split(self._rng)
            t0 = time.perf_counter()
            result = self.core.prefill(req.prompt_ids, req.temperature,
                                       req.top_k, req.top_p, sub)
            first_tok = int(jax.device_get(result[0])[0])
            req.first_token_at = time.perf_counter()
            REGISTRY.histogram("ttft_s").observe(req.first_token_at - req.submitted_at)
            REGISTRY.histogram("prefill_s").observe(req.first_token_at - t0)

            detok = IncrementalDetokenizer(self.tokenizer)
            if first_tok == self.core.eos_id or req.max_tokens <= 1:
                if first_tok != self.core.eos_id:
                    req.completion_tokens = 1
                    req.out_queue.put(detok.push(first_tok) + detok.flush())
                req.out_queue.put(_STOP)
                REGISTRY.counter("requests_completed").inc()
                continue
            slot = self._free.pop()
            self._state = self.core.insert(
                self._state, result, slot, len(req.prompt_ids), req.max_tokens,
                req.temperature, req.top_k, req.top_p)
            req.completion_tokens = 1
            delta = detok.push(first_tok)
            if delta:
                req.out_queue.put(delta)
            self._slots[slot] = _SlotInfo(request=req, detok=detok)

    def _step(self) -> None:
        self._state, out = self.core.decode(self._state)
        sampled = np.asarray(jax.device_get(out["sampled"]))
        emitted = np.asarray(jax.device_get(out["emitted"]))
        done = np.asarray(jax.device_get(out["done"]))
        hit_eos = np.asarray(jax.device_get(out["hit_eos"]))
        REGISTRY.counter("decode_steps").inc()
        REGISTRY.counter("tokens_generated").inc(int(emitted.sum()))
        for slot, info in list(self._slots.items()):
            if not emitted[slot]:
                continue
            if not (done[slot] and hit_eos[slot]):
                info.request.completion_tokens += 1
                delta = info.detok.push(int(sampled[slot]))
                if delta:
                    info.request.out_queue.put(delta)
            if done[slot]:
                tail = info.detok.flush()
                if tail:
                    info.request.out_queue.put(tail)
                info.request.out_queue.put(_STOP)
                del self._slots[slot]
                self._free.append(slot)
                REGISTRY.counter("requests_completed").inc()
                REGISTRY.histogram("request_latency_s").observe(
                    time.perf_counter() - info.request.submitted_at)

    def _loop(self) -> None:
        logger.info("engine driver thread started (slots=%d)", self.core.batch)
        while self._running:
            try:
                self._admit()
                if self._slots:
                    self._step()
                else:
                    # idle: wait for work without burning the core
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            except Exception:
                # Fail loudly but keep the driver alive: release every blocked
                # consumer, reset device state, and continue serving — a dead
                # silent driver with /health green is the worst failure mode.
                logger.exception("engine driver step failed; resetting state")
                REGISTRY.counter("driver_errors").inc()
                self._fail_all("engine error")
                self._state = self.core.init_state()
        self._fail_all("scheduler stopped")
        logger.info("engine driver thread stopped")
