"""Continuous-batching scheduler: request queue → pages/slots → token streams.

The host-side orchestrator around `EngineCore` — the in-tree stand-in for
TRT-LLM's inflight batcher (ref: NIM container, docker-compose-nim-ms.yaml:2-28).
One driver thread owns the device; each tick it

  1. **admits** pending requests: allocates a slot and the prompt's KV pages
     (FIFO with bounded-bypass skip-ahead — later prompts that fit may pass
     a page-blocked head a limited number of times, see _admit);
  2. runs **one prefill chunk** of the oldest admission — chunked prefill
     interleaves with decode, so active slots never stall for a whole prompt
     and arbitrarily long prompts are processed without truncation;
  3. runs **one decode step** over all active slots, fanning sampled tokens
     out to per-request queues (thread-safe iterators of text deltas).

Page management: the scheduler mirrors the device block table on the host,
growing a slot's page list as decode crosses page boundaries. When the pool
is exhausted, the *youngest* active slot is preempted: its pages are freed
and the request re-queued as a resume (prompt + tokens generated so far), so
its stream continues seamlessly after re-prefill — recompute-style preemption,
the same policy the reference's paged batcher applies under memory pressure.

Requests whose prompts cannot fit the cache at all are failed loudly (the
chain server also caps prompt length at the API, ref server.py:61-66) —
never silently truncated.
"""

from __future__ import annotations

import heapq
import logging
import math
import os
import queue
import threading
import uuid
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import chaos as chaos_mod
from generativeaiexamples_tpu.observability import forensics as forensics_mod
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability import usage as usage_mod
from generativeaiexamples_tpu.observability.devtime import DEVTIME, pow2_bucket
from generativeaiexamples_tpu.observability.flight import FLIGHT, REQUEST_LOG
from generativeaiexamples_tpu.observability.lockwatch import tracked_lock
from generativeaiexamples_tpu.observability.trace import TRACE
from generativeaiexamples_tpu.engine.engine import (
    DecodeState, EngineCore, bits_to_f32, unpack_decode_out)
from generativeaiexamples_tpu.engine import qos as qos_mod
from generativeaiexamples_tpu.engine.prefix_cache import chain_hashes
from generativeaiexamples_tpu.engine import kv_tier as kv_tier_mod
from generativeaiexamples_tpu.engine.kv_tier import (
    KVSpillPool, PrefixKVTier, spill_budget_bytes, tier_disk_bytes, tier_mode)
from generativeaiexamples_tpu.engine.tokenizer import IncrementalDetokenizer, Tokenizer

logger = logging.getLogger(__name__)

_STOP = object()


def _fetch(arr, metric: str = "fetch_rtt_s", steps: int = 0) -> np.ndarray:
    """Device→host fetch, run on a fetcher thread (releases the GIL during
    the transfer, so it overlaps the driver thread's dispatching).
    ``metric`` keeps the packed-decode transfers (what pipeline-depth
    tuning reads) and the tiny first-token scalars in separate histograms.
    ``steps`` is how many decode steps this one transfer amortises (K for
    the per-step path, K·M for a multi-step dispatch; 0 for non-decode
    scalars) — it feeds the ``engine_steps_per_fetch`` window gauge and
    the ``engine_host_fetches_total`` counter, the decode-dispatch-tail
    telemetry."""
    t0 = clock.perf()
    out = np.asarray(jax.device_get(arr))   # tpulint: disable=devtime-fence -- the ONE counted host-fetch seam; every result fetch routes through here and is accounted by DEVTIME.note_fetch
    REGISTRY.histogram(metric).observe(clock.perf() - t0)
    DEVTIME.note_fetch(steps)
    return out


def _stop_scan(stops, buf: str):
    """Incremental stop-sequence matching over the detokenized stream.
    Returns (emit, hold, stopped): ``emit`` is safe to stream now, ``hold``
    is a trailing fragment that could still become a stop match (at most
    max(len(stop))-1 chars), ``stopped`` means a stop string matched —
    ``emit`` then ends just before it and the request must finish."""
    idxs = [i for i in (buf.find(s) for s in stops) if i >= 0]
    if idxs:
        return buf[:min(idxs)], "", True
    hold = 0
    for s in stops:
        for L in range(min(len(buf), len(s) - 1), 0, -1):
            if buf.endswith(s[:L]):
                hold = max(hold, L)
                break
    return buf[:len(buf) - hold], buf[len(buf) - hold:] if hold else "", False


@dataclass
class Request:
    prompt_ids: List[int]
    max_tokens: int = 128
    temperature: float = 0.7
    top_k: int = 0
    top_p: float = 1.0
    # OpenAI-contract sampling surface (ref docs/api_reference/
    # openapi_schema.json:517-526 for `stop`): stop strings end the
    # generation host-side (matched incrementally on the detokenized
    # stream, never emitted); `seed` pins the slot's PRNG base key for
    # batch-composition-independent determinism (None = random per
    # request); `logprobs`/`top_logprobs` fill `logprob_data` with
    # (token_id, logprob, [(alt_id, alt_logprob)] | None) per token.
    stop: List[str] = field(default_factory=list)
    seed: Optional[int] = None
    logprobs: bool = False
    top_logprobs: int = 0
    logprob_data: List[tuple] = field(default_factory=list)
    # registered LoRA adapter name ("" = base model); resolved to a
    # resident slot at admission, one decode batch mixes adapters freely
    adapter: str = ""
    # compiled constrained-decoding grammar (engine/grammar.py Grammar) or
    # None; on an engine without free grammar slots the request silently
    # degrades to unconstrained (prompt+parse still applies upstream).
    # grammar_prefix: output text ALREADY emitted for this generation by
    # another worker (failover continuation) — the DFA starts from the
    # state reached after walking it, so the constrained suffix composes
    # into one valid document.
    grammar: Optional[object] = None
    grammar_prefix: str = ""
    # (kind, payload) constructor spec of `grammar` — e.g. ("schema",
    # schema-json) — stamped by the serving layer so a prefill_only
    # request can ship the grammar ACROSS the KV handoff as two scalar
    # strings (the decode replica recompiles via its _grammar_for cache).
    # None = nothing rides the wire (plain unconstrained handoff).
    grammar_spec: Optional[tuple] = None
    # set by the scheduler once the grammar-attachment decision is made
    # (final prefill chunk): True = token-level enforcement active, False =
    # degraded to unconstrained (slots pinned / unsupported), None = not
    # yet decided. The serving layer MUST check this before promising
    # token-level-valid output to a streaming client (engine/server.py
    # falls back to its buffered extract path when it isn't True).
    grammar_attached: Optional[bool] = None
    # SLO plane (observability/slo.py): the request's serving class (empty
    # = config default, stamped at submit), its remaining end-to-end
    # budget in seconds (NOT an absolute instant — propagated across
    # processes as remaining-ms, so clocks never need to agree), the W3C
    # trace id exemplars/breach records link on, and the post-finish
    # judgment (slo_outcome is a scheduler preset — "shed" — that
    # overrides judging; slo is the full verdict dict).
    slo_class: str = ""
    deadline_s: Optional[float] = None
    trace_id: str = ""
    slo_outcome: Optional[str] = None
    slo: Optional[dict] = None
    # Usage plane (observability/usage.py): the tenant identity this
    # request bills to — extracted from X-Tenant-Id / API-key headers in
    # the serving layer ("" bills as "anon"), riding the KV-handoff
    # payload so a disaggregated chat's prefill and decode legs land
    # under ONE tenant; kv_page_seconds accumulates pages-held x wall
    # seconds, stamped by the scheduler at alloc/grow/release/export
    # (preemption resumes keep accumulating on the same request).
    tenant: str = ""
    kv_page_seconds: float = 0.0
    # Disaggregated serving (engine roles): prefill_only requests run
    # chunked prefill and END at the first sampled token — instead of
    # decoding, the scheduler exports the slot's KV pages + sampling state
    # into ``handoff`` (core.export_slot_kv) and finishes with
    # finish_reason "handoff"; no text is ever streamed. A decode-role
    # worker admits the payload via submit_prefilled() and decodes from
    # the first token on.
    prefill_only: bool = False
    handoff: Optional[dict] = None
    # host-observed seconds spent importing a handoff payload at admission
    # (decode role; includes the devtime fence when one was sampled) — the
    # kv_handoff span's kv.import_s attribute reads this
    kv_import_s: Optional[float] = None
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    # filled by the scheduler:
    out_queue: "queue.Queue" = field(default_factory=queue.Queue)
    # per-request timeline (observability/flight.py renders it): every stamp
    # shares the perf_counter clock, so queued <= admitted <= prefill_start
    # <= first_token <= finished holds exactly. Stamps record the FIRST
    # occurrence — a preemption resume re-admits and re-prefills, but the
    # client-visible phases happened once; the resume shows up in
    # `preemptions` instead.
    submitted_at: float = field(default_factory=clock.perf)
    admitted_at: Optional[float] = None
    prefill_start_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    preemptions: int = 0
    # resume-mode accounting next to `preemptions` (/debug/requests
    # timelines distinguish transfer recovery from recompute recovery):
    # spill_resumes counts page-exhaust preemptions promoted back from
    # the host spill pool (zero re-prefill); snapshot_resumes counts
    # mid-decode snapshot admissions on THIS worker (the request was
    # evacuated from a peer and resumed here token-identically)
    spill_resumes: int = 0
    snapshot_resumes: int = 0
    prefix_hit_tokens: int = 0
    # prompt tokens whose KV was promoted from the prefix-addressed host
    # tier (engine/kv_tier.py) at admission — a subset of
    # prefix_hit_tokens (the tier hit also counts as a prefix hit; the
    # split tells the flight recorder WHERE the hit was served from)
    tier_hit_tokens: int = 0
    completion_tokens: int = 0
    error: Optional[str] = None
    # why generation ended — "eos" (model emitted EOS), "stop" (a stop
    # sequence matched), "length" (max_tokens or cache capacity). The
    # serving layer maps this to the OpenAI finish_reason contract
    # (eos/stop → "stop", length → "length"); None = not finished / failed.
    finish_reason: Optional[str] = None


@dataclass
class _Job:
    """A request's journey through the engine: prefilling, then decoding.

    ``ids`` is the sequence prefilled so far — the prompt, plus (after a
    preemption) the tokens already generated, so a resume re-prefills the
    full context and the stream continues where it left off.
    """

    request: Request
    detok: IncrementalDetokenizer
    ids: List[int]
    slot: int = -1
    pages: List[int] = field(default_factory=list)
    prefilled: int = 0            # tokens of `ids` already chunked in
    total_len: int = 0            # host mirror of cache lengths[slot]
    gen_ids: List[int] = field(default_factory=list)   # generated so far
    admit_seq: int = 0            # admission order (preemption picks max)
    bypass_count: int = 0         # times skipped over while at the head
    shared: int = 0               # prefix-cache tokens skipped this admission
    page_hashes: List[bytes] = field(default_factory=list)  # chain/full page
    hashed_len: int = -1          # len(ids) the hashes were computed for
    prefill_started: float = 0.0  # wall clock of this prompt's first chunk
    # set when the fused final chunk has sampled this job's first token
    # on-device; resolved (and cleared) by whichever lands first — the
    # scheduler's batched state.tokens fetch or the next decode sync's
    # out["input_tokens"]
    first_pending: bool = False
    first_batched: bool = False   # included in an in-flight batched fetch
    first_inflight: bool = False  # already snapshotted into a decode dispatch
    first_epoch: int = 0          # bumps per (re-)prefill: stale fetches
                                  # of a preempted+re-admitted job no-op
    gram_on: bool = False         # constrained decoding active for the slot
    stop_buf: str = ""            # held-back text (possible stop prefix)
    stopped: bool = False         # a stop sequence matched; tail suppressed
    adapter_ix: int = 0           # resolved LoRA slot (0 = base)
    # KV-handoff payload for admit-with-prefilled-KV (submit_prefilled):
    # imported at admission instead of running prefill chunks
    preload: Optional[dict] = None
    # host-spilled snapshot of a page-exhaust-preempted slot (engine/
    # spill.py): the payload's host buffers re-import at re-admission
    # (_admit_spilled) instead of re-prefilling — the job keeps its live
    # detok/stop/grammar state, only the KV pages moved
    spill: Optional[dict] = None
    # prefix-tier promotion plan from _plan_admission: (entry_key,
    # covered_tokens) — _admit imports the covered pages from the host
    # tier (engine/kv_tier.py) and the chunked prefill starts at the
    # boundary. Recomputed every admission pass; never survives a plan.
    tier_plan: Optional[tuple] = None
    # trailing acceptance EMA (drafts accepted per widened step) — the
    # adaptive spec-width controller's per-slot signal; seeded from the
    # scheduler-global EMA at admission so fresh slots start where the
    # workload's recent acceptance actually sits
    spec_ema: float = -1.0
    # page-second clock (usage plane): perf_counter of the last page-count
    # change while this job holds pages; 0.0 = not holding (billing
    # stopped). _bill_pages accumulates pages x elapsed into the request.
    page_clock: float = 0.0


class Scheduler:
    """Drives an EngineCore from a single background thread."""

    def __init__(self, core: EngineCore, tokenizer: Tokenizer) -> None:
        self.core = core
        self.tokenizer = tokenizer
        # disaggregated serving role (core/config.py APP_ENGINE_ROLE): a
        # "prefill" worker NEVER dispatches decode — finished prefills
        # export their KV instead (_export_handoff); "decode"/"unified"
        # behave identically here (the role is a routing contract)
        self._role = str(getattr(core, "role", "unified") or "unified")
        self._lock = tracked_lock("scheduler._lock")
        self._pending: Deque[_Job] = deque()     # awaiting slot+pages
        self._prefilling: Deque[_Job] = deque()  # admitted, chunking in
        self._slots: Dict[int, _Job] = {}        # decoding
        self._free: List[int] = list(range(core.batch))
        self._alloc = core.new_allocator()
        # prefix caching (engine/prefix_cache.py): present iff the core's
        # allocator speaks match/acquire/insert. The hash-chain seed
        # namespaces pages by the weights that produced their KV: the seed
        # string appends the request's ADAPTER NAME, and names are
        # immutable-once-registered (core.register_adapter refuses
        # rebinding — the invariant this constant seed relies on; if
        # rebinding is ever allowed, an adapter-epoch counter must be
        # folded in here).
        self._caching = hasattr(self._alloc, "match")
        self._cache_seed = 0
        # speculative decoding widens every decode step to W positions per
        # slot (page growth and in-flight accounting are in POSITIONS).
        # _spec_w is the CEILING width; with an adaptive ladder
        # (core.spec_widths, >1 rung) each dispatch picks the smallest
        # rung covering every slot's acceptance-tuned draft cap.
        self._spec_w = getattr(core, "spec_width", 1)
        self._spec_widths = tuple(getattr(core, "spec_widths",
                                          (self._spec_w,)))
        # decode batch-width ladder (core.decode_widths): pure-decode
        # dispatches run at the smallest rung covering the highest live
        # slot; slot allocation below is lowest-id-first (heap) so the
        # live set compacts into the narrow rungs
        self._decode_widths = tuple(getattr(core, "decode_widths",
                                            (core.batch,)))
        # scheduler-global acceptance EMA: seeds fresh slots' controllers.
        # Seeded at spec_draft/2 so a fresh slot's cap (= ceil(2 x ema))
        # is exactly the CONFIGURED static draft — rungs past it are
        # earned by measured acceptance, never assumed (an assumed-wide
        # start was measured hoarding the page-growth horizon's pool
        # slack and starving skip-ahead admission).
        cfg_draft = int(getattr(getattr(core, "cfg", None), "spec_draft",
                                max(self._spec_w - 1, 0)) or 0)
        self._spec_ema_global = min(cfg_draft, self._spec_w - 1) / 2.0
        self._table = np.zeros((core.batch, core.max_pages_per_slot), np.int32)
        self._table_dev: Optional[jax.Array] = None
        self._inflight: Deque[tuple] = deque()   # dispatched, not yet synced
        self._pending_steps = 0                  # decode steps in flight
        # Dispatches kept in flight: results stream back on fetcher threads
        # while the driver keeps dispatching — on a remote-attached chip
        # (~100 ms round trip, measured) this is what keeps decode from
        # being round-trip-bound. Staleness cost: done slots are reused
        # (and first tokens resolve) up to depth dispatches late — round 4
        # measured depth 2 strictly better than 4 once grouped prefill made
        # refills cheap (occupancy 0.79 vs 0.70, +10% tok/s): the engine is
        # device-bound now, so extra depth only delays slot turnover.
        self._pipeline_depth = max(1, core.cfg.pipeline_depth)
        # one worker per in-flight dispatch: a single fetcher serializes the
        # ~100 ms RTTs and caps the whole engine at ~10 dispatches/s
        # (measured round 3 — THE round-2 throughput bottleneck); each
        # worker's device_get releases the GIL, so transfers overlap.
        self._fetcher = ThreadPoolExecutor(max_workers=self._pipeline_depth + 1,
                                           thread_name_prefix="kv-fetch")
        self._admit_counter = 0
        self._holding = False      # inside a prefill-priority ramp episode
        self._hold_left = 0        # chunk budget remaining in the episode
        # mixed-phase dispatch accounting (ragged paged attention): how
        # many decode dispatches fused a prefill chunk, and the last
        # dispatch's query-row utilization (active rows / padded rows) —
        # the kernel-occupancy observables next to batch_occupancy
        self._decode_dispatches = 0
        self._mixed_dispatches = 0
        self._ragged_row_util = 0.0
        # batched first-token fetches in flight: [(future, pairs)]. Several
        # ride concurrently (one per admission burst) — a single serialized
        # fetch would resolve the whole ramp's first tokens only after the
        # LAST prefill chunk executes on device (~the full ramp, measured
        # +1 s of p50 TTFT at a 20-slot burst).
        self._first_fetches: List[tuple] = []
        self._first_fetch_depth = 4
        self._state: DecodeState = core.init_state()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        # host-spill preemption (engine/spill.py): with a byte budget
        # armed, page-exhaust preemption demotes the victim's pages to
        # host RAM instead of freeing-and-recomputing them; 0 = off.
        budget = spill_budget_bytes(getattr(core, "cfg", None))
        self._spill: Optional[KVSpillPool] = None
        self._tier: Optional[PrefixKVTier] = None
        if (budget > 0 and hasattr(core, "export_slot_kv")
                and hasattr(core, "import_slot_kv")):
            if (tier_mode(getattr(core, "cfg", None)) == "prefix"
                    and hasattr(core, "import_pages_kv")):
                # prefix-addressed tier (engine/kv_tier.py): the spill
                # pool grows retention, hash addressing, and value-priced
                # eviction; _plan_admission probes it for every prompt
                self._tier = PrefixKVTier(
                    budget,
                    disk_budget_bytes=tier_disk_bytes(
                        getattr(core, "cfg", None)),
                    perf_model=getattr(core, "perf_model", None))
                self._spill = self._tier
            else:
                # off (default): the request-keyed pool, byte-identical
                # to pre-tier spill behavior — zero tier code on any path
                self._spill = KVSpillPool(budget)
        # flight-dump occupancy surface (observability/flight.py): the
        # crash-dump artifact embeds the pool snapshot without holding a
        # scheduler reference
        kv_tier_mod.register_pool(self._spill)
        # QoS admission plane (engine/qos.py, APP_QOS=off|fair): None in
        # off mode — the admission path then runs the exact pre-QoS FIFO
        # walk with zero qos calls (the APP_CHAOS/APP_DEVTIME
        # zero-overhead pattern, test-enforced). With fair on, _admit
        # consults the policy for weighted-fair tenant ordering, EDF
        # within a tenant, quota throttling, and shed-before-prefill;
        # _pick_victim weighs tenant overuse + SLO slack.
        self._qos: Optional[qos_mod.QosPolicy] = qos_mod.policy_from_env(
            getattr(core, "cfg", None),
            perf_model=getattr(core, "perf_model", None),
            batch_hint=int(getattr(core, "batch", 1) or 1))
        if self._tier is not None and self._qos is not None:
            # compose tier eviction with the QoS victim doctrine: cached
            # prefixes contributed by an overusing tenant evict first,
            # exactly as that tenant's live jobs spill first (PR 15).
            # The HINT variant reads a published snapshot without the QoS
            # lock: the tier calls this under its own lock, and
            # kv_tier._lock -> qos._lock was the cross-module ordering
            # edge the lock-order analyses flagged
            self._tier.set_victim_bias(self._qos.tenant_overuse_hint)
        # live-migration evacuation (drain/SIGTERM/watchdog-trip): callers
        # queue a request, the DRIVER thread (owner of _state) performs it
        # inside _tick, parking each live slot's mid-decode snapshot in the
        # outbox for the router to pull (/v1/kv/evacuation/<rid>). The
        # outbox is count-capped AND TTL'd: device-native snapshots pin
        # real HBM (dense KV copies), and an unpulled entry — resume
        # disabled router-side, no router at all, a watchdog-recovered
        # worker that keeps serving — must not hold device memory forever
        # on exactly the worker that just tripped under pressure.
        self._evac_lock = tracked_lock("scheduler._evac_lock")
        self._evac_reqs: List[dict] = []
        self._evac_outbox: "OrderedDict[str, tuple]" = OrderedDict()
        self._evac_outbox_cap = 64
        self._evac_ttl_s = float(os.environ.get("APP_EVAC_TTL_S", "")
                                 or 120.0)
        # tick heartbeat for the engine watchdog (engine/watchdog.py): the
        # driver stamps this every loop iteration; a sustained gap while
        # _running means the driver is wedged inside one tick
        self.last_tick_mono = clock.mono()

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        if self._running:
            return
        # devtime plane: hand the ledger this engine's analytic perf model
        # (live MFU/HBM gauges) and close the warm window — program keys
        # first compiled after this point are mid-serving recompiles
        try:
            DEVTIME.attach_perf(getattr(self.core, "perf_model", None))
        except Exception:   # tpulint: disable=except-swallow -- fakes without device peaks must not block startup; the ledger just runs gauge-less
            pass
        DEVTIME.mark_serving()
        self._running = True
        self._thread = threading.Thread(target=self._loop, name="engine-driver",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                # Driver still mid-step (e.g. a long XLA compile): touching
                # job state concurrently would corrupt bookkeeping — leave
                # cleanup to the driver, which checks _running after the step.
                # The fetcher still gets released (it tolerates a racing
                # submit by raising into the driver's guarded loop).
                logger.warning("driver thread still busy at stop(); "
                               "skipping forced cleanup")
                self._fetcher.shutdown(wait=False)
                return
        # only after the driver has exited: a mid-tick dispatch must not see
        # a shut-down executor
        self._fetcher.shutdown(wait=False)
        self._fail_all("scheduler stopped")
        if self._tier is not None:
            # bounded-join shutdown of the tier's write-behind thread —
            # queued disk ops (including _fail_all's deletes) drain first
            self._tier.close()

    def submit(self, request: Request) -> Request:
        """Enqueue; stream deltas via `iter_text(request)`."""
        if request.seed is None:
            # unseeded requests still get a PER-REQUEST key, so concurrent
            # streams never correlate and the effective seed is reportable
            import random as _random
            request.seed = _random.getrandbits(31)
        else:
            # OpenAI accepts 64-bit seeds; the device key is int32. Map
            # deterministically instead of letting np.int32 raise mid-tick
            # (which would fail every in-flight request via _fail_all)
            request.seed = int(request.seed) & 0x7FFFFFFF
        # resolve the SLO class + deadline now (explicit fields win; else
        # the ambient admission context; else the config default with its
        # full e2e budget) — judging at finish needs both
        slo_mod.stamp_request(request,
                              slo_class=request.slo_class or None,
                              deadline_s=request.deadline_s)
        job = _Job(request=request,
                   detok=IncrementalDetokenizer(self.tokenizer),
                   ids=list(request.prompt_ids))
        with self._lock:
            self._pending.append(job)
        self._wake.set()
        REGISTRY.counter("requests_submitted").inc()
        if TRACE.enabled:
            self._trace("submit", request,
                        prompt_tokens=len(request.prompt_ids),
                        max_tokens=request.max_tokens,
                        slo=request.slo_class,
                        deadline_s=request.deadline_s,
                        prefix=self.prefix_key_hex(request.prompt_ids,
                                                   request.adapter or ""),
                        est_cost_s=self._est_cost_s(len(request.prompt_ids),
                                                    request.max_tokens))
        return request

    def submit_prefilled(self, request: Request, payload: dict) -> Request:
        """Admit-with-prefilled-KV (decode role): enqueue a request whose
        prompt KV arrives as an exported handoff payload instead of being
        prefilled locally. Admission imports the pages into freshly
        allocated ones (core.import_slot_kv), seeds history, and starts
        decoding at the payload's first token — stamping the same timeline
        fields a local prefill would, so SLO accounting and the flight
        recorder stay truthful. Raises ValueError (synchronously, before
        anything is queued) when the payload cannot be hosted by this
        engine's pool — geometry/dtype mismatches must be a loud admission
        failure, never a mid-tick driver reset."""
        if not hasattr(self.core, "import_slot_kv"):
            raise ValueError("this engine cannot import handed-off KV")
        self.core.validate_handoff(payload)
        if request.seed is None:
            request.seed = int(payload.get("seed", 0) or 0)
        request.seed = int(request.seed) & 0x7FFFFFFF
        slo_mod.stamp_request(request,
                              slo_class=request.slo_class or None,
                              deadline_s=request.deadline_s)
        job = _Job(request=request,
                   detok=IncrementalDetokenizer(self.tokenizer),
                   ids=list(request.prompt_ids))
        if payload.get("resume"):
            # mid-decode snapshot (export_live_slot on a peer): the
            # payload's prompt_ids span EVERYTHING whose KV is written —
            # the true prompt plus the tokens generated before the
            # snapshot, split by prompt_len. The job's ids mirror the
            # written KV (page math, history seeding, preemption rebuild
            # all key off them); gen_ids reseed the generated prefix so
            # grammar walks, stop accounting, and a later preemption of
            # the RESUMED stream stay exactly as if it had decoded here
            # from token 0.
            full = [int(t) for t in payload.get("prompt_ids", [])]
            plen = max(0, min(int(payload.get("prompt_len", len(full))),
                              len(full)))
            request.prompt_ids = full[:plen]
            job.ids = list(full)
            job.gen_ids = full[plen:]
        job.preload = dict(payload)
        with self._lock:
            self._pending.append(job)
        self._wake.set()
        REGISTRY.counter("requests_submitted").inc()
        REGISTRY.counter("kv_handoff_submitted").inc()
        if TRACE.enabled:
            self._trace("submit", request,
                        prompt_tokens=len(request.prompt_ids),
                        max_tokens=request.max_tokens,
                        slo=request.slo_class,
                        deadline_s=request.deadline_s,
                        handoff=True, resume=bool(payload.get("resume")),
                        est_cost_s=0.0)
        return request

    def load_stats(self) -> Dict[str, object]:
        """Live load surface for the routing frontend: /health rides these
        fields so the router's least-loaded scoring sees queue depth and
        slot fill with every probe it already makes (server/failover.py)."""
        with self._lock:
            waiting = len(self._pending)
        # per-replica prefix-cache coverage (ROADMAP items 1/3): the hit
        # fraction is per-REPLICA today — at N replicas random routing
        # divides it by N, which is exactly why the router's affinity work
        # needs this signal per worker. Rides /health with every probe
        # and mirrors to the prefix_hit_frac gauge on /metrics.
        hits = REGISTRY.counter("prefix_hit_tokens").value
        prompted = REGISTRY.counter("prefix_prompt_tokens").value
        hit_frac = round(hits / prompted, 4) if prompted else 0.0
        REGISTRY.gauge("prefix_hit_frac").set(hit_frac)
        out = {
            "engine_role": self._role,
            "running": len(self._slots),
            "prefilling": len(self._prefilling),
            "waiting": waiting,
            "batch": int(getattr(self.core, "batch", 0) or 0),
            "kv_pages_free": int(getattr(self._alloc, "available", 0)),
            "inflight_dispatches": len(self._inflight),
            "prefix_hit_frac": hit_frac,
            # host spill/tier occupancy: the router must see a replica's
            # host-RAM headroom BEFORE routing preemption-heavy load at it
            "kv_spill_used_bytes": (self._spill.used_bytes
                                    if self._spill is not None else 0),
            "kv_spill_budget_bytes": (self._spill.budget_bytes
                                      if self._spill is not None else 0),
        }
        if self._tier is not None:
            # fleet hotset advert: tier occupancy + the top-K hottest h0
            # hashes — what the router's promote routing matches against
            out.update(self._tier.hot_stats())
        return out

    def prefix_key_hex(self, prompt_ids: Sequence[int],
                       adapter: str = "") -> str:
        """h0 — the chain hash of a prompt's FIRST full page under this
        scheduler's cache seed: the identity the fleet hotset protocol
        advertises (load_stats) and the router learns from the
        ``X-KV-Prefix`` response header. "" when the tier is off or the
        prompt doesn't cover one page (nothing shareable to advertise)."""
        if self._tier is None:
            return ""
        ps = int(self.core.page_size)
        if len(prompt_ids) < ps:
            return ""
        hs = chain_hashes([int(t) for t in prompt_ids[:ps]], ps,
                          seed=f"{self._cache_seed}|{adapter}")
        return hs[0].hex() if hs else ""

    # ------------------------------------------------------- event trace

    def _est_cost_s(self, prompt_tokens: int, max_tokens: int) -> float:
        """Perfmodel-estimated service seconds for a request: prefill over
        the prompt plus one weight-read-bound decode pass per budgeted
        token — the same first-principles model the QoS plane budgets
        with, stamped on every trace record so replay (ops/simulate.py)
        and live cost accounting read identical estimates. 0.0 when the
        core carries no perf model."""
        pm = getattr(self.core, "perf_model", None)
        if pm is None:
            return 0.0
        try:
            per_tok = pm.weight_read_bytes(1) / pm.peak_bw
            return round(pm.prefill_seconds(max(1, prompt_tokens))
                         + max(1, max_tokens) * per_tok, 6)
        except Exception:   # tpulint: disable=except-swallow -- a cost estimate is advisory trace metadata; a perfmodel stub without these fields degrades to 0.0, never blocks admission
            return 0.0

    def _trace(self, kind: str, req: Request, **fields) -> None:
        """One canonical fleet-trace record (observability/trace.py) for a
        request-scoped scheduler event. Callers guard with TRACE.enabled
        so the off mode costs one attribute read."""
        TRACE.emit(kind, rid=req.request_id,
                   tenant=str(getattr(req, "tenant", "") or ""), **fields)

    def iter_text(self, request: Request) -> Iterator[str]:
        """Blocking iterator over the request's text deltas."""
        while True:
            item = request.out_queue.get()
            if item is _STOP:
                return
            yield item

    def generate(self, prompt_ids: Sequence[int], **kw) -> str:
        """Synchronous convenience: submit and join the full text. Raises on
        per-request failure (e.g. over-capacity prompt) — never returns a
        silently empty string for a rejected request."""
        req = Request(prompt_ids=list(prompt_ids), **kw)
        self.submit(req)
        text = "".join(self.iter_text(req))
        if req.error:
            raise RuntimeError(f"request {req.request_id} failed: {req.error}")
        return text

    # ------------------------------------------------------------- internals

    def _fail_all(self, reason: str) -> None:
        """Unblock every queued and in-flight consumer (shutdown/crash path)."""
        with self._lock:
            jobs = list(self._pending)
            self._pending.clear()
        jobs += list(self._prefilling) + list(self._slots.values())
        self._prefilling.clear()
        self._slots.clear()
        now = clock.perf()
        for job in jobs:
            job.request.error = reason
            if job.request.finished_at is None:
                job.request.finished_at = now
            # the crash path counts in the finish-cause family too — a
            # dashboard summing requests_finished{finish=...} over an
            # incident must agree with the /debug/requests log
            REGISTRY.counter("requests_failed").inc()
            REGISTRY.counter("requests_finished",
                             labels={"finish": "error"}).inc()
            slo_mod.SLO.observe(job.request)
            # page-second clocks close BEFORE the pool rebuild below: a
            # driver reset must not leave a job billing pages the fresh
            # allocator no longer tracks (conservation through resets —
            # the fuzz harness asserts the bound)
            self._bill_pages(job)
            job.page_clock = 0.0
            # spilled host buffers die with their job (budget conservation
            # through driver resets — fuzz-asserted)
            self._drop_spill(job)
            self._qos_settle(job)
            usage_mod.USAGE.bill_request(job.request)
            REQUEST_LOG.record(job.request)
            if forensics_mod.FORENSICS.enabled:
                forensics_mod.FORENSICS.observe(job.request)
            job.request.out_queue.put(_STOP)
            job.pages = []
            job.slot = -1
        # rebuild slot/page bookkeeping to a clean slate
        self._alloc = self.core.new_allocator()
        self._free = list(range(self.core.batch))
        self._table[:] = 0
        self._table_dev = None
        self._inflight.clear()
        self._first_fetches = []
        self._pending_steps = 0
        # unblock evacuation waiters: their jobs just failed loudly — a
        # drain handler must not sit out its full timeout on a dead driver
        with self._evac_lock:
            waiters, self._evac_reqs = self._evac_reqs, []
        for entry in waiters:
            entry["result"] = {"error": reason}
            entry["event"].set()

    def _qos_settle(self, job: _Job) -> None:
        """Close the job's QoS admission reservation (virtual-time true-up
        + quota refund) at its terminal event — called at EVERY path that
        bills the usage ledger, so the policy's outstanding set conserves
        through finishes, failures, evacuations, and driver resets (the
        fuzz harness asserts it drains to zero). No-op in off mode."""
        if self._qos is not None:
            self._qos.settle(job.request)

    def _bill_pages(self, job: _Job) -> None:
        """Accumulate the job's KV page-seconds (pages held x wall) into
        its request and restamp the clock — called at EVERY page-count
        change (admission alloc, decode growth, release/export) so the
        usage plane's page-second vector integrates exactly the pages
        this job actually occupied. A stopped clock (0.0) only restamps:
        admission uses that to start billing."""
        now = clock.perf()
        if job.page_clock and job.pages:
            job.request.kv_page_seconds += (len(job.pages)
                                            * (now - job.page_clock))
        job.page_clock = now

    def _release(self, job: _Job) -> None:
        """Return the job's slot and pages to the pools."""
        self._bill_pages(job)
        job.page_clock = 0.0      # billing stops with the hold
        if job.slot >= 0:
            # min-heap: admission reuses the LOWEST free slot id first, so
            # live slots compact toward 0 and the decode batch-width
            # ladder's narrow rungs actually cover them
            heapq.heappush(self._free, job.slot)
            self._table[job.slot, :] = 0
            self._table_dev = None
            job.slot = -1
        if job.pages:
            self._alloc.free(job.pages)
            job.pages = []

    def _finish(self, job: _Job) -> None:
        tail = job.detok.flush()
        if job.stopped:
            pass          # text at/after the stop match is never emitted
        elif job.request.stop:
            # natural end with holdback pending: the tail may still
            # complete a stop match across the flush boundary; an unmatched
            # hold is legitimate output and flushes too
            emit, hold, hit = _stop_scan(job.request.stop,
                                         job.stop_buf + tail)
            if not hit:
                emit += hold
            if hit:
                # a stop match found only at flush still ended the output
                # at the stop string — report "stop", not the budget/EOS
                # cause the caller recorded
                job.request.finish_reason = "stop"
            if emit:
                job.request.out_queue.put(emit)
        elif tail:
            job.request.out_queue.put(tail)
        job.stop_buf = ""
        # stamp + log BEFORE releasing the stream: a client that reads
        # X-Request-Id off the finished response and immediately GETs
        # /debug/requests/<id> (or the server's span-attribute read after
        # the drain ends) must find the completed timeline — _STOP is the
        # happens-before edge consumers synchronize on
        req = job.request
        req.finished_at = clock.perf()
        REGISTRY.counter("requests_completed").inc()
        # labeled family: finish-cause breakdown without a counter per name
        REGISTRY.counter("requests_finished",
                         labels={"finish": req.finish_reason or "unknown"}
                         ).inc()
        REGISTRY.histogram("request_latency_s").observe(
            req.finished_at - req.submitted_at)
        if TRACE.enabled:
            self._trace("finish", req,
                        finish=req.finish_reason or "unknown",
                        completion_tokens=len(job.gen_ids),
                        prompt_tokens=len(req.prompt_ids),
                        e2e_s=round(req.finished_at - req.submitted_at, 6),
                        ttft_s=(round(req.first_token_at
                                      - req.submitted_at, 6)
                                if req.first_token_at else None),
                        preemptions=req.preemptions,
                        prefix_hit_tokens=req.prefix_hit_tokens,
                        tier_hit_tokens=req.tier_hit_tokens)
        # judge SLO attainment BEFORE the log write and the stream release:
        # the /debug/requests timeline and the breach record a client can
        # fetch right after [DONE] already carry the verdict
        slo_mod.SLO.observe(req)
        # bill the usage ledger with the same happens-before discipline —
        # page-seconds close out first (the job still holds its pages
        # here; _release below would otherwise bill the final window
        # AFTER the request was already recorded)
        self._bill_pages(job)
        job.page_clock = 0.0
        self._qos_settle(job)
        usage_mod.USAGE.bill_request(req)
        REQUEST_LOG.record(req)
        if forensics_mod.FORENSICS.enabled:
            forensics_mod.FORENSICS.observe(req)
        req.out_queue.put(_STOP)
        # decode-written pages join the prefix cache before release: a
        # follow-up turn whose templated prompt embeds this conversation
        # verbatim re-admits against them
        self._cache_insert(job, with_generated=True)
        self._drop_spill(job)
        self._release(job)

    def _fail(self, job: _Job, reason: str) -> None:
        job.request.error = reason
        job.request.finished_at = clock.perf()
        REGISTRY.counter("requests_failed").inc()
        REGISTRY.counter("requests_finished", labels={"finish": "error"}).inc()
        if TRACE.enabled:
            self._trace("finish", job.request, finish="error",
                        error=reason[:200],
                        completion_tokens=len(job.gen_ids),
                        prompt_tokens=len(job.request.prompt_ids))
        slo_mod.SLO.observe(job.request)
        # close out page-seconds before billing: failure paths that still
        # hold pages (kv-export failure) release AFTER this call
        self._bill_pages(job)
        job.page_clock = 0.0
        self._drop_spill(job)
        self._qos_settle(job)
        usage_mod.USAGE.bill_request(job.request)
        REQUEST_LOG.record(job.request)
        if forensics_mod.FORENSICS.enabled:
            forensics_mod.FORENSICS.observe(job.request)
        job.request.out_queue.put(_STOP)

    def _table_device(self) -> jax.Array:
        if self._table_dev is None:
            self._table_dev = self.core.put_table(self._table)
        return self._table_dev

    def _alloc_pages(self, n: int):   # tpulint: hot-path
        """The ONE KV page allocation seam (admission + decode growth):
        the chaos plane's forced-exhaustion fault injects here, so a
        chaos run exercises exactly the paths a genuinely empty pool
        takes — head-of-line waiting, youngest-slot preemption — and
        nothing else. APP_CHAOS=off is a single attribute read."""
        if chaos_mod.CHAOS.enabled and chaos_mod.CHAOS.page_fault():
            return None
        return self._alloc.alloc(n)

    # -- admission ----------------------------------------------------------

    _ADMIT_SCAN = 32     # pending jobs considered per admission pass
    _BYPASS_MAX = 8      # admissions allowed past a page-blocked head

    def _cap_shared(self, n: int, shared: int) -> int:
        """Largest usable prefix-cache coverage for an n-token prompt.

        Two geometry constraints cap a raw match: (a) at least the final
        token must be recomputed (its logits seed the first sample), so
        coverage stops at the last FULL page before position n-1; (b) the
        chunk walk the prefill loop runs from ``shared`` must keep its
        final padded bucket inside the block-table row — page-aligned (but
        not chunk-aligned) starts can push the last bucket past max_seq,
        whose clamped page slice would corrupt earlier pages. (b) is
        re-established by stepping coverage down a page at a time; at any
        chunk-multiple it holds by the max_seq %% chunk == 0 invariant."""
        ps = self.core.page_size
        chunk = self.core.chunk
        row_tokens = self.core.max_pages_per_slot * ps
        shared = min(shared, ((n - 1) // ps) * ps)
        while shared > 0:
            start = shared
            while n - start > chunk:
                start += chunk
            bucket = next(b for b in self.core.buckets if (n - start) <= b)
            if start + bucket <= row_tokens:
                break
            shared -= ps
        return max(shared, 0)

    def _plan_admission(self, job: _Job):
        """(fresh_pages_needed, shared_tokens, hit_pages) for admitting the
        job now. Long prompts that qualify for the sequence-parallel
        prefill pass skip reuse unless the cache covers most of the prompt
        — one ring pass beats re-chunking a nearly-uncovered prompt."""
        n = len(job.ids)
        job.tier_plan = None
        if job.preload is not None or job.spill is not None \
                or (not self._caching and self._tier is None):
            # handoff/spill imports SCATTER into their pages — they must
            # never be served shared (refcounted) prefix-cache pages, which
            # other requests may be reading; always allocate fresh
            return self.core.pages_for(n), 0, []
        if job.hashed_len != n:
            # the chain seed namespaces by adapter: KV depends on the
            # weights that produced it, so requests served under different
            # adapters must never share pages
            job.page_hashes = chain_hashes(
                job.ids, self.core.page_size,
                seed=f"{self._cache_seed}|{job.request.adapter}")
            job.hashed_len = n
        hits = self._alloc.match(job.page_hashes) if self._caching else []
        shared = self._cap_shared(n, len(hits) * self.core.page_size)
        if (shared and job.request.grammar is None
                and not job.request.adapter
                and self.core.cfg.long_prefill != "off"
                and self.core.supports_long_prefill
                and n - shared > 4 * self.core.chunk):
            shared = 0
        if self._tier is not None:
            # prefix-tier probe: when the host tier covers MORE of the
            # prompt than the device cache, plan a promotion — fresh
            # pages for the whole prompt (imports scatter, same rule as
            # handoff/spill above), the covered span imported from host,
            # the chunk walk starting at the boundary. Same long-prefill
            # guard as the device path: one ring pass beats importing a
            # sliver of a long prompt.
            hit = self._tier.probe(job.page_hashes)
            if hit is not None:
                key, depth = hit
                covered = self._cap_shared(n, depth * self.core.page_size)
                if (covered and job.request.grammar is None
                        and not job.request.adapter
                        and self.core.cfg.long_prefill != "off"
                        and self.core.supports_long_prefill
                        and n - covered > 4 * self.core.chunk):
                    covered = 0
                if covered > shared:
                    job.tier_plan = (key, covered)
                    return self.core.pages_for(n), 0, []
        hits = hits[: shared // self.core.page_size]
        return self.core.pages_for(n) - len(hits), shared, hits

    def _can_alloc(self, need: int, hits) -> bool:
        if self._caching:
            return self._alloc.can_serve(need, hits)
        return self._alloc.available >= need

    def _cache_insert(self, job: _Job, with_generated: bool = False) -> None:
        """Publish the job's fully-written pages to the prefix cache. Call
        only once the writing dispatches have been ISSUED (the driver
        thread's in-order stream makes any later reader safe): at
        final-chunk dispatch for prompt pages; at finish/preempt also the
        decode-written pages (minus the last generated token, whose KV may
        never have been fed back)."""
        if not self._caching or job.slot < 0 or not job.pages:
            return
        ids = job.ids
        if with_generated:
            if job.prefilled < len(job.ids):
                # preempted mid-prefill: only the chunks already dispatched
                # have content; pages past them are garbage
                ids = job.ids[:job.prefilled]
            else:
                # decoding: every generated token except the last has been
                # fed back (its KV write dispatched)
                ids = list(job.request.prompt_ids) + list(job.gen_ids)
                if job.gen_ids:
                    ids = ids[:-1]
                if (len(ids) // self.core.page_size
                        > len(job.ids) // self.core.page_size):
                    job.page_hashes = chain_hashes(
                        ids, self.core.page_size,
                        seed=f"{self._cache_seed}|{job.request.adapter}")
                    job.hashed_len = -1   # differs from ids: force recompute
        n_full = min(len(ids) // self.core.page_size, len(job.pages),
                     len(job.page_hashes))
        if n_full > 0:
            self._alloc.insert(job.page_hashes[:n_full], job.pages[:n_full])

    def _shed_pending(self) -> None:
        """Load shedding under critical error-budget burn (observability/
        slo.py): while ``SLO.pressure()`` is ``critical``, pending
        requests of a sheddable class (``best_effort`` by default) are
        rejected at admission — a fast, honest 'shed' error beats queueing
        them behind traffic that is already missing its budgets. Only
        FRESH submissions shed: a preempted resume already streamed tokens
        to its client, and truncating a live stream to save budget would
        be a worse breach than the one being protected against."""
        if slo_mod.SLO.pressure() != "critical":
            return
        with self._lock:
            shed = [j for j in self._pending
                    if not j.gen_ids and j.admit_seq == 0
                    and slo_mod.SLO.resolve_or_default(
                        j.request.slo_class).sheddable]
            for job in shed:
                self._pending.remove(job)
        for job in shed:
            job.request.slo_outcome = "shed"
            if TRACE.enabled:
                self._trace("qos", job.request, decision="shed",
                            reason="slo_pressure")
            REGISTRY.counter("slo_shed_total",
                             labels={"class": job.request.slo_class}).inc()
            self._fail(job, "shed: SLO pressure is critical (error budget "
                            "burning); best-effort admission rejected — "
                            "retry when pressure clears (/debug/slo)")

    def _qos_shed_unmeetable(self) -> None:
        """Shed-before-prefill (engine/qos.py, APP_QOS=fair): a sheddable
        pending request whose remaining deadline budget cannot cover its
        ESTIMATED prefill+decode service time is shed at admission —
        slo_outcome "shed", loud error finish — instead of burning prefill
        programs on a generation that was already lost. Only FRESH local
        submissions shed: resumes already streamed to a client, and
        handoff/spill imports carry work another worker (or this pool's
        host tier) already paid for."""
        now = clock.perf()
        with self._lock:
            shed = []
            for j in self._pending:
                if (j.gen_ids or j.admit_seq != 0 or j.preload is not None
                        or j.spill is not None):
                    continue
                if not slo_mod.SLO.resolve_or_default(
                        j.request.slo_class).sheddable:
                    continue
                est = self._qos.should_shed(j.request, len(j.ids), now)
                if est is not None:
                    shed.append((j, est))
            for job, _est in shed:
                self._pending.remove(job)
        for job, est in shed:
            job.request.slo_outcome = "shed"
            if TRACE.enabled:
                self._trace("qos", job.request, decision="shed",
                            reason="deadline_unmeetable",
                            est_s=round(est, 6))
            self._qos.note_shed(job.request)
            REGISTRY.counter("slo_shed_total",
                             labels={"class": job.request.slo_class}).inc()
            rem = qos_mod.request_remaining_s(job.request, now)
            self._fail(job, f"shed: deadline unmeetable before prefill "
                            f"(estimated service {est:.3f}s > remaining "
                            f"budget {0.0 if rem is None else rem:.3f}s); "
                            f"nothing was dispatched — retry with a larger "
                            f"deadline (/debug/qos)")

    def _admit(self) -> None:
        """Move pending jobs into the prefilling set while slots+pages last.

        FIFO with bounded-bypass skip-ahead: the queue head is admitted the
        moment its pages are free — always first. While the head's pages
        are NOT yet free, later pending jobs that DO fit may be admitted
        out of order (never taking the last free slot), so small prompts
        stop convoying behind a big one (the round-2 TTFT tail: a 3x p50
        max from head-of-line blocking) and the batch stays full. Each
        bypass is counted against the blocked head; past _BYPASS_MAX the
        queue reverts to strict FIFO until the head admits, so a stream of
        small prompts cannot starve the big one.

        With the QoS plane armed (APP_QOS=fair, engine/qos.py) the scan
        prefix comes from the policy instead of raw FIFO order: per-tenant
        EDF merged by weighted-fair virtual time, quota-throttled tenants
        held back for the pass (their jobs stay pending and admit once the
        bucket refills — no starvation), and unmeetable-deadline sheddable
        requests shed before any prefill program. The page-fit and
        bounded-bypass machinery below runs unchanged on the reordered
        prefix — the policy decides WHO is next, not whether they fit."""
        self._shed_pending()
        if self._qos is not None:
            self._qos_shed_unmeetable()
        while self._free:
            with self._lock:
                pending = list(self._pending)
            if self._qos is not None:
                cands = self._qos.order(pending, self._ADMIT_SCAN)
            else:
                cands = pending[: self._ADMIT_SCAN]
            if not cands:
                return
            chosen: Optional[_Job] = None
            oversized: Optional[_Job] = None
            bad_adapter: Optional[_Job] = None
            plan = None
            head = cands[0]
            for pos, job in enumerate(cands):
                if job.request.adapter:
                    try:
                        job.adapter_ix = self.core.adapter_index(
                            job.request.adapter)
                    except (KeyError, AttributeError):
                        bad_adapter = job
                        break
                n = len(job.ids)
                need = self.core.pages_for(n)
                # capacity: a fresh prompt prefills n positions and its
                # first decode writes at n (peak n + 1); a decoding resume
                # re-feeds its last generated token as the first decode
                # input (peak n), so a request preempted at exactly
                # max_seq - 1 tokens still fits for its capacity-step
                # token — the solo run emits it, so the resume must too
                peak = n if job.gen_ids else n + 1
                if (peak >= self.core.max_seq
                        or need > self.core.num_pages - 1):
                    oversized = job
                    break
                need, shared, hits = self._plan_admission(job)
                if pos == 0:
                    if self._can_alloc(need, hits):
                        chosen, plan = job, (need, shared, hits)
                        break
                    if head.bypass_count >= self._BYPASS_MAX:
                        return   # head's turn is overdue: strict FIFO now
                elif (len(self._free) >= 2
                        and self._can_alloc(need, hits)):
                    chosen, plan = job, (need, shared, hits)
                    head.bypass_count += 1
                    REGISTRY.counter("admission_skips").inc()
                    break
            if bad_adapter is not None:
                # never silently serve base weights under a fine-tune's name
                job = bad_adapter
                with self._lock:
                    try:
                        self._pending.remove(job)
                    except ValueError:
                        continue
                self._fail(job, f"unknown adapter "
                                f"{job.request.adapter!r}; registered: "
                                f"{getattr(self.core, 'adapter_names', [])}")
                continue
            if oversized is not None:
                job = oversized
                with self._lock:
                    try:
                        self._pending.remove(job)
                    except ValueError:
                        continue   # raced with a re-queue; rescan
                n = len(job.ids)
                need = self.core.pages_for(n)
                if job.gen_ids:
                    # a preempted resume that has outgrown capacity: end it
                    # cleanly at its current length (mirrors the engine's
                    # out_of_cache cap), keeping the streamed output valid
                    logger.warning("resume of %s no longer fits (%d tokens); "
                                   "finishing at capacity",
                                   job.request.request_id, n)
                    job.request.finish_reason = "length"
                    self._finish(job)
                else:
                    # could never be served — fail loudly rather than hang
                    # in the queue forever (the API also caps prompts,
                    # ref server.py:61-66)
                    self._fail(job, f"prompt of {n} tokens needs {need} KV "
                                    f"pages and {n + 1} cache positions "
                                    f"(prompt + first token); capacity is "
                                    f"{self.core.num_pages - 1} pages / "
                                    f"{self.core.max_seq - 1} positions "
                                    f"(max prompt {self.core.max_seq - 2})")
                continue
            if chosen is None:
                return  # head waits for pages; no admissible surplus job
            job = chosen
            need, shared, hits = plan
            if hits:
                try:
                    self._alloc.acquire(hits)
                except ValueError:
                    continue   # matched pages evicted mid-pass; rescan
            fresh = self._alloc_pages(need)
            if fresh is None:
                if hits:
                    self._alloc.free(hits)
                return   # lost the surplus since the scan; retry next tick
            pages = list(hits) + fresh
            with self._lock:
                try:
                    self._pending.remove(job)
                except ValueError:
                    self._alloc.free(pages)
                    continue
            slot = heapq.heappop(self._free)   # lowest id first (see _release)
            job.slot = slot
            job.pages = pages
            # start the page-second clock (usage plane): the request now
            # occupies pool pages; growth/release restamp as the count
            # changes. A preemption resume restarts here — its request
            # keeps accumulating across holds.
            self._bill_pages(job)
            job.prefilled = shared
            job.total_len = shared
            job.shared = shared
            if job.request.admitted_at is None:
                job.request.admitted_at = clock.perf()
            if self._caching or self._tier is not None:
                if shared:
                    job.request.prefix_hit_tokens += shared
                    REGISTRY.counter("prefix_hit_tokens").inc(shared)
                    if self._spec_w > 1 and hasattr(self.core,
                                                    "seed_history"):
                        # cache-hit chunks skip prefill, so the drafting
                        # history row must be seeded explicitly
                        self._state = self.core.seed_history(
                            self._state, slot, job.ids)
                REGISTRY.counter("prefix_prompt_tokens").inc(len(job.ids))
            if job.admit_seq == 0:
                # resumes keep their original admission age, so preemption
                # (youngest-first) cannot thrash an old request forever
                self._admit_counter += 1
                job.admit_seq = self._admit_counter
                if self._qos is not None:
                    # FIRST admission charges the tenant's virtual clock +
                    # quota reservation (resumes re-admit free — a
                    # preemption must not double-bill); settled at the
                    # request's terminal event (_qos_settle)
                    self._qos.charge_admission(job.request)
            self._table[slot, :] = 0
            self._table[slot, :len(pages)] = pages
            self._table_dev = None
            if TRACE.enabled:
                self._trace("admit", job.request, slot=slot,
                            pages=len(pages), shared_tokens=shared,
                            resume=bool(job.gen_ids),
                            waited_s=round(clock.perf()
                                           - job.request.submitted_at, 6),
                            path=("handoff" if job.preload is not None
                                  else "spill" if job.spill is not None
                                  else "tier" if job.tier_plan is not None
                                  else "prefill"))
            if job.preload is not None:
                self._admit_prefilled(job)
            elif job.spill is not None:
                self._admit_spilled(job)
            elif job.tier_plan is not None:
                self._admit_tier(job)
            else:
                self._prefilling.append(job)

    def _admit_prefilled(self, job: _Job) -> None:   # tpulint: hot-path
        """Admission-with-prefilled-KV: import the handoff payload into the
        slot's freshly allocated pages, seed history, activate at the
        payload's first token, and emit that token — after this the slot
        decodes exactly as if the prefill had run locally. Timeline stamps
        mirror a local admission (prefill_start == the import instant), so
        /debug/requests, the flight recorder, and SLO judging stay
        truthful for disaggregated traffic."""
        req = job.request
        payload = job.preload
        job.preload = None
        now = clock.perf()
        if req.prefill_start_at is None:
            req.prefill_start_at = now
        self._state = self.core.import_slot_kv(
            self._state, job.slot, job.pages, payload)
        n = len(job.ids)
        job.prefilled = n
        job.total_len = n
        # import dispatch is async: retain=False keeps the sampled fence
        # target (the fresh state's tokens) out of the ledger's queue
        # marker — the NEXT dispatch donates the state, and fencing a
        # donated-away buffer raises
        pb = min(pow2_bucket(int(payload.get("n_pages", 1))),
                 int(getattr(self.core, "max_pages_per_slot", 1 << 30)))
        DEVTIME.commit("kv_import", f"p{pb}", self._state.tokens, t0=now,
                       tokens=n, mfu=False, retain=False)
        req.kv_import_s = round(clock.perf() - now, 6)
        REGISTRY.counter("kv_handoff_imports").inc()
        first = int(payload.get("first_token", self.core.eos_id))
        gen = max(1, int(payload.get("generated", 1)))
        resume = bool(payload.get("resume"))
        if resume:
            REGISTRY.counter("snapshot_resumes").inc()
            req.snapshot_resumes += 1
        elif req.first_token_at is None:
            # the first token was sampled remotely; it reaches this
            # worker's client now — TTFT is honest end-to-end latency
            # (a mid-stream snapshot resume instead streamed its first
            # token long ago, on the evacuating worker — no TTFT here)
            req.first_token_at = now
            REGISTRY.histogram("ttft_s").observe(now - req.submitted_at)
        if first == self.core.eos_id and not resume:
            req.finish_reason = "eos"
            self._finish(job)
            return
        alive = gen < req.max_tokens
        if alive:
            if (self._spec_w > 1 and hasattr(self.core, "seed_history")):
                # imported pages skip prefill dispatches, so the drafting
                # history row must be seeded explicitly (as for prefix-
                # cache hits)
                self._state = self.core.seed_history(self._state, job.slot,
                                                     job.ids)
            gs = 0
            if req.grammar is not None:
                # grammar rode the handoff: register it on THIS engine's
                # stack and walk prefix bytes + the remotely-sampled first
                # token host-side — the slot activates at exactly the DFA
                # state the prefill worker's fused sample reached, and
                # decode continues token-level constrained (no more
                # prompt+parse degradation on disaggregated routes). A
                # rejecting walk (the prefill side degraded and sampled
                # off-grammar) or pinned slots fall back to unconstrained.
                # Snapshot resumes walk their whole emitted history the
                # same way (gen_ids was reseeded at submit).
                gs = self._gram_state_for(job, extra=(first,))
            kw = {"gram_state": gs} if gs else {}   # fakes predate the kwarg
            self._state = self.core.activate(
                self._state, job.slot, first, gen, req.max_tokens,
                req.temperature, req.top_k, req.top_p,
                seed=req.seed or 0, **kw)
            self._slots[job.slot] = job
        if resume:
            self._resume_stream_state(job, payload, first, alive)
            return
        if self._emit_token(job, first,
                            float(payload.get("first_logprob") or 0.0)):
            if alive:
                self._retire(job)
            else:
                self._finish(job)
            return
        if not alive:
            req.finish_reason = "length"
            self._finish(job)

    def _admit_tier(self, job: _Job) -> None:   # tpulint: hot-path
        """Prefix-tier promotion at admission (engine/kv_tier.py): import
        the cached prefix run into the job's freshly allocated pages (a
        partial page scatter — no slot state) and start the chunk walk at
        the covered boundary. Zero prefill programs over the covered
        span; the tail prefills exactly as a fresh admission, so the
        stream is token-identical to an uncached run by construction.
        Every failure mode (entry evicted since the plan, corrupt disk
        copy, geometry mismatch) falls back to a plain full prefill on
        the same pages — the tier can only ever SAVE work."""
        key, covered = job.tier_plan
        job.tier_plan = None
        req = job.request
        tier = self._tier
        payload = tier.checkout(key) if tier is not None else None
        if payload is None:
            self._prefilling.append(job)
            return
        now = clock.perf()
        n_imp = covered // self.core.page_size
        try:
            self._state = self.core.import_pages_kv(
                self._state, job.pages, payload, n_pages=n_imp)
        except Exception as exc:
            logger.warning("kv tier promote failed for %s (%s); "
                           "re-prefilling", req.request_id, exc)
            REGISTRY.counter("kv_tier_total",
                             labels={"outcome": "import_failed"}).inc()
            tier.checkin(key)
            self._prefilling.append(job)
            return
        tier.checkin(key)
        job.prefilled = covered
        job.total_len = covered
        job.shared = covered
        # the import dispatch is async; retain=False as in _admit_prefilled
        # (the NEXT dispatch donates the state away)
        pb = min(pow2_bucket(max(1, n_imp)),
                 int(getattr(self.core, "max_pages_per_slot", 1 << 30)))
        DEVTIME.commit("kv_import", f"p{pb}", self._state.tokens, t0=now,
                       tokens=covered, mfu=False, retain=False)
        req.kv_import_s = round(clock.perf() - now, 6)
        req.tier_hit_tokens += covered
        req.prefix_hit_tokens += covered
        REGISTRY.counter("prefix_hit_tokens").inc(covered)
        REGISTRY.counter("kv_tier_hit_tokens").inc(covered)
        REGISTRY.counter("kv_tier_total",
                         labels={"outcome": "promoted"}).inc()
        if TRACE.enabled:
            self._trace("promote", req, source="tier",
                        covered_tokens=covered,
                        import_s=req.kv_import_s)
        if self._spec_w > 1 and hasattr(self.core, "seed_history"):
            # promoted pages skip prefill dispatches, so the drafting
            # history row must be seeded explicitly (as for cache hits)
            self._state = self.core.seed_history(self._state, job.slot,
                                                 job.ids)
        self._prefilling.append(job)

    def _resume_stream_state(self, job: _Job, payload: dict, first: int,
                             alive: bool) -> None:
        """Reconstitute a mid-decode snapshot's HOST stream state: replay
        the emitted-token history through the fresh detokenizer (held
        UTF-8 bytes continue exactly where the exporting worker stopped),
        restore the stop-sequence holdback, and re-emit only the text the
        CLIENT has not seen yet (``resume_chars`` — the router stamps how
        many chars it delivered; a hard-death pull may lag the exporting
        worker's emitted tokens, and that gap must reach the client, not
        be discarded). The pending token joins ``gen_ids`` WITHOUT
        streaming its text again."""
        req = job.request
        replay = "".join(job.detok.push(int(t))
                         for t in list(job.gen_ids) + [first])
        job.gen_ids.append(first)
        job.total_len += 1
        job.stop_buf = str(payload.get("stop_buf") or "")
        # chars the exporting worker actually streamed = every delta it
        # processed minus the holdback it was still sitting on
        streamed = max(0, len(replay) - len(job.stop_buf))
        sent = payload.get("resume_chars")
        already = streamed if sent is None else max(0, min(int(sent),
                                                           streamed))
        gap = replay[already:streamed]
        if gap:
            req.out_queue.put(gap)
        if not alive:
            # the snapshot landed exactly at the generation budget (the
            # exporting worker would normally have finished instead) —
            # end cleanly; the replayed text was already streamed, so the
            # detok tail must NOT flush again
            job.stopped = True
            req.finish_reason = "length"
            self._finish(job)

    # -- prefill ------------------------------------------------------------

    def _prefill_step(self) -> int:
        """Run one GROUPED prefill dispatch: up to cfg.prefill_group jobs'
        next chunks batched into one program (engine.prefill_group) — the
        per-dispatch overhead of a remote-attached chip (~90 ms regardless
        of size, measured) made serial per-prompt chunks THE admission-ramp
        and slot-refill bottleneck at round 3 (occupancy 0.70). Returns the
        number of chunks consumed (the hold budget's unit).

        On a mesh with a "seq" axis and ``long_prefill != off``, multi-chunk
        prompts instead take ONE sequence-parallel ring-attention pass
        (engine.prefill_long_last): decode does not interleave during it,
        but the pass runs seq-axis-times faster than the chunk loop — the
        §5.7 long-context serving trade."""
        t0 = clock.perf()
        try:
            return self._prefill_step_inner()
        finally:
            REGISTRY.histogram("prefill_issue_s").observe(
                clock.perf() - t0)

    def _prefill_step_inner(self) -> int:
        from generativeaiexamples_tpu.engine.engine import PrefillItem

        job = self._prefilling[0]
        req = job.request
        # Grammared requests stay on the chunked path (the predicate lives
        # in _long_pass_claims, shared with the mixed packer): the long
        # sequence-parallel program's activation tail clears gram_state
        # (engine.py _activate_sampled), so taking it would silently drop
        # token-level enforcement the serving layer promised the client.
        if self._long_pass_claims(job):
            job.prefill_started = clock.perf()
            if req.prefill_start_at is None:
                req.prefill_start_at = job.prefill_started
            self._prefilling.popleft()
            REGISTRY.counter("prefill_long_passes").inc()
            t0 = DEVTIME.track()
            self._state, tok = self.core.prefill_long_last(
                self._state, job.ids, self._table[job.slot], job.slot,
                generated=len(job.gen_ids) + 1, max_gen=req.max_tokens,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed or 0)
            job.prefilled = len(job.ids)
            job.total_len = job.prefilled
            self._cache_insert(job)
            # ledger key: the ring pass compiles per padded-length bucket;
            # warmup never pre-compiles it, so its first live use fires the
            # compile-watch (a TRUE mid-serving latency cliff)
            nb = pow2_bucket(len(job.ids), start=self.core.chunk)
            # retain=False: `tok` rides state.tokens, which the next
            # dispatch donates — a retained queue marker would fence a
            # deleted buffer (same hazard as the kv_import commit)
            DEVTIME.commit("prefill_long", f"n{nb}", tok, t0=t0,
                           tokens=len(job.ids), padded_tokens=nb,
                           weight_passes=1.0, retain=False)
            del tok   # value rides state.tokens (_mark_first_pending)
            if TRACE.enabled:
                self._trace("dispatch", req, phase="prefill_long",
                            tokens=len(job.ids))
            self._enter_decode(job)
            return 1

        # Build a group of up to prefill_group CHUNKS, head job first —
        # consecutive chunks of one prompt may share the dispatch (each
        # layer's scatters precede every row's attention gather, so chunk
        # j+1 reads chunk j's pages written in the same program): a long
        # prompt prefills group-times fewer dispatches deep.
        budget = max(1, self.core.cfg.prefill_group)
        items: List[PrefillItem] = []
        finals: List[_Job] = []
        for job in list(self._prefilling):
            if len(items) >= budget:
                break
            req = job.request
            start = job.prefilled
            if start == job.shared:
                job.prefill_started = clock.perf()
                if req.prefill_start_at is None:
                    req.prefill_start_at = job.prefill_started
            while len(items) < budget and start < len(job.ids):
                chunk_ids = job.ids[start:start + self.core.chunk]
                last = start + len(chunk_ids) >= len(job.ids)
                # Final chunks fuse sampling + activation into the group
                # program (engine._group_impl) — admission never blocks on
                # a host round trip. The first token's VALUE comes back via
                # the batched state.tokens fetch, with the next decode
                # sync's out["input_tokens"] as the fallback resolver.
                gram_state = self._gram_state_for(job) if last else 0
                items.append(PrefillItem(
                    chunk_ids=chunk_ids, page_row=self._table[job.slot],
                    slot=job.slot, start_pos=start, is_last=last,
                    generated=len(job.gen_ids) + 1, max_gen=req.max_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    top_p=req.top_p, gram_state=gram_state,
                    seed=req.seed or 0, adapter_ix=job.adapter_ix))
                start += len(chunk_ids)
                if last:
                    finals.append(job)
            job.prefilled = start
            job.total_len = start
        REGISTRY.counter("prefill_chunks").inc(len(items))
        t0 = DEVTIME.track()
        self._state, _toks = self.core.prefill_group(self._state, items)
        # one ledger entry per grouped-prefill compile unit (the padded
        # power-of-two group bucket); gram_state rides as data inside the
        # same program, so grammar does NOT split the key here
        g_bucket = next(b for b in self.core.group_buckets
                        if len(items) <= b)
        DEVTIME.commit("prefill", f"g{g_bucket}", _toks, t0=t0,
                       tokens=sum(len(it.chunk_ids) for it in items),
                       padded_tokens=g_bucket * self.core.chunk,
                       weight_passes=1.0)
        if TRACE.enabled:
            slots_hit = {it.slot for it in items}
            # rids roster: the forensics plane joins this GLOBAL emit back
            # to each member request's critical path (finals are removed
            # from _prefilling only below, so the roster walk sees them)
            TRACE.emit("dispatch", phase="prefill", chunks=len(items),
                       tokens=sum(len(it.chunk_ids) for it in items),
                       jobs=len(slots_hit),
                       rids=",".join(j.request.request_id
                                     for j in self._prefilling
                                     if j.slot in slots_hit))
        for job in finals:
            self._prefilling.remove(job)
            # prompt pages are now fully write-dispatched: publish them
            self._cache_insert(job)
            self._enter_decode(job)
        return len(items)

    def _gram_state_for(self, job: _Job, extra: tuple = ()) -> int:
        """Flat DFA start state for a grammared job's fused first token
        (0 = unconstrained). Resumes re-walk the tokens already emitted;
        ``extra`` appends tokens not yet in ``gen_ids`` — the KV handoff's
        remotely-sampled first token, walked before the slot activates.
        Registration failure (unsupported schema, grammar slots pinned)
        degrades to unconstrained — the serving layer's prompt+parse path
        still applies, so the guarantee is strictly additive."""
        grammar = job.request.grammar
        if grammar is None:
            return 0
        job.request.grammar_attached = False   # until registration succeeds
        try:
            self.core.ensure_token_bytes(self.tokenizer)
            # _pending counts too: a PREEMPTED job's grammar must stay
            # pinned while it waits to resume — its client was already
            # promised token-level enforcement, a fresh request can still
            # fall back to prompt+parse
            active = {j.request.grammar.key
                      for j in (list(self._slots.values())
                                + list(self._prefilling)
                                + list(self._pending))
                      if j.request.grammar is not None}
            prefix = job.request.grammar_prefix.encode("utf-8")
            tokens = list(job.gen_ids) + list(extra)
            if tokens or prefix:
                state = self.core.walk_grammar(grammar, tokens, active,
                                               prefix=prefix)
            else:
                state = self.core.register_grammar(grammar, active)
            job.gram_on = state > 0
            job.request.grammar_attached = job.gram_on
            return state
        except Exception as exc:
            logger.warning("constrained decoding disabled for %s: %s",
                           job.request.request_id, exc)
            job.gram_on = False
            return 0

    def _mark_first_pending(self, job: _Job, tok) -> None:
        """Flag the fused first token for resolution. The value comes back
        via the next BATCHED state.tokens fetch (_maybe_fetch_firsts): one
        (B,) transfer resolves every pending admission — per-request
        scalar fetches measured ~100 ms EACH on the serialized tunnel
        channel, turning a 20-request burst into ~2 s of queued TTFT."""
        del tok   # value rides state.tokens; fetching it per-job is slower
        job.first_pending = True
        job.first_inflight = False
        job.first_batched = False
        job.first_epoch += 1

    def _enter_decode(self, job: _Job) -> None:
        """A job's final chunk is dispatched: flag its fused first token
        and hand the slot to the decode set. prefill_only slots are
        RELEASED on device immediately — the fused activation turned them
        on, but nothing may decode-advance them before the export
        (state.tokens immutably holds the fused first token for the
        batched fetch; decode's input_tokens carries it too on workers
        that keep dispatching)."""
        self._mark_first_pending(job, None)
        self._slots[job.slot] = job
        if job.request.prefill_only:
            self._state = self.core.release(self._state, job.slot)

    def _retire(self, job: _Job) -> None:
        """Stop-sequence retirement: the device still thinks the slot is
        generating, so deactivate it before finishing (in-flight results
        for the slot are dropped by the identity check)."""
        del self._slots[job.slot]
        self._state = self.core.release(self._state, job.slot)
        self._finish(job)

    def _resolve_first(self, job: _Job, first: int, now: float,
                       lp: Optional[float] = None) -> None:
        """Emit + stamp a job's fused first token — called by whichever
        lands first, the direct scalar fetch or a decode sync (idempotent
        via first_pending). The job must be active in its slot."""
        if not job.first_pending:
            return
        job.first_pending = False
        job.first_batched = False
        req = job.request
        if req.first_token_at is None:         # not a preemption resume
            req.first_token_at = now
            REGISTRY.histogram("ttft_s").observe(now - req.submitted_at)
        # whole-prompt prefill latency, first chunk dispatched → first
        # token value on the host (an upper bound that includes the
        # fetch RTT; every dispatch is async, so there is no tighter
        # host-observable event)
        if job.prefill_started:
            REGISTRY.histogram("prefill_s").observe(now - job.prefill_started)
            job.prefill_started = 0.0
        if req.prefill_only:
            # disaggregated serving: a prefill-role request ENDS here —
            # export the slot's KV pages + sampling state instead of
            # decoding (the decode worker emits this token to the client)
            self._export_handoff(job, first, lp)
            return
        already = len(job.gen_ids)
        if first == self.core.eos_id:
            req.finish_reason = "eos"
            del self._slots[job.slot]
            self._finish(job)
            return
        if self._emit_token(job, first, lp):
            self._retire(job)
            return
        if already + 1 >= req.max_tokens:
            req.finish_reason = "length"
            del self._slots[job.slot]
            self._finish(job)

    def _export_handoff(self, job: _Job, first: int,
                        lp: Optional[float] = None) -> None:   # tpulint: hot-path
        """Finish a prefill_only request by exporting its KV pages +
        sampling state (core.export_slot_kv) into Request.handoff. The
        export gather is dispatched BEFORE the slot's pages are released,
        so the driver's in-order stream makes it safe against reuse; the
        fetch is this role's per-request host sync point."""
        req = job.request
        t0 = clock.perf()
        try:
            payload = self.core.export_slot_kv(self._state, job.pages,
                                               len(job.ids))
        except Exception as exc:
            logger.exception("KV export failed for %s", req.request_id)
            del self._slots[job.slot]
            self._state = self.core.release(self._state, job.slot)
            self._fail(job, f"kv export failed: {exc}")
            self._release(job)
            return
        self._commit_export(payload, job, t0, tokens=len(job.ids))
        REGISTRY.counter("kv_handoff_exports").inc()
        payload.update({
            "prompt_ids": [int(t) for t in job.ids],
            "first_token": int(first),
            "first_logprob": float(lp) if lp is not None else 0.0,
            "generated": len(job.gen_ids) + 1,
            # sampling/SLO/tenant + grammar scalar passthroughs — shared
            # with the mid-decode snapshot (export_live_slot) so a knob
            # added to one wire form cannot silently miss the other.
            # Grammar semantics here: the serving layer stamped the
            # CONSTRUCTOR spec (compact, cacheable via _grammar_for on
            # the decode side) and this worker's fused final chunk
            # sampled the first token under the DFA mask (gram_on); the
            # decode replica recompiles, walks prefix + first token, and
            # activates at the reached state. grammar_attached records
            # whether enforcement was live HERE — a prefill-side degrade
            # must not be laundered into a token-level guarantee.
            **self._sampling_scalars(req),
        })
        payload.update(self._grammar_scalars(job))
        req.handoff = payload
        req.finish_reason = "handoff"
        del self._slots[job.slot]
        # the fused final chunk activated the slot on device; nothing may
        # decode it (prefill role never dispatches decode, but a unified
        # worker serving prefill_only traffic does) — released at
        # activation time, release again here is a cheap no-op safeguard
        self._state = self.core.release(self._state, job.slot)
        self._finish(job)

    # ---------------------------------------- live migration (evacuation)

    def _commit_export(self, payload: dict, job: _Job, t0: float,
                       tokens: int) -> None:
        """Shared accounting tail of every KV export (prefill handoff and
        mid-decode snapshot): the kv_export_s histogram, the devtime
        ledger commit, and the payload's export_s attribution. The export
        is DEVICE-NATIVE by default (engine.export_slot_kv keeps jax
        arrays; the wire encode pays the one host copy later, off this
        thread), so the gather is timed like any other dispatch —
        marker-fenced when sampled, zero fences in off mode; export_s
        measures dispatch issue, not the copy-out (kv_fetch_s covers
        that). Bucket mirrors the engine's export compile unit
        (_export_bucket: pow2 CLAMPED at the slot's page capacity — an
        unclamped key would name a program that never compiles)."""
        export_s = clock.perf() - t0
        REGISTRY.histogram("kv_export_s").observe(export_s)
        pb = min(pow2_bucket(int(payload.get("n_pages", 1))),
                 int(getattr(self.core, "max_pages_per_slot", 1 << 30)))
        marker = payload.get("k")
        if marker is not None and hasattr(marker, "block_until_ready"):
            DEVTIME.commit("kv_export", f"p{pb}", marker, t0=t0,
                           tokens=tokens, mfu=False, retain=False)
        else:
            # host export (fetch=True callers / fakes): the fetch already
            # synced, the wall IS the device+copy time — pre-measured
            DEVTIME.commit("kv_export", f"p{pb}", device_s=export_s,
                           tokens=tokens, mfu=False)
        # riding the payload, the downstream kv_prefill span attributes
        # the export's device time per request (decode side ignores it)
        payload["export_s"] = round(export_s, 6)

    def _sampling_scalars(self, req: Request) -> dict:
        """The sampling/SLO/tenant scalar passthrough every exported
        payload carries — one copy for the prefill handoff and the
        mid-decode snapshot, so the two wire forms cannot drift."""
        return {
            "seed": int(req.seed or 0),
            "max_tokens": int(req.max_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "stop": list(req.stop),
            "slo_class": req.slo_class,
            "tenant": req.tenant,
        }

    def _grammar_scalars(self, job: _Job) -> dict:
        req = job.request
        if not req.grammar_spec:
            return {}
        return {"grammar_kind": req.grammar_spec[0],
                "grammar_payload": req.grammar_spec[1],
                "grammar_prefix": req.grammar_prefix,
                "grammar_attached": bool(job.gram_on)}

    def _snapshot_eligible(self, job: _Job) -> bool:
        """May this slot's live decode state be exported mid-stream?
        Needs a resolved pending token (gen_ids non-empty, no fused first
        token still in flight), base weights (the import side activates
        at adapter slot 0 — an adapter'd snapshot would silently resume
        on the wrong weights), and a drained dispatch pipeline (the host
        view must equal the device view, or the snapshot would drop the
        in-flight steps' tokens)."""
        req = job.request
        return (not req.prefill_only and not req.adapter
                and bool(job.gen_ids) and not job.first_pending
                and not job.stopped
                and len(job.gen_ids) < req.max_tokens
                and not self._inflight
                and hasattr(self.core, "export_slot_kv"))

    def export_live_slot(self, job: _Job, fetch: bool = False) -> dict:
        """Generalize ``_export_handoff`` to a MID-DECODE slot: a snapshot
        a peer replica resumes TOKEN-IDENTICALLY at the snapshot position,
        not at token 0. The payload is the prefill handoff's shape plus
        the mid-stream state: KV pages for every position already written
        (``total_len - 1`` — the last emitted token is the pending next
        input, its KV not yet fed back), the emitted-token history (rides
        ``prompt_ids`` + ``prompt_len``), sampling seed + position (the
        per-position ``fold_in`` keys make the resumed sample sequence
        bit-equal), the stop-sequence holdback, and the grammar spec. The
        caller must have verified :meth:`_snapshot_eligible`."""
        req = job.request
        written = job.total_len - 1
        t0 = clock.perf()
        payload = self.core.export_slot_kv(self._state, job.pages, written,
                                           fetch=fetch)
        self._commit_export(payload, job, t0, tokens=written)
        payload.update({
            "prompt_ids": ([int(t) for t in req.prompt_ids]
                           + [int(t) for t in job.gen_ids[:-1]]),
            "prompt_len": len(req.prompt_ids),
            "first_token": int(job.gen_ids[-1]),
            "first_logprob": 0.0,
            "generated": len(job.gen_ids),
            "resume": True,
            "stop_buf": job.stop_buf,
            **self._sampling_scalars(req),
        })
        payload.update(self._grammar_scalars(job))
        return payload

    def request_evacuation(self, rids: Optional[set] = None,
                           wait_s: float = 30.0,
                           reason: str = "drain",
                           guard=None) -> dict:
        """Queue an evacuation for the DRIVER thread (it owns the device
        state) and optionally wait for the summary. ``rids`` limits the
        sweep to specific request ids (the router's single-stream pull on
        a broken connection); None evacuates everything live. Safe from
        any thread; with ``wait_s=0`` returns immediately (SIGTERM /
        watchdog-trip callers that must not block). ``guard`` is
        re-evaluated by the driver at execution time — False cancels the
        sweep (a watchdog-trip evacuation queued while the driver was
        wedged must NOT kill every live stream after the transient
        condition already cleared; the trip reason may be minutes
        stale by the time the driver can act on it)."""
        ev = threading.Event()
        entry = {"rids": set(rids) if rids else None, "event": ev,
                 "result": None, "reason": reason, "guard": guard}
        with self._evac_lock:
            self._evac_reqs.append(entry)
        self._wake.set()
        if wait_s and ev.wait(wait_s):
            return entry["result"] or {}
        return entry["result"] or {"queued": True, "reason": reason}

    def _prune_outbox(self) -> None:
        """Expire outbox entries past APP_EVAC_TTL_S (caller holds
        _evac_lock). Insertion order == age order (OrderedDict)."""
        now = clock.mono()
        while self._evac_outbox:
            rid, (_payload, parked) = next(iter(self._evac_outbox.items()))
            if now - parked <= self._evac_ttl_s:
                break
            self._evac_outbox.popitem(last=False)
            REGISTRY.counter("evacuation_snapshots_expired").inc()
            logger.warning("evacuation snapshot for %s expired unpulled "
                           "after %.0fs; its stream can only resume via "
                           "re-prefill now", rid, self._evac_ttl_s)

    def take_evacuated(self, rid: str) -> Optional[dict]:
        """Pop a parked snapshot from the evacuation outbox (the
        /v1/kv/evacuation/<rid> pull — each snapshot is served once)."""
        with self._evac_lock:
            self._prune_outbox()
            entry = self._evac_outbox.pop(rid, None)
        return entry[0] if entry is not None else None

    def evacuated_ids(self) -> List[str]:
        with self._evac_lock:
            self._prune_outbox()
            return list(self._evac_outbox)

    def _run_evacuations(self) -> bool:
        """Driver-side: perform any queued evacuation requests (and age
        out unpulled snapshots — expiry must not depend on a pull ever
        arriving)."""
        with self._evac_lock:
            if self._evac_outbox:
                self._prune_outbox()
            if not self._evac_reqs:
                return False
            entries, self._evac_reqs = self._evac_reqs, []
        for entry in entries:
            try:
                guard = entry.get("guard")
                if guard is not None and not guard():
                    logger.warning("evacuation (%s) canceled: its trigger "
                                   "condition cleared before the driver "
                                   "could act", entry["reason"])
                    entry["result"] = {"canceled": True,
                                       "reason": entry["reason"]}
                    continue
                entry["result"] = self._do_evacuate(entry["rids"],
                                                    entry["reason"])
            except Exception as exc:
                logger.exception("evacuation failed")
                entry["result"] = {"error": str(exc)}
            finally:
                entry["event"].set()
        return True

    def _do_evacuate(self, rids: Optional[set], reason: str) -> dict:
        """Export every live slot's mid-decode snapshot into the outbox
        and end its stream with finish_reason "evacuated" (the router
        recognizes the marker, pulls the snapshot, and resumes on a peer
        — server/failover.py). Slots that cannot be snapshotted (fused
        first token still in flight, mid-prefill, adapter'd) end with the
        same marker but NO snapshot: the router's pull 404s and falls
        back to the ``continue_text`` re-prefill it always had — loud,
        never silent truncation. ``engine_evacuations_total{outcome}``
        counts both."""
        # the host view must equal the device view before any export:
        # in-flight dispatches carry tokens the snapshot must include
        while self._inflight:
            self._process_decode()
        summary = {"reason": reason, "snapshot": [], "reprefill": []}

        def count(outcome: str, req: Request) -> None:
            REGISTRY.counter("engine_evacuations_total",
                             labels={"outcome": outcome}).inc()
            summary["snapshot" if outcome == "snapshot"
                    else "reprefill"].append(req.request_id)

        for slot, job in list(self._slots.items()):
            req = job.request
            if rids is not None and req.request_id not in rids:
                continue
            if self._slots.get(slot) is not job:
                continue
            if req.prefill_only:
                continue   # awaiting its own KV export (handoff path)
            payload = None
            if self._snapshot_eligible(job):
                try:
                    payload = self.export_live_slot(job)
                except Exception:
                    logger.exception("snapshot export failed for %s; "
                                     "falling back to re-prefill",
                                     req.request_id)
                    payload = None
            del self._slots[slot]
            self._state = self.core.release(self._state, slot)
            # what this slot computed stays reusable locally either way
            self._cache_insert(job, with_generated=True)
            count("snapshot" if payload is not None else "reprefill", req)
            self._finish_evacuated(job, payload)
        for job in list(self._prefilling):
            req = job.request
            if rids is not None and req.request_id not in rids:
                continue
            self._prefilling.remove(job)
            if job.slot >= 0:
                self._state = self.core.release(self._state, job.slot)
                self._cache_insert(job, with_generated=True)
            count("reprefill", req)
            self._finish_evacuated(job, None)
        with self._lock:
            pending = [j for j in self._pending
                       if rids is None or j.request.request_id in rids]
            for job in pending:
                self._pending.remove(job)
        for job in pending:
            # a SPILLED pending job already holds a complete host-side
            # snapshot — ship exactly that instead of degrading to
            # re-prefill (the spill payload IS export_live_slot's shape)
            payload = job.spill
            if payload is not None:
                self._drop_spill(job, outcome="evacuated")
            count("snapshot" if payload is not None else "reprefill",
                  job.request)
            self._finish_evacuated(job, payload)
        FLIGHT.event("evacuation", reason=reason,
                     snapshots=len(summary["snapshot"]),
                     reprefills=len(summary["reprefill"]))
        return summary

    def _finish_evacuated(self, job: _Job, payload: Optional[dict]) -> None:
        """End an evacuated request's local stream: finish_reason
        "evacuated" (never "error" — the router must treat it as
        resumable, not a dead request), snapshot parked BEFORE the _STOP
        release (a consumer that sees the stream end and immediately
        pulls /v1/kv/evacuation/<rid> must find it), and NO detok flush
        (held UTF-8 bytes re-emerge from the resume side's replay — a
        flush here would stream bytes the oracle never produced)."""
        req = job.request
        req.finish_reason = "evacuated"
        if payload is not None:
            with self._evac_lock:
                self._prune_outbox()
                self._evac_outbox[req.request_id] = (payload,
                                                     clock.mono())
                self._evac_outbox.move_to_end(req.request_id)
                while len(self._evac_outbox) > self._evac_outbox_cap:
                    self._evac_outbox.popitem(last=False)
        req.slo_outcome = req.slo_outcome or "evacuated"
        req.finished_at = clock.perf()
        REGISTRY.counter("requests_finished",
                         labels={"finish": "evacuated"}).inc()
        if TRACE.enabled:
            self._trace("migrate", req, snapshot=payload is not None,
                        generated=len(job.gen_ids))
        slo_mod.SLO.observe(req)
        self._bill_pages(job)
        job.page_clock = 0.0
        self._qos_settle(job)
        usage_mod.USAGE.bill_request(req)
        REQUEST_LOG.record(req)
        if forensics_mod.FORENSICS.enabled:
            forensics_mod.FORENSICS.observe(req)
        req.out_queue.put(_STOP)
        self._release(job)

    # ----------------------------------------------- host-spill preemption

    def _drop_spill(self, job: _Job, outcome: str = "dropped") -> None:
        """Return a dead spilled job's bytes to the pool budget."""
        if job.spill is not None and self._spill is not None:
            self._spill.release(job.request.request_id, outcome=outcome)
        job.spill = None

    def _spill_out(self, job: _Job) -> bool:   # tpulint: hot-path
        """Demote a preemption victim's pages to the host spill pool
        instead of freeing-and-recomputing them: ONE device→host transfer
        now, one host→device transfer at promotion — zero prefill
        programs, token-identical (the snapshot is export_live_slot's).
        False = ineligible or over budget; the caller takes the recompute
        path (same stream contract, just slower)."""
        if self._spill is None or not self._snapshot_eligible(job) \
                or self._slots.get(job.slot) is not job:
            return False
        if chaos_mod.CHAOS.enabled and chaos_mod.CHAOS.spill_fault():
            return False   # injected pool exhaustion: recompute fallback
        req = job.request
        try:
            payload = self.export_live_slot(job, fetch=True)
        except Exception:
            logger.exception("spill export failed for %s; recomputing",
                             req.request_id)
            return False
        if not self._spill.admit(req.request_id, payload):
            return False   # over APP_KV_SPILL_MB: recompute fallback
        if self._tier is not None:
            self._tier_contribute(job, payload)
        job.spill = payload
        del self._slots[job.slot]
        self._state = self.core.release(self._state, job.slot)
        self._cache_insert(job, with_generated=True)
        self._release(job)
        # the job keeps its live detok/stop/grammar stream state — only
        # the KV moved; ids mirror the written context + pending token so
        # re-admission page math covers the next write
        job.ids = list(req.prompt_ids) + list(job.gen_ids)
        job.prefilled = 0
        job.total_len = 0
        job.prefill_started = 0.0
        with self._lock:
            self._pending.appendleft(job)
        req.preemptions += 1
        REGISTRY.counter("preemptions").inc()
        logger.info("spilled request %s at %d generated tokens (%d bytes "
                    "host)", req.request_id, len(job.gen_ids),
                    self._spill.used_bytes)
        if TRACE.enabled:
            self._trace("spill", req, generated=len(job.gen_ids),
                        pool_used_bytes=self._spill.used_bytes)
        return True

    def _tier_contribute(self, job: _Job, payload: dict) -> None:
        """Register a freshly spilled payload's full-page prefix run in
        the prefix tier (engine/kv_tier.py) under its chain hashes: the
        spill registry pins the entry while the spill is live; after the
        rid releases it stays behind as value-priced cache, so FUTURE
        requests sharing the prefix promote instead of re-prefilling.
        Hashes run over the WRITTEN context (prompt + fed-back generated
        tokens) — a returning conversation's next turn extends exactly
        that sequence."""
        req = job.request
        ids = payload.get("prompt_ids") or []
        ps = self.core.page_size
        hashes = chain_hashes([int(t) for t in ids], ps,
                              seed=f"{self._cache_seed}|{req.adapter}")
        depth = min(len(hashes), int(payload.get("n_pages", 0)))
        if depth <= 0:
            return
        self._tier.contribute(
            req.request_id, hashes[:depth], payload, tokens=depth * ps,
            tenant=str(getattr(req, "tenant", "") or ""),
            slack_s=qos_mod.request_remaining_s(req))

    def _admit_spilled(self, job: _Job) -> None:   # tpulint: hot-path
        """Promotion: re-import a spilled job's pages into its freshly
        allocated ones and reactivate the slot at the snapshot position —
        the resume dispatches ZERO prefill programs (the acceptance
        criterion's devtime assertion) and the stream continues
        token-identically. The job never left this scheduler, so detok,
        stop holdback, and emitted text are already live; nothing is
        re-emitted."""
        req = job.request
        payload = job.spill
        job.spill = None
        if self._spill is not None:
            self._spill.release(req.request_id, outcome="promoted")
        now = clock.perf()
        try:
            self._state = self.core.import_slot_kv(
                self._state, job.slot, job.pages, payload)
        except Exception as exc:
            # a local promote cannot fail for wire reasons; anything here
            # is a bug — fail the stream loudly, never serve garbage KV
            logger.exception("spill promote failed for %s", req.request_id)
            self._fail(job, f"kv spill promote failed: {exc}")
            self._release(job)
            return
        job.prefilled = len(job.ids)
        job.total_len = len(job.ids)
        pb = min(pow2_bucket(int(payload.get("n_pages", 1))),
                 int(getattr(self.core, "max_pages_per_slot", 1 << 30)))
        DEVTIME.commit("kv_import", f"p{pb}", self._state.tokens, t0=now,
                       tokens=int(payload.get("length", 0)), mfu=False,
                       retain=False)
        REGISTRY.counter("spill_resumes").inc()
        req.spill_resumes += 1
        if TRACE.enabled:
            self._trace("promote", req, source="spill",
                        generated=len(job.gen_ids),
                        length=int(payload.get("length", 0)))
        if self._spec_w > 1 and hasattr(self.core, "seed_history"):
            self._state = self.core.seed_history(self._state, job.slot,
                                                 job.ids)
        gs = self._gram_state_for(job) if req.grammar is not None else 0
        kw = {"gram_state": gs} if gs else {}
        self._state = self.core.activate(
            self._state, job.slot, int(job.gen_ids[-1]), len(job.gen_ids),
            req.max_tokens, req.temperature, req.top_k, req.top_p,
            seed=req.seed or 0, **kw)
        self._slots[job.slot] = job

    def _emit_token(self, job: _Job, tok: int, lp: Optional[float] = None,
                    top: Optional[list] = None) -> bool:
        """Append a generated token: detokenize, scan stop sequences,
        stream the emit-safe text. Returns True when a stop sequence
        matched — the caller must retire the slot."""
        job.gen_ids.append(tok)
        job.request.completion_tokens += 1
        job.total_len += 1
        req = job.request
        if req.logprobs:
            req.logprob_data.append((tok, lp, top))
        delta = job.detok.push(tok)
        if req.stop:
            emit, job.stop_buf, stopped = _stop_scan(req.stop,
                                                     job.stop_buf + delta)
            if emit:
                req.out_queue.put(emit)
            if stopped:
                job.stopped = True
                req.finish_reason = "stop"
                return True
        elif delta:
            req.out_queue.put(delta)
        return False

    # -- decode -------------------------------------------------------------

    def _grow_pages(self, steps: int, spec_w: Optional[int] = None) -> int:
        """Give every active slot pages for its next writes, targeting a
        ``steps``-deep dispatch. Preemption (youngest first) only kicks in
        when even ONE step cannot be covered; mere horizon pressure instead
        shrinks the dispatch depth. Returns the number of fused steps every
        surviving slot has pages for (>= 1). ``spec_w`` is the PLANNED
        dispatch width (defaults to the ceiling) — with the adaptive
        ladder the horizon tracks the width actually dispatched, not the
        widest rung, so a wide ceiling cannot hoard pool slack it will
        never write (under-coverage is still safe either way: the kernel
        clamps acceptance to the covered span)."""
        spec_w = spec_w or self._spec_w
        effective = steps
        for slot in list(self._slots):
            job = self._slots.get(slot)
            if job is None:
                continue
            if getattr(job.request, "prefill_only", False):
                continue   # awaiting KV export; never decode-advances
            while self._slots.get(slot) is job:
                # total_len is the host view (updated only when a dispatch is
                # processed); writes already in flight plus this dispatch's
                # K steps land at indices up to total_len + pending +
                # K·W - 1 (W = speculative width; ceiling: covers just-
                # activated and mid-decode cases). Device-side out_of_cache
                # keeps writes under max_seq, mirrored here by the
                # table-row clamp; rows the grower could not cover land on
                # the null page and the device clamps acceptance to the
                # covered span, so a starved grow costs speculation, not
                # correctness.
                next_write = job.total_len + self._pending_steps
                target = min(
                    self.core.pages_for(next_write + steps * spec_w - 1),
                    self.core.max_pages_per_slot)
                minimum = min(self.core.pages_for(next_write),
                              self.core.max_pages_per_slot)
                if len(job.pages) >= target:
                    break
                got = self._alloc_pages(1)
                if got is not None:
                    # bill the held window at the OLD page count before
                    # the count changes (usage-plane page-seconds)
                    self._bill_pages(job)
                    self._table[slot, len(job.pages)] = got[0]
                    job.pages.extend(got)
                    self._table_dev = None
                    continue
                if len(job.pages) >= minimum:
                    break  # one step covered; just shrink the horizon
                if self._inflight:
                    # the host view is up to pending_steps stale — in-flight
                    # results may already finish this job or free pages.
                    # Drain before any destructive decision (rare slow path).
                    while self._inflight:
                        self._process_decode()
                    continue  # re-evaluate with fresh totals
                victim = self._pick_victim()
                self._preempt(victim)
                if victim is job:
                    break  # the grower was youngest: it waits in the queue
            if self._slots.get(slot) is not job:
                continue  # finished while draining, or preempted itself
            if len(job.pages) < self.core.max_pages_per_slot:
                next_write = job.total_len + self._pending_steps
                covered = len(job.pages) * self.core.page_size - next_write
                effective = max(1, min(effective,
                                       covered // spec_w))
            # at full table capacity the device-side out_of_cache guard ends
            # the slot before it could outrun its row — no clamp needed
        # round down to a power of two: `steps` is a compile-time constant of
        # the decode program, so an unbounded range of values would trigger
        # a fresh XLA compile (seconds) mid-serving under page pressure
        p2 = 1
        while p2 * 2 <= effective:
            p2 *= 2
        return p2

    def _pick_victim(self) -> _Job:
        """Youngest admission — decoding slots and mid-prefill jobs alike
        (both hold pages). The growing job is a candidate too: if IT is the
        youngest, it preempts itself rather than evicting an older request
        (no thrash — resumes keep their original admission age).

        With the QoS plane armed the pick is slack-aware instead of
        age-only (engine/qos.py pick_victim): overusing tenants' jobs go
        first (virtual-time lead — the flood pays for the pool pressure
        it causes), then the job with the most SLO slack, with admission
        age as the tie-break; the spill path in _preempt composes
        unchanged, so overusing tenants SPILL first too."""
        cands = (list(self._prefilling) + list(self._slots.values()))
        if self._qos is not None:
            return self._qos.pick_victim(cands)
        return max(cands, key=lambda j: j.admit_seq)

    def _preempt(self, job: _Job) -> None:
        """Preemption under page pressure. With the host spill pool armed
        (APP_KV_SPILL_MB), a decoding victim's pages DEMOTE to host RAM
        and promote back at re-admission — one transfer each way instead
        of a full re-prefill recompute (engine/spill.py). Everything
        ineligible (mid-prefill, unresolved first token, adapter'd, pool
        over budget) keeps the recompute path: free the slot, requeue
        prompt+generated."""
        if self._spill_out(job):
            return
        if job.slot in self._slots and self._slots[job.slot] is job:
            del self._slots[job.slot]
        else:
            self._prefilling.remove(job)
        self._state = self.core.release(self._state, job.slot)
        # cache what this slot already computed: the resume's re-prefill
        # re-admits against these pages instead of recomputing from token 0
        self._cache_insert(job, with_generated=True)
        self._release(job)
        job.ids = list(job.request.prompt_ids) + list(job.gen_ids)
        job.prefilled = 0
        job.total_len = 0
        job.prefill_started = 0.0   # the resume's re-prefill is a fresh sample
        # an unsynced first token is recomputed by the resume's re-prefill
        job.first_pending = False
        job.first_batched = False
        job.first_inflight = False
        with self._lock:
            self._pending.appendleft(job)
        job.request.preemptions += 1
        REGISTRY.counter("preemptions").inc()
        logger.info("preempted request %s at %d generated tokens",
                    job.request.request_id, len(job.gen_ids))
        if TRACE.enabled:
            self._trace("preempt", job.request, mode="recompute",
                        generated=len(job.gen_ids))

    @property
    def _steps(self) -> int:
        """Fused decode steps per dispatch. At least the full configured
        depth: round 2 halved this while a prefill was in flight (finer
        chunk interleave), which under sustained load meant HALF the
        tokens per ~100 ms dispatch round trip almost all of the time —
        measured as the difference between ~500 and ~900+ tok/s at 2x
        load. Queued prompts still interleave between dispatches; the
        device-side wait behind a full pipeline is ~depth x 30 ms, a
        small TTFT cost next to that throughput cliff.

        When ``decode_steps_max`` is set, dispatches DEEPEN while every
        active slot still has the generation budget to use every fused
        step (minimum remaining budget net of steps already in flight —
        budget-floored, so deepening never wastes end-of-request steps):
        the serialized result-fetch channel (~10/s) is the throughput
        ceiling, and a deeper dispatch moves up to 2x the tokens through
        the same fetch. Gated on a half-full batch so ramp-time admissions
        keep the fine-grained interleave."""
        base = max(1, self.core.cfg.decode_steps_per_dispatch)
        cap = self.core.cfg.decode_steps_max or base
        if cap <= base or len(self._slots) < self.core.batch // 2:
            return base
        rem = (min(j.request.max_tokens - len(j.gen_ids)
                   for j in self._slots.values())
               - self._pending_steps) // self._spec_w
        steps = base
        while steps * 2 <= min(cap, rem):
            steps *= 2
        return steps

    def _long_pass_claims(self, job: _Job) -> bool:
        """Will the sequence-parallel long-prefill pass take this job's
        whole prompt? ONE predicate shared by the grouped packer
        (_prefill_step_inner) and the mixed packer (_mixed_eligible) — if
        the two ever disagreed, a job the ring pass expects could be
        consumed chunk-by-chunk instead (or vice versa)."""
        req = job.request
        return (job.prefilled == 0 and len(job.ids) > self.core.chunk
                and req.grammar is None and not req.adapter
                and self.core.cfg.long_prefill != "off"
                and self.core.supports_long_prefill)

    def _mixed_eligible(self, job: _Job) -> bool:
        """May this prefilling job's NEXT chunk ride the decode dispatch
        (engine.decode_mixed)? The packing policy is the existing chunked-
        prefill sizing; what stays on the two-dispatch path: jobs the
        sequence-parallel long pass will claim, adapter'd jobs (the mixed
        forward runs base weights only), prefill_only handoff jobs (their
        export path stays on the grouped program), and the BULK of very
        long prompts — the mixed program fuses one chunk per job per
        dispatch while the grouped path moves up to prefill_group chunks
        per tick, so a prompt with more than a group of chunks left would
        prefill group-times slower fused; it takes the grouped path until
        its tail fits one group. Grammared FINAL chunks ride too (r06):
        the mixed activation tail samples the fused first token under the
        DFA exactly as the grouped program does — constrained decoding no
        longer pays a separate-dispatch tax."""
        req = job.request
        if job.adapter_ix or req.adapter:
            return False
        if getattr(req, "prefill_only", False):
            return False
        if self._long_pass_claims(job):
            return False
        remaining = len(job.ids) - job.prefilled
        if remaining > max(1, self.core.cfg.prefill_group) * self.core.chunk:
            return False
        return True

    def _pack_mixed_chunks(self):   # tpulint: hot-path
        """Build every prefilling job's next chunk as PrefillItems to ride
        THIS decode dispatch as extra ragged rows — one chunk per DISTINCT
        job (their slots are disjoint by construction, so the fused page
        scatters never collide). Called AFTER _grow_pages (whose page-
        pressure preemption may evict jobs), so every check re-runs against
        post-grow state; returns (items, [(job, is_last), …]) or None (the
        chunks then take the normal grouped-prefill dispatch next tick)."""
        from generativeaiexamples_tpu.engine.engine import PrefillItem
        if (not self._prefilling or not self._slots
                or not getattr(self.core, "mixed_supported", False)):
            return None
        jobs = list(self._prefilling)
        cap = max(1, getattr(self.core.cfg, "prefill_group", 1))
        if len(jobs) > cap:
            return None
        if any(not self._mixed_eligible(j) for j in jobs):
            return None
        items, metas = [], []
        for job in jobs:
            req = job.request
            start = job.prefilled
            chunk_ids = job.ids[start:start + self.core.chunk]
            last = start + len(chunk_ids) >= len(job.ids)
            if start == job.shared:
                job.prefill_started = clock.perf()
                if req.prefill_start_at is None:
                    req.prefill_start_at = job.prefill_started
            # grammared finals sample their fused first token under the
            # DFA inside the mixed program (engine._activate_group) — the
            # same registration/walk the grouped path runs
            gram_state = self._gram_state_for(job) if last else 0
            items.append(PrefillItem(
                chunk_ids=chunk_ids, page_row=self._table[job.slot],
                slot=job.slot, start_pos=start, is_last=last,
                generated=len(job.gen_ids) + 1, max_gen=req.max_tokens,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, gram_state=gram_state,
                seed=req.seed or 0))
            metas.append((job, last))
        return items, metas

    # EMA smoothing of the acceptance signal, and the headroom multiplier
    # between the trailing accepted-drafts mean and the offered draft
    # width: cap = ceil(headroom x ema). Headroom > 1 lets a slot whose
    # drafts all land climb back up the ladder (ema == d → cap > d).
    _SPEC_EMA_ALPHA = 0.3
    _SPEC_HEADROOM = 2.0
    # below this trailing acceptance the slot gets ZERO draft budget:
    # capping only voids drafted positions (exact-match acceptance —
    # token-identical), and a draft-cap-0 fleet is what lets the
    # multi-step decode path engage mid-generation once drafts stop
    # landing (_multi_plan requires no speculative widening pending)
    _SPEC_MIN_EMA = 0.05

    def _choose_draft(self, job: _Job) -> int:
        """Acceptance-tuned draft budget for one slot: the smallest ladder
        draft covering headroom x trailing-acceptance, so a slot whose
        drafts keep missing stops paying full-width verify positions while
        a quoting slot keeps the whole ladder. Exact-match acceptance makes
        any cap token-identical — this tunes waste, never content."""
        if job.spec_ema < 0:
            job.spec_ema = self._spec_ema_global
        if job.spec_ema < self._SPEC_MIN_EMA:
            return 0
        want = math.ceil(self._SPEC_HEADROOM * job.spec_ema)
        top = self._spec_w - 1
        for w in self._spec_widths:
            if w - 1 >= want:
                return min(w - 1, top)
        return top

    def _spec_plan(self):
        """(dispatch spec width, per-slot draft caps) for THIS dispatch:
        caps from each slot's acceptance EMA, width = the smallest ladder
        rung covering every cap (one compile per rung, all warmed).
        Returns (ceiling, None) when the engine has no adaptive ladder —
        the static pre-r06 dispatch, bit-for-bit."""
        if self._spec_w <= 1 or len(self._spec_widths) <= 1:
            return self._spec_w, None
        caps = np.zeros((self.core.batch,), np.int32)
        top = 0
        for slot, job in self._slots.items():
            d = self._choose_draft(job)
            caps[slot] = d
            top = max(top, d)
        w_disp = next((w for w in self._spec_widths if w >= 1 + top),
                      self._spec_widths[-1])
        return w_disp, caps

    def _note_acceptance(self, out: Dict[str, np.ndarray], steps: int,
                         w_disp: int, active_map: Dict[int, "_Job"]) -> None:
        """Feed the adaptive controller + the spec telemetry from one
        landed dispatch: per widened step, the accepted-draft length
        (emitted tokens - 1) updates the slot's EMA and the scrapeable
        ``spec_accept_len`` histogram (the controller's input signal)."""
        if w_disp <= 1:
            return
        em = out["emitted"].reshape(-1, w_disp, out["emitted"].shape[1])
        per_step = em.sum(axis=1)                      # (steps, B)
        REGISTRY.counter("spec_bonus_tokens").inc(
            int(np.maximum(per_step - 1, 0).sum()))
        REGISTRY.counter("spec_base_steps").inc(int((per_step > 0).sum()))
        accept_h = REGISTRY.histogram("spec_accept_len")
        a = self._SPEC_EMA_ALPHA
        for slot, job in active_map.items():
            if job.gram_on:
                # constrained slots decode sequentially (their drafts are
                # voided in the engine) — their structural 0-acceptance is
                # not a property of the workload's draftability and must
                # not depress the controller's signal or the global seed
                continue
            col = per_step[:, slot]
            live = col > 0
            n_live = int(live.sum())
            if not n_live:
                continue
            accepted = col[live] - 1                   # drafts accepted
            for v in accepted:
                accept_h.observe(float(v))
            mean = float(accepted.mean())
            if job.spec_ema < 0:
                job.spec_ema = self._spec_ema_global
            job.spec_ema = (1 - a) * job.spec_ema + a * mean
            self._spec_ema_global = ((1 - a) * self._spec_ema_global
                                     + a * mean)

    def _multi_plan(self) -> int:
        """M multiplier for THIS dispatch (0 = the per-step path). The
        multi-step eligibility predicate — ALL of:

          * the core compiled a multi-step ladder (``multi_ms`` non-empty);
          * every live slot is plain steady-state decode: no grammar, no
            top-logprobs (separate program variants), no partial
            stop-string match already held back (``stop_buf`` — a stop is
            imminent, the per-step path ends it with minimal overshoot);
          * no speculative widening pending: spec off, or the adaptive
            controller currently budgets ZERO drafts for every slot
            (acceptance collapsed — the widened program would verify
            nothing, so the multi-step scan gives strictly more tokens
            per host interaction);
          * the shallowest rung's K·M still fits every slot's remaining
            generation budget (minus in-flight positions) — a multi-step
            dispatch must not overshoot a max_tokens finish by a whole
            block.

        Returns the LARGEST warmed rung whose K·M fits; page-pressure may
        still shrink it at dispatch (`_dispatch_decode` halves M until the
        grown page horizon covers it)."""
        ms = getattr(self.core, "multi_ms", ())
        if not ms or not self._slots:
            return 0
        for j in self._slots.values():
            if j.gram_on or j.stop_buf:
                return 0
            if j.request.logprobs and j.request.top_logprobs > 0:
                return 0
        if self._spec_w > 1:
            if len(self._spec_widths) <= 1:
                return 0   # static spec width: always widening
            if any(self._choose_draft(j) > 0 for j in self._slots.values()):
                return 0
        base = max(1, self.core.cfg.decode_steps_per_dispatch)
        rem = min(j.request.max_tokens - len(j.gen_ids)
                  for j in self._slots.values()) - self._pending_steps
        m = 0
        for rung in ms:
            if base * rung <= max(rem, 0):
                m = rung
        return m

    def _decode_width(self) -> int:
        """Batch-width ladder rung for a PURE decode dispatch: the smallest
        pre-compiled width covering the highest live slot (lowest-id-first
        allocation compacts the live set). Mixed dispatches keep the full
        width — their rows are already filled by fused chunks."""
        if len(self._decode_widths) <= 1 or not self._slots:
            return self.core.batch
        hi = max(self._slots) + 1
        return next(w for w in self._decode_widths if w >= hi)

    def _dispatch_decode(self, try_mixed: bool = False) -> None:   # tpulint: hot-path
        """Issue one K-step decode dispatch without waiting for its result
        (dispatch-ahead pipelining: the transfer of dispatch N overlaps the
        compute of dispatch N+1, hiding host-device sync latency entirely —
        the difference between ~470 and ~900 tok/s over a remote-attached
        chip). Freshly-activated slots are snapshotted with the dispatch so
        their fused-prefill first token is resolved against the right step-0
        input."""
        # plan the spec width FIRST: the page-grow horizon tracks the
        # width actually dispatched, not the ladder ceiling (a 2x-wide
        # ceiling must not hoard pool slack it will never write into)
        w_plan, _caps_plan = self._spec_plan()
        # multi-step plan: mixed dispatches carry prefill chunks (per-step
        # by construction); otherwise an eligible steady-state fleet runs
        # K·M steps with ONE deferred fetch. Page pressure halves M until
        # the grown horizon covers the whole block; below M=2 the plan
        # degrades to the ordinary per-step dispatch.
        if (not try_mixed and self._slots
                and getattr(self.core, "multi_ms", ())):
            # kill the dispatch tail outright: once in-flight steps cover
            # EVERY live slot's remaining budget, another dispatch can
            # only produce rows the result path discards (a slot cannot
            # decode past max_tokens, and EOS/stop only end it sooner) —
            # skip instead of burning a device program plus a host fetch
            # on padding. Re-evaluated every tick: if a preemption voids
            # the in-flight work, _pending_steps drains and dispatch
            # resumes. Gated on a compiled multi ladder so the legacy
            # pipeline keeps its exact dispatch cadence when the feature
            # is off.
            if (max(j.request.max_tokens - len(j.gen_ids)
                    for j in self._slots.values()) <= self._pending_steps):
                return
        multi_m = 0 if try_mixed else self._multi_plan()
        if multi_m:
            base = max(1, self.core.cfg.decode_steps_per_dispatch)
            grown = self._grow_pages(base * multi_m, 1)
            while multi_m >= 2 and base * multi_m > grown:
                multi_m //= 2
            if multi_m < 2:
                multi_m = 0
            steps = base if multi_m else self._grow_pages(self._steps,
                                                          w_plan)
        else:
            steps = self._grow_pages(self._steps, w_plan)
        if not self._slots:
            return
        packed_chunks = self._pack_mixed_chunks() if try_mixed else None
        fresh = [(s, j) for s, j in self._slots.items()
                 if j.first_pending and not j.first_inflight]
        for _, j in fresh:
            j.first_inflight = True   # only the first dispatch resolves it
        t0 = clock.perf()
        use_grammar = any(j.gram_on for j in self._slots.values())
        want_top = any(j.request.logprobs and j.request.top_logprobs > 0
                       for j in self._slots.values())
        # adaptive spec width: per-slot draft caps + the covering ladder
        # rung, re-planned AFTER _grow_pages (page-pressure preemption may
        # have evicted slots; fewer slots never widen the rung, so the
        # grown horizon stays sufficient). Static pre-r06 call shape when
        # the core has no ladder, so FakeCore / older cores see the
        # unchanged signature.
        w_disp, caps = self._spec_plan()
        if packed_chunks is not None:
            # mixed-phase dispatch: every prefilling job's next chunk rides
            # the decode program as extra ragged rows — active slots'
            # decode tick is not stalled by a separate prefill dispatch.
            # Grammared finals carry their DFA state as a ragged-row
            # attribute, so constrained jobs ride this path too. Mixed
            # always runs the CEILING spec width, uncapped (the ragged
            # kernel pads rows to q_block regardless — a cap would only
            # cut accepted drafts) and the full batch width.
            items, mixed_metas = packed_chunks
            if any(it.gram_state for it in items):
                use_grammar = True
            w_disp, caps = self._spec_w, None
            width = self.core.batch
            self._state, out = self.core.decode_mixed(
                self._state, self._table_device(), steps, items, use_grammar,
                want_top)
            self._mixed_dispatches += 1
            REGISTRY.counter("mixed_dispatches").inc()
            REGISTRY.counter("prefill_chunks").inc(len(items))
        elif multi_m:
            # multi-step decode: K·M plain steps, one dispatch, ONE
            # deferred fetch (the decode-dispatch-tail killer). The
            # eligibility predicate (_multi_plan) guaranteed every slot
            # is plain steady-state decode, so the program runs spec
            # width 1 at full batch. Stop-bearing slots get the
            # conservative on-device maybe-match pause: the union of
            # live stop strings builds the suspect table, has_stop marks
            # which slots it can pause — the host replay below
            # (_process_decode → _emit_token) stays the stop-string
            # truth, exactly as on the per-step path.
            w_disp, caps = 1, None
            width = self.core.batch
            stop_set = sorted({s for j in self._slots.values()
                               for s in j.request.stop})
            has_stop = np.zeros((self.core.batch,), np.bool_)
            for s, j in self._slots.items():
                if j.request.stop:
                    has_stop[s] = True
            if stop_set and hasattr(self.core, "ensure_token_bytes"):
                # vocab byte table: the suspect predicate's input (lazy —
                # grammarless deployments without stop strings never pay it)
                self.core.ensure_token_bytes(self.tokenizer)
            self._state, out = self.core.decode_multi(
                self._state, self._table_device(), steps, multi_m,
                stops=tuple(stop_set), has_stop=has_stop)
        else:
            if use_grammar or want_top:
                # minority program variants stay at the ceiling width and
                # full batch — warmup does not cross the ladders with them
                # (bounded compile grid; see EngineCore.warmup)
                w_disp, caps = self._spec_w, None
                width = self.core.batch
            else:
                # batch-width ladder: the narrowest pre-compiled rung
                # covering every live slot — at low occupancy the padded
                # (batch x W) token block shrinks with the live set
                width = self._decode_width()
            width_kw = ({} if caps is None
                        else {"spec_width": w_disp, "draft_cap": caps})
            if width != self.core.batch:
                width_kw["width"] = width
            self._state, out = self.core.decode(
                self._state, self._table_device(), steps, use_grammar,
                want_top, **width_kw)
        self._decode_dispatches += 1
        # kernel occupancy of this dispatch's query rows: active query
        # positions over padded positions. Fused chunks pad to the full
        # prefill_chunk bucket (and the group to its power-of-two bucket),
        # and inside a mixed dispatch every decode slot's row pads to the
        # engine's padded row width (q_block under the ragged kernel,
        # spec_w under the XLA fallback) — the gauge must report what the
        # kernel actually ran
        if caps is None:
            active_q = len(self._slots) * w_disp
        else:
            # adaptive widths: each slot's useful positions are its own
            # 1 + draft_cap, not the dispatch ceiling
            active_q = sum(1 + int(caps[s]) for s in self._slots)
        padded_q = width * w_disp
        if packed_chunks is not None:
            row_q = getattr(self.core, "mixed_row_queries", self._spec_w)
            g_bucket = next(b for b in self.core.group_buckets
                            if len(items) <= b)
            active_q += sum(len(it.chunk_ids) for it in items)
            padded_q = (self.core.batch * row_q
                        + g_bucket * self.core.chunk)
        self._ragged_row_util = active_q / padded_q
        REGISTRY.gauge("ragged_row_util").set(round(self._ragged_row_util, 4))
        REGISTRY.histogram("decode_issue_s").observe(clock.perf() - t0)
        REGISTRY.histogram("decode_batch_fill").observe(
            len(self._slots) / self.core.batch)
        if TRACE.enabled:
            TRACE.emit("dispatch", phase="decode", steps=steps,
                       width=width, slots=len(self._slots),
                       mixed=packed_chunks is not None,
                       fill=round(len(self._slots) / self.core.batch, 4),
                       rids=",".join(j.request.request_id
                                     for j in self._slots.values()))
        # devtime ledger (observability/devtime.py): classify this dispatch
        # into its XLA compile-unit key. Grammar and top-logprob variants
        # ARE separate compiles (static args), so they split the program
        # name; tokens are useful positions (steps x active slots x spec
        # width, plus fused chunk tokens — chunks run once, not per step).
        # With APP_DEVTIME=off this only counts; no fence is ever taken.
        suffix = (("+gram" if use_grammar else "")
                  + ("+top" if want_top else ""))
        if caps is None:
            dec_useful = steps * len(self._slots) * w_disp
        else:
            dec_useful = steps * sum(1 + int(caps[s]) for s in self._slots)
        if packed_chunks is not None:
            bucket = (self.core.mixed_bucket(g_bucket, steps)
                      if hasattr(self.core, "mixed_bucket")
                      else f"g{g_bucket}s{steps}")
            DEVTIME.commit(
                f"mixed{suffix}", bucket, out["packed"], t0=t0,
                tokens=(dec_useful
                        + sum(len(it.chunk_ids) for it in items)),
                padded_tokens=(steps * self.core.batch * w_disp
                               + g_bucket * self.core.chunk),
                weight_passes=float(steps))
        elif multi_m:
            # useful-vs-padded census is DEFERRED to result time
            # (DEVTIME.note_tokens in _process_decode): a slot may end or
            # pause mid-scan, so useful tokens are only known once the
            # block's emitted mask lands on the host — committing M·B
            # useful here would flatter engine_padding_waste_frac
            bucket = (self.core.decode_multi_bucket(steps, multi_m)
                      if hasattr(self.core, "decode_multi_bucket")
                      else f"s{steps}m{multi_m}")
            DEVTIME.commit(
                "decode_multi", bucket, out["packed"], t0=t0,
                tokens=0, padded_tokens=0,
                weight_passes=float(steps * multi_m), defer_census=True)
        else:
            bucket = (self.core.decode_bucket(steps, w_disp, width)
                      if hasattr(self.core, "decode_bucket")
                      else f"s{steps}")
            DEVTIME.commit(
                f"decode{suffix}", bucket, out["packed"], t0=t0,
                tokens=dec_useful,
                padded_tokens=steps * width * w_disp,
                weight_passes=float(steps))
        # hand the result to a fetcher thread NOW: the device→host round
        # trip (~100 ms over a remote-attached chip) overlaps further
        # dispatching instead of serializing into the driver loop. (Round 3
        # also tried pairing two dispatches' outputs into one transfer —
        # fewer round trips, but tokens then land a dispatch later, slot
        # turnover slows, and measured throughput was net WORSE.)
        n_steps = steps * multi_m if multi_m else steps
        packed = self._fetcher.submit(_fetch, out["packed"], "fetch_rtt_s",
                                      n_steps)
        # snapshot slot→job at dispatch time: a slot freed and reused while
        # this dispatch is in flight must not leak the old job's tokens into
        # the new job's stream (identity-checked at processing).
        # in-flight accounting is in POSITIONS (steps × speculative width);
        # (issue instant, steps) rides along for the watchdog's hung-
        # dispatch bound (engine/watchdog.py reads the head entry's age).
        # A multi-step dispatch counts its FULL K·M as steps (w_disp is 1
        # there, so positions == steps too) and appends a sixth element —
        # the deferred devtime-census key — which every other consumer
        # (watchdog head peek, chaos tests) ignores by unpacking entry[:5].
        entry = (n_steps * w_disp, packed, fresh, dict(self._slots),
                 (clock.mono(), n_steps))
        if multi_m:
            entry += (("decode_multi", bucket,
                       float(n_steps * self.core.batch)),)
        self._inflight.append(entry)
        self._pending_steps += n_steps * w_disp
        REGISTRY.counter("decode_steps").inc(n_steps)
        if packed_chunks is not None:
            # the fused chunks' writes are now dispatched: advance each
            # job's prefill bookkeeping exactly as _prefill_step_inner
            # does. An is_last chunk activated its slot ON DEVICE at the
            # end of the dispatch (after the fused decode steps), so the
            # job joins _slots AFTER the in-flight snapshot above — its
            # first token resolves via the next dispatch / batched fetch,
            # never against this dispatch's stale step-0 inputs.
            for (mixed_job, mixed_last), it in zip(mixed_metas, items):
                mixed_job.prefilled = it.start_pos + len(it.chunk_ids)
                mixed_job.total_len = mixed_job.prefilled
                if mixed_last:
                    self._prefilling.remove(mixed_job)
                    self._cache_insert(mixed_job)
                    self._enter_decode(mixed_job)

    def _process_decode(self) -> None:   # tpulint: hot-path
        """Sync + fan out the OLDEST in-flight dispatch (FIFO). Rows of the
        packed block are (step, position) micro-steps; with speculation a
        step can emit up to W accepted tokens."""
        # PEEK, don't pop: while this thread blocks in result() the entry
        # must stay visible as _inflight[0] — it is exactly the dispatch
        # the watchdog's hung-dispatch bound has to see (popping first
        # would hide a wedged dispatch and degrade detection to the much
        # coarser tick-stall heartbeat)
        entry = self._inflight[0]
        positions, packed, fresh, active_map, issued = entry[:5]
        # sixth element (multi-step dispatches only): the deferred
        # devtime-census key — useful tokens are only known now
        multi_meta = entry[5] if len(entry) > 5 else None
        # one transfer per dispatch, already in flight on the fetcher thread
        t0 = clock.perf()
        out = unpack_decode_out(packed.result())
        self._inflight.popleft()
        self._pending_steps -= positions
        REGISTRY.histogram("sync_wait_s").observe(clock.perf() - t0)
        now = clock.perf()
        REGISTRY.counter("tokens_generated").inc(int(out["emitted"].sum()))
        if multi_meta is not None:
            # deferred useful-vs-padded census: only rows the scan actually
            # emitted count as useful (early-exited / paused slots stop
            # emitting mid-block), so engine_padding_waste_frac stays
            # honest for multi-step dispatches
            m_prog, m_bucket, m_padded = multi_meta
            DEVTIME.note_tokens(m_prog, m_bucket,
                                float(out["emitted"].sum()), m_padded)
        # acceptance telemetry + the adaptive-width controller's EMA feed;
        # the dispatch's OWN width (positions / steps — ladder rungs vary
        # per dispatch), never the engine ceiling
        self._note_acceptance(out, issued[1], positions // issued[1],
                              active_map)
        for slot, job in fresh:
            if self._slots.get(slot) is not job:
                continue  # preempted while in flight; resume re-samples
            self._resolve_first(job, int(out["input_tokens"][0, slot]), now,
                                float(out["input_lp"][0, slot]))
        for slot, job in active_map.items():
            if self._slots.get(slot) is not job:
                continue  # finished or preempted since this dispatch
            req = job.request
            n_top = (min(req.top_logprobs, len(out.get("top_ids", ())))
                     if req.logprobs else 0)
            for k in range(out["sampled"].shape[0]):
                if not out["emitted"][k, slot]:
                    continue
                if not (out["done"][k, slot] and out["hit_eos"][k, slot]):
                    lp = (float(out["sampled_lp"][k, slot])
                          if req.logprobs else None)
                    top = ([(int(out["top_ids"][j, k, slot]),
                             float(out["top_lps"][j, k, slot]))
                            for j in range(n_top)] if n_top else None)
                    if self._emit_token(job, int(out["sampled"][k, slot]),
                                        lp, top):
                        self._retire(job)
                        break
                if out["done"][k, slot]:
                    # the device ends a slot for EOS, generation budget, or
                    # cache capacity — everything but EOS is a truncation
                    req.finish_reason = ("eos" if out["hit_eos"][k, slot]
                                         else "length")
                    del self._slots[slot]
                    self._finish(job)
                    break

    # -- driver loop --------------------------------------------------------

    def _flight_fields(self) -> Dict[str, object]:
        """One flight-recorder sample of scheduler state. Called only when a
        sample is due (FLIGHT.maybe_sample time-gates), so the lock grab and
        counter reads are off the per-tick fast path."""
        with self._lock:
            waiting = len(self._pending)
        free = int(self._alloc.available)
        total = int(self.core.num_pages)
        return {
            "fill": round(len(self._slots) / self.core.batch, 4),
            "running": len(self._slots),
            "prefilling": len(self._prefilling),
            "waiting": waiting,
            "inflight_dispatches": len(self._inflight),
            "kv_pages_free": free,
            "kv_pages_used": total - free,
            "prefix_hit_tokens": REGISTRY.counter("prefix_hit_tokens").value,
            "preemptions": REGISTRY.counter("preemptions").value,
            "tokens_generated": REGISTRY.counter("tokens_generated").value,
            # mixed-phase dispatch observables (mirrored as flight_* gauges):
            # what fraction of decode dispatches fused a prefill chunk, and
            # the last dispatch's active/padded query-row utilization —
            # kernel occupancy next to the slot-level `fill`
            "mixed_dispatch_frac": round(
                self._mixed_dispatches / self._decode_dispatches, 4)
                if self._decode_dispatches else 0.0,
            "ragged_row_util": round(self._ragged_row_util, 4),
            # padded-vs-useful token fraction over the ledger's trailing
            # window (observability/devtime.py) — what the batch-width and
            # spec-width ladders exist to shrink; mirrored to the
            # flight_padding_waste_frac gauge like every numeric field
            "padding_waste_frac": round(DEVTIME.padding_waste(), 4),
            # devtime plane: mid-serving XLA recompiles so far (the cliff
            # counter, engine_recompiles_total) and the device+queue+issue
            # seconds the ledger has attributed to named programs — both
            # mirror to flight_* gauges like every numeric field here
            "recompiles": REGISTRY.counter("engine_recompiles_total").value,
            "devtime_attributed_s": round(DEVTIME.attributed_s(), 4),
            # decode-dispatch-tail telemetry: trailing mean decode steps
            # amortized per device→host result fetch (K on the per-step
            # path, K·M when multi-step dispatches engage)
            "steps_per_fetch": round(DEVTIME.steps_per_fetch(), 2),
        }

    def _tick(self) -> bool:   # tpulint: hot-path
        """One scheduling round; returns False when fully idle."""
        # chaos plane (observability/chaos.py): injected tick stalls (what
        # the watchdog heartbeat detects) and worker death (propagates to
        # the driver loop's crash handler — every in-flight request fails
        # loudly, state resets). Off = one attribute read, nothing more.
        if chaos_mod.CHAOS.enabled:
            chaos_mod.CHAOS.tick_fault()
        # queued evacuations (drain/SIGTERM/watchdog-trip/router pull) run
        # FIRST: the driver owns the device state, and an evacuating
        # worker's remaining ticks should move streams out, not advance
        # them further on a worker the router is already routing around
        worked = self._run_evacuations()
        # continuous per-step telemetry: the ring the /debug/flight window,
        # SIGUSR1 dump, and bench.py occupancy stats all read. Idle ticks
        # sample too (the 50 ms wake loop keeps calling _tick), so a
        # post-incident window shows the queue draining to zero, not a gap.
        FLIGHT.maybe_sample(self._flight_fields)
        # eager drain: any dispatch whose result already landed on the host
        # resolves NOW — first tokens stamp and done slots free without
        # waiting for the pipeline-depth backpressure point
        while self._inflight and self._inflight[0][1].done():
            self._process_decode()
            worked = True
        # landed batched first-token fetches resolve without waiting for a
        # decode sync — the TTFT path while decode is held during ramps
        landed = [ff for ff in self._first_fetches if ff[0].done()]
        if landed:
            # complement by identity, NOT a second done() scan — a fetch
            # completing between two scans would fall into neither list
            # and its jobs' first tokens would never resolve
            landed_ids = {id(ff) for ff in landed}
            self._first_fetches = [ff for ff in self._first_fetches
                                   if id(ff) not in landed_ids]
            now = clock.perf()
            for fut, pairs in landed:
                snap_host = fut.result()      # (2, B): tokens, logprob bits
                for slot, job, epoch in pairs:
                    # identity AND epoch: the job may have been preempted
                    # and RE-admitted into the same slot while this fetch
                    # was in flight — its first token is a fresh sample,
                    # not the one this snapshot carries
                    if (self._slots.get(slot) is job
                            and job.first_epoch == epoch):
                        self._resolve_first(job, int(snap_host[0, slot]),
                                            now,
                                            bits_to_f32(snap_host[1, slot]))
            worked = True
        self._admit()
        # Prefill-priority ramp: while admissions are prefilling into a
        # batch under half full, decode dispatches are HELD — each one at
        # low fill burns a full ~100 ms fetch round trip on a trickle of
        # tokens (the round-2 occupancy sink). The hold is budgeted per
        # episode (cfg.prefill_hold_chunks) so a monster prompt can stall
        # active streamers only boundedly; held slots' first tokens
        # already rode their fused final chunks, so TTFT is untouched.
        ramp = (bool(self._prefilling)
                and len(self._slots) < self.core.batch // 2)
        if ramp and not self._holding:
            self._holding = True
            self._hold_left = self.core.cfg.prefill_hold_chunks
        elif not ramp:
            self._holding = False
        # Mixed-phase dispatch: when jobs are prefilling while decode is
        # live (the r05 TTFT-tail shape — prompts admitted mid-decode),
        # each one's next chunk rides the decode dispatch as extra ragged
        # rows (engine.decode_mixed) instead of a separate program, so the
        # decode tick never stalls for them. Up to prefill_group jobs fuse
        # per dispatch (one chunk each); ramps (hold active) and refills
        # with any ineligible job keep the grouped prefill path — G-at-once
        # chunk-deep prefill beats one fused chunk per job there.
        try_mixed = (self._role != "prefill"
                     and bool(self._prefilling) and bool(self._slots)
                     and len(self._prefilling)
                     <= max(1, getattr(self.core.cfg, "prefill_group", 1))
                     and not (self._holding and self._hold_left > 0)
                     and getattr(self.core, "mixed_supported", False)
                     and all(self._mixed_eligible(j)
                             for j in self._prefilling))
        if self._prefilling and not try_mixed:
            # ONE grouped dispatch per tick: up to prefill_group jobs' chunks
            # ride a single program (same device-seconds as serial chunks,
            # 1/G the dispatch overhead, G-at-once slot activation). Each
            # tick's activations share one batched first-token fetch, so the
            # group size is also the TTFT resolution granularity of a ramp.
            consumed = self._prefill_step()
            if self._holding:
                self._hold_left -= consumed
            worked = True
        # batched first-token fetch: one (B,) transfer covers every job
        # activated since the last one. Submitted BEFORE the decode
        # dispatch, while state.tokens still holds those jobs' first
        # tokens (decode would advance them; such jobs resolve via the
        # decode sync instead — first_inflight gates the overlap).
        # …but ONLY while decode is held or the pipeline is shallow: the
        # fetch channel is serialized (~10/s), and when dispatches are
        # queued deep a first token resolves via the next decode sync
        # anyway — dedicated first fetches there just steal result-fetch
        # slots (measured as a lower dispatch rate at round 4)
        hold = self._holding and self._hold_left > 0 and bool(self._prefilling)
        waiting = [(j.slot, j, j.first_epoch) for j in self._slots.values()
                   if j.first_pending and not j.first_inflight
                   and not j.first_batched]
        if (waiting and (hold or len(self._inflight) <= 1)
                and len(self._first_fetches) < self._first_fetch_depth):
            # one (2, B) snapshot: token ids + logprob bits. The stack is
            # a fresh on-device buffer, so fetching it never races the
            # next dispatch's donation of the state it reads from.
            snap = jnp.stack([
                self._state.tokens,
                jax.lax.bitcast_convert_type(self._state.last_logprob,
                                             jnp.int32)])
            fut = self._fetcher.submit(_fetch, snap, "first_fetch_rtt_s")
            for _, j, _e in waiting:
                j.first_batched = True
            self._first_fetches.append((fut, waiting))
        if self._slots and not hold and self._role != "prefill":
            # a prefill-role worker NEVER dispatches decode: its "slots"
            # are finished prefills awaiting the batched first-token fetch
            # and their KV export (_export_handoff)
            self._dispatch_decode(try_mixed)
            worked = True
        # backpressure: bound dispatches in flight; drain fully once
        # nothing is left to dispatch
        while (len(self._inflight) > self._pipeline_depth
               or (self._inflight and not self._slots)):
            self._process_decode()
            worked = True
        return worked

    def _loop(self) -> None:
        logger.info("engine driver thread started (slots=%d pages=%d)",
                    self.core.batch, self.core.num_pages)
        while self._running:
            self.last_tick_mono = clock.mono()
            try:
                if not self._tick():
                    # idle: wait for work without burning the core
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            except Exception:
                # Fail loudly but keep the driver alive: release every blocked
                # consumer, reset device state, and continue serving — a dead
                # silent driver with /health green is the worst failure mode.
                logger.exception("engine driver step failed; resetting state")
                REGISTRY.counter("driver_errors").inc()
                self._fail_all("engine error")
                self._state = self.core.init_state()
        self._fail_all("scheduler stopped")
        logger.info("engine driver thread stopped")
