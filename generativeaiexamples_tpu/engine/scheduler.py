"""Continuous-batching scheduler: request queue → pages/slots → token streams.

The host-side orchestrator around `EngineCore` — the in-tree stand-in for
TRT-LLM's inflight batcher (ref: NIM container, docker-compose-nim-ms.yaml:2-28).
One driver thread owns the device; each tick it

  1. **admits** pending requests: allocates a slot and the prompt's KV pages
     (FIFO — a request that doesn't fit blocks later ones, no starvation);
  2. runs **one prefill chunk** of the oldest admission — chunked prefill
     interleaves with decode, so active slots never stall for a whole prompt
     and arbitrarily long prompts are processed without truncation;
  3. runs **one decode step** over all active slots, fanning sampled tokens
     out to per-request queues (thread-safe iterators of text deltas).

Page management: the scheduler mirrors the device block table on the host,
growing a slot's page list as decode crosses page boundaries. When the pool
is exhausted, the *youngest* active slot is preempted: its pages are freed
and the request re-queued as a resume (prompt + tokens generated so far), so
its stream continues seamlessly after re-prefill — recompute-style preemption,
the same policy the reference's paged batcher applies under memory pressure.

Requests whose prompts cannot fit the cache at all are failed loudly (the
chain server also caps prompt length at the API, ref server.py:61-66) —
never silently truncated.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterator, List, Optional, Sequence

import jax
import numpy as np

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.engine.engine import DecodeState, EngineCore
from generativeaiexamples_tpu.engine.tokenizer import IncrementalDetokenizer, Tokenizer

logger = logging.getLogger(__name__)

_STOP = object()


@dataclass
class Request:
    prompt_ids: List[int]
    max_tokens: int = 128
    temperature: float = 0.7
    top_k: int = 0
    top_p: float = 1.0
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    # filled by the scheduler:
    out_queue: "queue.Queue" = field(default_factory=queue.Queue)
    submitted_at: float = field(default_factory=time.perf_counter)
    first_token_at: Optional[float] = None
    completion_tokens: int = 0
    error: Optional[str] = None


@dataclass
class _Job:
    """A request's journey through the engine: prefilling, then decoding.

    ``ids`` is the sequence prefilled so far — the prompt, plus (after a
    preemption) the tokens already generated, so a resume re-prefills the
    full context and the stream continues where it left off.
    """

    request: Request
    detok: IncrementalDetokenizer
    ids: List[int]
    slot: int = -1
    pages: List[int] = field(default_factory=list)
    prefilled: int = 0            # tokens of `ids` already chunked in
    total_len: int = 0            # host mirror of cache lengths[slot]
    gen_ids: List[int] = field(default_factory=list)   # generated so far
    admit_seq: int = 0            # admission order (preemption picks max)
    prefill_elapsed: float = 0.0  # wall time across this prompt's chunks


class Scheduler:
    """Drives an EngineCore from a single background thread."""

    def __init__(self, core: EngineCore, tokenizer: Tokenizer) -> None:
        self.core = core
        self.tokenizer = tokenizer
        self._lock = threading.Lock()
        self._pending: Deque[_Job] = deque()     # awaiting slot+pages
        self._prefilling: Deque[_Job] = deque()  # admitted, chunking in
        self._slots: Dict[int, _Job] = {}        # decoding
        self._free: List[int] = list(range(core.batch))
        self._alloc = core.new_allocator()
        self._table = np.zeros((core.batch, core.max_pages_per_slot), np.int32)
        self._table_dev: Optional[jax.Array] = None
        self._admit_counter = 0
        self._state: DecodeState = core.init_state()
        self._rng = jax.random.PRNGKey(1234)
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, name="engine-driver",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                # Driver still mid-step (e.g. a long XLA compile): touching
                # job state concurrently would corrupt bookkeeping — leave
                # cleanup to the driver, which checks _running after the step.
                logger.warning("driver thread still busy at stop(); "
                               "skipping forced cleanup")
                return
        self._fail_all("scheduler stopped")

    def submit(self, request: Request) -> Request:
        """Enqueue; stream deltas via `iter_text(request)`."""
        job = _Job(request=request,
                   detok=IncrementalDetokenizer(self.tokenizer),
                   ids=list(request.prompt_ids))
        with self._lock:
            self._pending.append(job)
        self._wake.set()
        REGISTRY.counter("requests_submitted").inc()
        return request

    def iter_text(self, request: Request) -> Iterator[str]:
        """Blocking iterator over the request's text deltas."""
        while True:
            item = request.out_queue.get()
            if item is _STOP:
                return
            yield item

    def generate(self, prompt_ids: Sequence[int], **kw) -> str:
        """Synchronous convenience: submit and join the full text. Raises on
        per-request failure (e.g. over-capacity prompt) — never returns a
        silently empty string for a rejected request."""
        req = Request(prompt_ids=list(prompt_ids), **kw)
        self.submit(req)
        text = "".join(self.iter_text(req))
        if req.error:
            raise RuntimeError(f"request {req.request_id} failed: {req.error}")
        return text

    # ------------------------------------------------------------- internals

    def _fail_all(self, reason: str) -> None:
        """Unblock every queued and in-flight consumer (shutdown/crash path)."""
        with self._lock:
            jobs = list(self._pending)
            self._pending.clear()
        jobs += list(self._prefilling) + list(self._slots.values())
        self._prefilling.clear()
        self._slots.clear()
        for job in jobs:
            job.request.error = reason
            job.request.out_queue.put(_STOP)
            job.pages = []
            job.slot = -1
        # rebuild slot/page bookkeeping to a clean slate
        self._alloc = self.core.new_allocator()
        self._free = list(range(self.core.batch))
        self._table[:] = 0
        self._table_dev = None

    def _release(self, job: _Job) -> None:
        """Return the job's slot and pages to the pools."""
        if job.slot >= 0:
            self._free.append(job.slot)
            self._table[job.slot, :] = 0
            self._table_dev = None
            job.slot = -1
        if job.pages:
            self._alloc.free(job.pages)
            job.pages = []

    def _finish(self, job: _Job) -> None:
        tail = job.detok.flush()
        if tail:
            job.request.out_queue.put(tail)
        job.request.out_queue.put(_STOP)
        self._release(job)
        REGISTRY.counter("requests_completed").inc()
        REGISTRY.histogram("request_latency_s").observe(
            time.perf_counter() - job.request.submitted_at)

    def _fail(self, job: _Job, reason: str) -> None:
        job.request.error = reason
        job.request.out_queue.put(_STOP)
        REGISTRY.counter("requests_failed").inc()

    def _table_device(self) -> jax.Array:
        if self._table_dev is None:
            self._table_dev = self.core.put_table(self._table)
        return self._table_dev

    # -- admission ----------------------------------------------------------

    def _admit(self) -> None:
        """Move pending jobs into the prefilling set while slots+pages last."""
        while self._free:
            with self._lock:
                if not self._pending:
                    return
                job = self._pending[0]
            n = len(job.ids)
            need = self.core.pages_for(n)
            if n + 1 >= self.core.max_seq or need > self.core.num_pages - 1:
                with self._lock:
                    self._pending.popleft()
                if job.gen_ids:
                    # a preempted resume that has outgrown capacity: end it
                    # cleanly at its current length (mirrors the engine's
                    # out_of_cache cap), keeping the streamed output valid
                    logger.warning("resume of %s no longer fits (%d tokens); "
                                   "finishing at capacity",
                                   job.request.request_id, n)
                    self._finish(job)
                else:
                    # could never be served — fail loudly rather than hang
                    # the FIFO head forever (the API also caps prompts,
                    # ref server.py:61-66)
                    self._fail(job, f"prompt of {n} tokens needs {need} KV "
                                    f"pages and {n + 1} cache positions "
                                    f"(prompt + first token); capacity is "
                                    f"{self.core.num_pages - 1} pages / "
                                    f"{self.core.max_seq - 1} positions "
                                    f"(max prompt {self.core.max_seq - 2})")
                continue
            pages = self._alloc.alloc(need)
            if pages is None:
                return  # FIFO head-of-line: wait for pages to free up
            with self._lock:
                self._pending.popleft()
            slot = self._free.pop()
            job.slot = slot
            job.pages = pages
            job.prefilled = 0
            job.total_len = 0
            if job.admit_seq == 0:
                # resumes keep their original admission age, so preemption
                # (youngest-first) cannot thrash an old request forever
                self._admit_counter += 1
                job.admit_seq = self._admit_counter
            self._table[slot, :] = 0
            self._table[slot, :len(pages)] = pages
            self._table_dev = None
            self._prefilling.append(job)

    # -- prefill ------------------------------------------------------------

    def _prefill_step(self) -> None:
        """Run ONE chunk of the oldest admission (interleaves with decode)."""
        job = self._prefilling[0]
        req = job.request
        start = job.prefilled
        remaining = len(job.ids) - start
        chunk_ids = job.ids[start:start + min(remaining, self.core.chunk)]
        t0 = time.perf_counter()
        self._state, logits = self.core.prefill_chunk(
            self._state, chunk_ids, self._table[job.slot], job.slot, start)
        job.prefilled += len(chunk_ids)
        job.total_len = job.prefilled
        REGISTRY.counter("prefill_chunks").inc()
        if job.prefilled < len(job.ids):
            job.prefill_elapsed += time.perf_counter() - t0
            return  # mid-prompt; decode interleaves before the next chunk

        # final chunk: sample the first token (host sync = TTFT)
        self._prefilling.popleft()
        self._rng, sub = jax.random.split(self._rng)
        tok = self.core.sample(logits, sub, req.temperature, req.top_k,
                               req.top_p)
        resumed = bool(job.gen_ids)
        if not resumed:
            req.first_token_at = time.perf_counter()
            REGISTRY.histogram("ttft_s").observe(
                req.first_token_at - req.submitted_at)
        # whole-prompt prefill time: every chunk (accumulated across the
        # interleaved ticks) plus the first-token sample sync above
        job.prefill_elapsed += time.perf_counter() - t0
        REGISTRY.histogram("prefill_s").observe(job.prefill_elapsed)

        already = len(job.gen_ids)
        if tok == self.core.eos_id or already + 1 >= req.max_tokens:
            if tok != self.core.eos_id:
                self._emit_token(job, tok)
            self._finish(job)
            return
        self._emit_token(job, tok)
        self._state = self.core.activate(
            self._state, job.slot, tok, generated=already + 1,
            max_gen=req.max_tokens, temperature=req.temperature,
            top_k=req.top_k, top_p=req.top_p)
        self._slots[job.slot] = job

    def _emit_token(self, job: _Job, tok: int) -> None:
        job.gen_ids.append(tok)
        job.request.completion_tokens += 1
        job.total_len += 1
        delta = job.detok.push(tok)
        if delta:
            job.request.out_queue.put(delta)

    # -- decode -------------------------------------------------------------

    def _grow_pages(self) -> None:
        """Give every active slot a page for its next write; preempt the
        youngest admissions when the pool runs dry."""
        for slot in list(self._slots):
            job = self._slots.get(slot)
            if job is None:
                continue
            # total_len counts the just-sampled (not yet written) token, so
            # the next decode write lands at index total_len - 1; while the
            # slot is active that stays < max_seq and within the table row.
            while len(job.pages) < self.core.pages_for(job.total_len - 1):
                got = self._alloc.alloc(1)
                if got is not None:
                    self._table[slot, len(job.pages)] = got[0]
                    job.pages.extend(got)
                    self._table_dev = None
                    continue
                victim = self._pick_victim()
                self._preempt(victim)
                if victim is job:
                    break  # the grower was youngest: it waits in the queue

    def _pick_victim(self) -> _Job:
        """Youngest admission — decoding slots and mid-prefill jobs alike
        (both hold pages). The growing job is a candidate too: if IT is the
        youngest, it preempts itself rather than evicting an older request
        (no thrash — resumes keep their original admission age)."""
        cands = (list(self._prefilling) + list(self._slots.values()))
        return max(cands, key=lambda j: j.admit_seq)

    def _preempt(self, job: _Job) -> None:
        """Recompute-preemption: free the slot, requeue prompt+generated."""
        if job.slot in self._slots and self._slots[job.slot] is job:
            del self._slots[job.slot]
        else:
            self._prefilling.remove(job)
        self._state = self.core.release(self._state, job.slot)
        self._release(job)
        job.ids = list(job.request.prompt_ids) + list(job.gen_ids)
        job.prefilled = 0
        job.total_len = 0
        job.prefill_elapsed = 0.0   # the resume's re-prefill is a fresh sample
        with self._lock:
            self._pending.appendleft(job)
        REGISTRY.counter("preemptions").inc()
        logger.info("preempted request %s at %d generated tokens",
                    job.request.request_id, len(job.gen_ids))

    def _decode_once(self) -> None:
        self._grow_pages()
        if not self._slots:
            return
        self._state, out = self.core.decode(self._state, self._table_device())
        sampled = np.asarray(jax.device_get(out["sampled"]))
        emitted = np.asarray(jax.device_get(out["emitted"]))
        done = np.asarray(jax.device_get(out["done"]))
        hit_eos = np.asarray(jax.device_get(out["hit_eos"]))
        REGISTRY.counter("decode_steps").inc()
        REGISTRY.counter("tokens_generated").inc(int(emitted.sum()))
        for slot, job in list(self._slots.items()):
            if not emitted[slot]:
                continue
            if not (done[slot] and hit_eos[slot]):
                self._emit_token(job, int(sampled[slot]))
            if done[slot]:
                del self._slots[slot]
                self._finish(job)

    # -- driver loop --------------------------------------------------------

    def _tick(self) -> bool:
        """One scheduling round; returns False when fully idle."""
        self._admit()
        worked = False
        if self._prefilling:
            self._prefill_step()
            worked = True
        if self._slots:
            self._decode_once()
            worked = True
        return worked

    def _loop(self) -> None:
        logger.info("engine driver thread started (slots=%d pages=%d)",
                    self.core.batch, self.core.num_pages)
        while self._running:
            try:
                if not self._tick():
                    # idle: wait for work without burning the core
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            except Exception:
                # Fail loudly but keep the driver alive: release every blocked
                # consumer, reset device state, and continue serving — a dead
                # silent driver with /health green is the worst failure mode.
                logger.exception("engine driver step failed; resetting state")
                REGISTRY.counter("driver_errors").inc()
                self._fail_all("engine error")
                self._state = self.core.init_state()
        self._fail_all("scheduler stopped")
        logger.info("engine driver thread stopped")
