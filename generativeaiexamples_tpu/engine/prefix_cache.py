"""Host-side prefix caching over the paged KV pool.

The TPU-native counterpart of the shared-prompt KV reuse TRT-LLM performs
inside the reference's NIM container (ref: the NIM serving layer,
RAG/examples/local_deploy/docker-compose-nim-ms.yaml:2-28 — "paged
attention" with prefix reuse): every RAG request re-sends the same chat
template + system prompt, and repeated queries re-send the same retrieved
chunks, so re-prefilling from token 0 wastes exactly the tokens the cache
can skip.

Design (page-granular, immutable, no copy-on-write):

  * **Unit of sharing = one full physical page.** A page holds KV for a
    page-aligned token span; its content is a pure function of the token
    prefix up to its end (and the serving params/adapters), so a
    **chain hash** ``h_i = H(h_{i-1}, tokens[i*ps:(i+1)*ps])`` identifies
    it exactly. Only *fully covered* pages are ever shared: the page being
    appended to by decode is always request-private, so shared pages are
    immutable by construction and divergence needs no copy-on-write — a
    diverging request simply stops matching the chain one page earlier.
  * **Refcounts, not ownership.** Every allocated page carries a refcount
    of live owners (one per request whose block-table row references it).
    ``free`` decrements; a cached page at refcount 0 parks in an LRU of
    *evictable* pages — still valid, resurrected by the next matching
    admission — and is only reclaimed when the free list runs dry. A
    never-inserted page at refcount 0 returns to the free list directly.
  * **Write-before-share is dispatch-order.** The scheduler inserts a
    page into the cache only after the dispatch that writes it has been
    *issued*; the engine serializes dispatches on one device stream, so
    any later admission's read executes after the write. (Insertion
    happens at final-chunk dispatch for prompt pages and at
    finish/preempt for generated-token pages — by then the writes have
    not only been issued but fetched.)
  * **Correct across resumes and turns.** KV for position t depends only
    on tokens 0..t, so pages covering *generated* tokens hash and share
    exactly like prompt pages — a preemption resume re-admits against its
    own prior pages, and a multi-turn conversation's next request (whose
    templated prompt embeds the previous turns verbatim) hits the pages
    decode wrote.
  * ``seed`` namespaces the chain (serving-params epoch / per-request
    adapter id): KV depends on the weights that produced it, so requests
    served under different adapters must never share pages.

The scheduler caps how much of a match it uses (it must recompute at least
the final token for logits, and keeps its chunk-bucket geometry inside the
block-table row); the cache itself only answers "which pages hold this
chain".
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional, Sequence

from generativeaiexamples_tpu.core.metrics import REGISTRY


def chain_hashes(ids: Sequence[int], page_size: int, seed: int = 0
                 ) -> List[bytes]:
    """Chain hash per fully-covered page of ``ids``: h_i commits to every
    token in pages 0..i, so equal h_i ⇔ equal token prefix. blake2b-128,
    not Python's builtin hash: a collision would serve another request's
    KV as this prompt's prefix, so the identity must hold against
    adversarial prompts, not just accidental ones."""
    out: List[bytes] = []
    h = hashlib.blake2b(str(seed).encode(), digest_size=16).digest()
    for i in range(len(ids) // page_size):
        page = ids[i * page_size:(i + 1) * page_size]
        buf = b"".join(int(t).to_bytes(4, "little", signed=True)
                       for t in page)
        h = hashlib.blake2b(h + buf, digest_size=16).digest()
        out.append(h)
    return out


class CachingAllocator:
    """Drop-in for :class:`kv_cache.PageAllocator` with prefix reuse.

    API compatibility: ``alloc``/``free``/``available`` keep the free-list
    semantics the scheduler already speaks (``free`` means "this owner is
    done", not "scrub the page"). New surface: ``match`` + ``acquire`` for
    admission-time reuse, ``insert`` to publish written pages.
    """

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: deque = deque(range(1, num_pages))
        self._refs: Dict[int, int] = {}            # page -> live owners (>0)
        self._hash_to_page: Dict[bytes, int] = {}
        self._page_to_hash: Dict[int, bytes] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # ref==0, cached

    # ------------------------------------------------------------ invariants

    @property
    def available(self) -> int:
        """Pages an ``alloc`` could hand out right now (free + evictable)."""
        return len(self._free) + len(self._lru)

    @property
    def cached_pages(self) -> int:
        return len(self._hash_to_page)

    def live_refs(self) -> int:
        return sum(self._refs.values())

    def can_serve(self, n: int, acquired: Sequence[int] = ()) -> bool:
        """Could ``acquire(acquired)`` then ``alloc(n)`` succeed right now?
        Acquiring an evictable page removes it from the LRU, so it stops
        counting toward alloc headroom."""
        in_lru = sum(1 for p in acquired if p in self._lru)
        return len(self._free) + len(self._lru) - in_lru >= n

    # ------------------------------------------------------------- alloc/free

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n fresh pages (refcount 1 each), evicting LRU-cached pages
        when the free list runs dry; None (and no change) if impossible."""
        if n > len(self._free) + len(self._lru):
            return None
        out: List[int] = []
        for _ in range(n):
            if self._free:
                p = self._free.popleft()
            else:
                p, _ = self._lru.popitem(last=False)   # oldest evictable
                h = self._page_to_hash.pop(p)
                del self._hash_to_page[h]
                REGISTRY.counter("prefix_evictions").inc()
            self._refs[p] = 1
            out.append(p)
        return out

    def acquire(self, pages: Iterable[int]) -> None:
        """Add an owner to each page (admission sharing a matched chain).
        Atomic: validates every page before mutating, so a raise leaves no
        half-taken refs for the caller's rescan path to leak."""
        pages = list(pages)
        for p in pages:
            if self._refs.get(p, 0) == 0 and p not in self._lru:
                raise ValueError(f"acquire of unallocated page {p}")
        for p in pages:
            r = self._refs.get(p, 0)
            if r == 0:
                del self._lru[p]
            self._refs[p] = r + 1

    def free(self, pages: Iterable[int]) -> None:
        """Drop one owner per page; orphaned cached pages become evictable,
        orphaned uncached pages return to the free list."""
        for p in pages:
            r = self._refs.get(p)
            if r is None:
                raise ValueError(f"freeing unowned page {p}")
            if r > 1:
                self._refs[p] = r - 1
                continue
            del self._refs[p]
            if p in self._page_to_hash:
                self._lru[p] = None
                self._lru.move_to_end(p)
            else:
                self._free.append(p)

    # ----------------------------------------------------------------- cache

    def match(self, hashes: Sequence[int]) -> List[int]:
        """Longest cached prefix of the chain → its pages (no ref taken;
        call ``acquire`` on the slice actually used). A page matched here
        can only disappear through ``alloc`` eviction, so acquire in the
        same scheduler tick."""
        pages: List[int] = []
        for h in hashes:
            p = self._hash_to_page.get(h)
            if p is None:
                break
            pages.append(p)
        return pages

    def insert(self, hashes: Sequence[int], pages: Sequence[int]) -> None:
        """Publish written pages under their chain hashes. Idempotent; a
        hash already cached keeps its first page (the duplicate page stays
        request-private and frees normally). Call only after the writing
        dispatch has been issued."""
        for h, p in zip(hashes, pages):
            if h in self._hash_to_page:
                continue
            if p in self._page_to_hash:     # page re-used under a new chain
                old = self._page_to_hash.pop(p)
                self._hash_to_page.pop(old, None)
            if self._refs.get(p, 0) == 0 and p not in self._lru:
                raise ValueError(f"insert of unallocated page {p}")
            self._hash_to_page[h] = p
            self._page_to_hash[p] = h
            REGISTRY.counter("prefix_inserted_pages").inc()
