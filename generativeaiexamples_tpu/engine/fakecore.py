"""Deterministic fake paged core — the EngineCore stand-in the scheduler
fuzz harness (tests/test_scheduler_fuzz.py) and the trace-replay simulator
(ops/simulate.py) both drive.

Promoted out of the fuzz file verbatim (ROADMAP item 4 named it "80% of
the fleet simulator already"): a pure-numpy core with REAL paged
semantics — prefill writes token values into physical pages through the
block-table row, decode reads each slot's full context back THROUGH the
page table and emits ``f(context)``. Any scheduler bookkeeping bug (a page
freed early and reused, a stale table row, a length desync, a cross-slot
leak) corrupts a context sum and the emitted stream diverges from the
solo :func:`oracle`.

What the promotion adds — and the fuzz deliberately ignores — is
**perfmodel-costed virtual time**: every ``prefill_group``/``decode``
dispatch accrues estimated device seconds (core/perfmodel.py when a
:class:`~generativeaiexamples_tpu.core.perfmodel.PerfModel` is supplied;
calibrated fallback constants otherwise) into :attr:`consumed_s`. The
simulator drains that accumulator each tick to advance its virtual clock,
so a 1000-replica what-if sweep runs in wall-seconds while the simulated
timeline carries realistic device costs. Default construction changes
NOTHING for the fuzz: costs accrue into an attribute nobody reads.
"""

from __future__ import annotations

from dataclasses import dataclass
from types import SimpleNamespace
from typing import List, Optional

import numpy as np

from generativeaiexamples_tpu.engine.kv_cache import PageAllocator

EOS = 3
VOCAB = 260

# virtual-time fallbacks when no PerfModel is supplied (no chip peaks on
# CPU): roughly a small model on one v5e-class chip — 20 µs/prefill token,
# 4 ms per fused decode step (one full weight read). The absolute values
# only scale the simulated timeline; determinism never depends on them.
FALLBACK_PREFILL_S_PER_TOKEN = 2e-5
FALLBACK_DECODE_S_PER_STEP = 4e-3


def _next_token(context: List[int]) -> int:
    """Deterministic 'model': next token from the FULL context. EOS appears
    on a deterministic schedule so budget-exhaustion and eos paths both get
    exercised."""
    s = (sum(context) * 31 + len(context) * 7) & 0xFFFF
    if s % 13 == 0:
        return EOS
    return 32 + s % (VOCAB - 64)


def oracle(prompt: List[int], max_tokens: int, max_seq: int) -> List[int]:
    """Solo-run reference: what a correct engine must stream for a prompt.
    Generation ends at eos, the token budget, or cache capacity (the engine
    retires a slot when its context reaches max_seq - 1; the capacity-step
    token itself is still emitted, the eos token never is)."""
    ctx = list(prompt)
    out: List[int] = []
    cap = max(0, max_seq - len(prompt))          # 1 fused + (max_seq-1-n) decode
    while len(out) < min(max_tokens, cap):
        t = _next_token(ctx)
        if t == EOS:
            break
        out.append(t)
        ctx.append(t)
    return out


@dataclass
class _FakeState:
    pool: np.ndarray              # (num_pages, page_size) written token values
    lengths: np.ndarray           # (B,)
    tokens: np.ndarray            # (B,) last sampled token
    active: np.ndarray            # (B,) bool
    generated: np.ndarray         # (B,)
    max_gen: np.ndarray           # (B,)
    last_logprob: np.ndarray = None  # (B,) f32 (scheduler snapshot shape)


class FakeCore:
    """Pure-numpy stand-in for EngineCore with REAL paged-read semantics."""

    def __init__(self, batch=4, max_seq=64, page_size=8, num_pages=0,
                 chunk=16, steps=4, steps_max=0, group=4, prefix_cache=False,
                 width_ladder=False, perf_model=None, multistep=0):
        self.batch, self.max_seq = batch, max_seq
        self.page_size, self.chunk = page_size, chunk
        self.max_pages_per_slot = -(-max_seq // page_size)
        self.num_pages = num_pages or batch * self.max_pages_per_slot + 1
        self.eos_id = EOS
        self.donates_state = False
        self.supports_long_prefill = False
        self.prefix_cache = prefix_cache
        if width_ladder and batch > 2:
            # decode batch-width ladder (engine.decode_widths): the
            # scheduler dispatches at the narrowest rung covering the
            # highest live slot, and rung transitions happen mid-stream as
            # slots fill and drain — the fuzz menu exercises exactly that
            self.decode_widths = (2, batch)
        # multi-step decode ladder (engine.multi_ms): eligible steady-state
        # dispatches run K·M steps with one deferred fetch — the fuzz menu
        # exercises mid-block finish/preempt/evacuate against the oracle
        if multistep >= 2:
            rungs, r = [], 2
            while r <= multistep:
                rungs.append(r)
                r *= 2
            self.multi_ms = tuple(rungs)
        else:
            self.multi_ms = ()
        self.cfg = SimpleNamespace(
            decode_steps_per_dispatch=steps, decode_steps_max=steps_max,
            prefill_group=group, long_prefill="off", prefill_hold_chunks=8,
            pipeline_depth=2)
        self.group_buckets = (1, 2, 4)
        # final-chunk bucket ladder (the prefix-cache coverage cap reads it)
        buckets, b = [], page_size
        while b < chunk:
            buckets.append(b)
            b *= 2
        buckets.append(chunk)
        self.buckets = tuple(buckets)
        # perfmodel-costed virtual time (ops/simulate.py): estimated
        # device seconds accrued by dispatches since the last take
        self.perf_model = perf_model
        self.consumed_s = 0.0
        self.dispatches = 0

    # -- virtual-time accounting (additive; the fuzz never reads it) ------

    def take_consumed(self) -> float:
        """Drain the accrued virtual device seconds (the simulator's tick
        loop advances its clock by this)."""
        s, self.consumed_s = self.consumed_s, 0.0
        return s

    def _charge_prefill(self, tokens: int) -> None:
        cost = None
        if self.perf_model is not None:
            cost = self.perf_model.prefill_seconds(tokens)
        if cost is None:
            cost = tokens * FALLBACK_PREFILL_S_PER_TOKEN
        self.consumed_s += cost
        self.dispatches += 1

    def _charge_decode(self, steps: int) -> None:
        cost = None
        pm = self.perf_model
        if pm is not None and pm.peak_bw:
            # decode is weight-read-bound: one full weight read per step
            cost = pm.weight_read_bytes(steps) / pm.peak_bw
        if cost is None:
            cost = steps * FALLBACK_DECODE_S_PER_STEP
        self.consumed_s += cost
        self.dispatches += 1

    def init_state(self) -> _FakeState:
        B = self.batch
        return _FakeState(
            pool=np.zeros((self.num_pages, self.page_size), np.int32),
            lengths=np.zeros((B,), np.int32), tokens=np.zeros((B,), np.int32),
            active=np.zeros((B,), bool), generated=np.zeros((B,), np.int32),
            max_gen=np.zeros((B,), np.int32),
            last_logprob=np.zeros((B,), np.float32))

    def new_allocator(self):
        """Caching episodes run the REAL CachingAllocator against the fake
        paged pool: a page shared wrongly (content not actually the matched
        prefix) or evicted while referenced corrupts a stream's context sum
        and diverges from the solo oracle."""
        if self.prefix_cache:
            from generativeaiexamples_tpu.engine.prefix_cache import (
                CachingAllocator)
            return CachingAllocator(self.num_pages, self.page_size)
        return PageAllocator(self.num_pages)

    def pages_for(self, n: int) -> int:
        return n // self.page_size + 1

    def put_table(self, table: np.ndarray) -> np.ndarray:
        return np.array(table, np.int32)      # snapshot, like a device copy

    def _read_context(self, st: _FakeState, row: np.ndarray, n: int) -> List[int]:
        ps = self.page_size
        out = []
        for i in range(n):
            out.append(int(st.pool[row[i // ps], i % ps]))
        return out

    @staticmethod
    def _clone(st: _FakeState) -> _FakeState:
        """Functional update, like real jax dispatches: handles the
        scheduler kept into an OLD state (the batched first-token fetch of
        state.tokens) must stay stable snapshots."""
        return _FakeState(*(a.copy() for a in (
            st.pool, st.lengths, st.tokens, st.active, st.generated,
            st.max_gen, st.last_logprob)))

    def release(self, st: _FakeState, slot: int) -> _FakeState:
        st = self._clone(st)
        st.active[slot] = False
        return st

    # -- live-migration surface (export_live_slot / spill / resume) -------
    # Mirrors EngineCore's handoff trio with REAL paged semantics: export
    # reads the slot's written token values back THROUGH its page list,
    # import scatters them into different physical pages. Any length or
    # page-math slip in the scheduler's snapshot/spill paths corrupts the
    # resumed context sum and the stream diverges from the solo oracle.

    def export_slot_kv(self, st: _FakeState, pages, length,
                       fetch: bool = False) -> dict:
        n = max(1, -(-int(length) // self.page_size))
        rows = np.zeros((n, self.page_size), np.int32)
        for i, p in enumerate(list(pages)[:n]):
            rows[i] = st.pool[p]
        return {"length": int(length), "n_pages": n,
                "page_size": self.page_size, "k": rows}

    def validate_handoff(self, payload: dict) -> None:
        if payload.get("page_size") != self.page_size:
            raise ValueError("page_size mismatch")
        n = int(payload.get("length", 0))
        if n < 1 or n + 1 >= self.max_seq:
            raise ValueError("length outside serving range")
        if "prompt_ids" in payload and len(payload["prompt_ids"]) != n:
            raise ValueError("prompt_ids/length mismatch")

    def import_slot_kv(self, st: _FakeState, slot: int, pages,
                       payload: dict) -> _FakeState:
        self.validate_handoff(payload)
        st = self._clone(st)
        n = int(payload["n_pages"])
        for i, p in enumerate(list(pages)[:n]):
            st.pool[p] = payload["k"][i]
        st.lengths[slot] = int(payload["length"])
        return st

    def import_pages_kv(self, st: _FakeState, pages, payload: dict,
                        n_pages: Optional[int] = None) -> _FakeState:
        """Partial page import — the prefix-tier promote surface
        (engine/kv_tier.py): scatter the payload's first ``n_pages`` page
        rows into freshly allocated physical pages, touching NO slot
        state. The promoted job's chunk walk starts at the covered
        boundary; any coverage/page-math slip here corrupts the read-back
        context sum and the stream diverges from the solo oracle."""
        if payload.get("page_size") != self.page_size:
            raise ValueError("page_size mismatch")
        n = int(n_pages if n_pages is not None else payload["n_pages"])
        if n < 1 or n > int(payload["n_pages"]):
            raise ValueError("n_pages outside payload coverage")
        st = self._clone(st)
        for i, p in enumerate(list(pages)[:n]):
            st.pool[p] = payload["k"][i]
        return st

    def activate(self, st: _FakeState, slot: int, token: int,
                 generated: int, max_gen: int, temperature: float,
                 top_k: int, top_p: float, seed: int = 0,
                 gram_state: int = 0) -> _FakeState:
        st = self._clone(st)
        st.tokens[slot] = int(token)
        st.active[slot] = True
        st.generated[slot] = int(generated)
        st.max_gen[slot] = int(max_gen)
        return st

    def prefill_group(self, st: _FakeState, items) -> tuple:
        self._charge_prefill(sum(len(it.chunk_ids) for it in items))
        st = self._clone(st)
        toks = np.zeros((len(items),), np.int32)
        for i, it in enumerate(items):
            ps = self.page_size
            row = np.asarray(it.page_row)
            for j, t in enumerate(it.chunk_ids):
                pos = it.start_pos + j
                st.pool[row[pos // ps], pos % ps] = t
            n = it.start_pos + len(it.chunk_ids)
            st.lengths[it.slot] = n
            if it.is_last:
                ctx = self._read_context(st, row, n)
                tok = _next_token(ctx)
                toks[i] = tok
                alive = (tok != EOS) and (it.generated < it.max_gen)
                st.tokens[it.slot] = tok
                st.active[it.slot] = alive
                st.generated[it.slot] = it.generated
                st.max_gen[it.slot] = it.max_gen
        return st, toks

    def decode(self, st: _FakeState, table: np.ndarray, steps: int = 1,
               use_grammar: bool = False, want_top: bool = False,
               width: int = 0) -> tuple:
        self._charge_decode(steps)
        st = self._clone(st)
        B, ps = (width or self.batch), self.page_size
        # a narrow batch-width rung must cover every live slot — the
        # scheduler's lowest-id-first allocation guarantees it; a slot at
        # or past the rung would silently stall here, which the episode
        # invariants catch as a livelock/diverged stream
        # 7 rows: the scheduler's unpack expects the logprob rows too
        # (they carry 0.0 bits here — the fake model has no distribution)
        out = np.zeros((7, steps, B), np.int32)
        for k in range(steps):
            for b in range(B):
                out[4, k, b] = st.tokens[b]              # input_tokens
                if not st.active[b]:
                    continue
                out[1, k, b] = 1                          # emitted
                n = int(st.lengths[b])
                # write the input token at position n (through the table,
                # like the real engine), then read the WHOLE context back
                st.pool[table[b, n // ps], n % ps] = st.tokens[b]
                st.lengths[b] = n + 1
                ctx = self._read_context(st, table[b], n + 1)
                tok = _next_token(ctx)
                out[0, k, b] = tok                        # sampled
                st.generated[b] += 1
                hit_eos = tok == EOS
                done = (hit_eos or st.generated[b] >= st.max_gen[b]
                        or st.lengths[b] >= self.max_seq - 1)
                out[2, k, b] = int(done)
                out[3, k, b] = int(hit_eos)
                if done:
                    st.active[b] = False
                else:
                    st.tokens[b] = tok
        return st, {"packed": out, "emitted": out[1]}

    def decode_multi(self, st: _FakeState, table: np.ndarray,
                     steps: int = 1, m: int = 2, *, stops=(),
                     has_stop=None) -> tuple:
        """Multi-step decode: K·M plain steps as one dispatch / one packed
        block. The fake skips the on-device stop *maybe-match* pause (no
        vocab byte table) — that flag only bounds overshoot on the real
        engine; the scheduler's host replay is the stop truth either way,
        so the emitted stream is token-identical to M per-step dispatches
        by construction (exactly what the fuzz oracle asserts)."""
        if m not in self.multi_ms:
            raise ValueError(f"multistep m {m} is not a ladder rung "
                             f"{self.multi_ms}")
        return self.decode(st, table, steps * m)
