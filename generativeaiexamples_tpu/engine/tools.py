"""OpenAI tool calling + JSON mode for the serving layer.

Capability parity with the NIM tool-calling surface the reference's agent
notebooks consume (`tools` / `tool_choice` / `tool_calls` /
`response_format`; ref: RAG/notebooks/langchain/
Agent_use_tools_leveraging_NVIDIA_AI_endpoints.ipynb and
NIM_tool_call_HumanInTheLoop_MultiAgents.ipynb bind tools through the
OpenAI schema and read `message.tool_calls` back).

Mechanism: tools are rendered into the system prompt as JSON schemas with
a strict output contract (the llama-3 style of tool use — the template
teaches the model to answer with a single JSON object when it wants a
tool), and the generated text is parsed back into structured
`tool_calls`. Parsing is deliberately forgiving about the shapes tuned
models actually emit ({"name","arguments"} | {"name","parameters"} |
{"tool_calls":[...]} | a bare list), but strict about unknown tool names
— a hallucinated tool comes back as plain content, never as a bogus call.

JSON mode (`response_format={"type":"json_object"}`) rides the same
prompt+extract path. Since round 4 the prompt contract is additionally
ENFORCED at the token level when the output shape is unambiguous
(json_schema / forced tools): engine/grammar.py compiles the schema to a
byte-level DFA whose logit mask runs INSIDE the fused decode step — no
per-token host round trip, so the multi-step dispatch fusion
(scheduler.py's throughput design point) survives. The prompt+parse
machinery here remains the portable fallback (unsupported schemas,
tool_choice "auto" where prose is legal) and the wire-shape parser either
way.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

TOOL_PROMPT = """\
You have access to the following tools. To call a tool, respond with ONLY \
a JSON object of the form {{"tool_calls": [{{"name": "<tool name>", \
"arguments": {{...}}}}]}} and nothing else. Call a tool only when it helps \
answer the request; otherwise reply normally in plain text.

Tools:
{tools}"""

TOOL_REQUIRED = ("\nYou MUST call one of the tools — a plain-text reply is "
                 "not acceptable for this request.")
TOOL_NAMED = ("\nYou MUST call the tool named {name!r} — no other tool and "
              "no plain-text reply.")

JSON_PROMPT = ("Respond with ONLY a single valid JSON object — no prose, "
               "no code fences.")
JSON_SCHEMA_PROMPT = ("Respond with ONLY a single valid JSON object matching "
                      "this JSON schema — no prose, no code fences:\n{schema}")
JSON_WITH_TOOLS_PREFIX = ("When you are NOT calling a tool, your reply must "
                          "follow this rule: ")


def _tool_lines(tools: Sequence[Dict[str, Any]]) -> str:
    lines = []
    for t in tools:
        fn = t.get("function", t)
        lines.append(json.dumps({
            "name": fn.get("name", ""),
            "description": fn.get("description", ""),
            "parameters": fn.get("parameters", {}),
        }))
    return "\n".join(lines)


def tool_names(tools: Sequence[Dict[str, Any]]) -> List[str]:
    return [t.get("function", t).get("name", "") for t in tools]


def forced_name(tool_choice) -> Optional[str]:
    """The tool name a {"type":"function","function":{"name":...}} choice
    pins, else None."""
    if isinstance(tool_choice, dict):
        return tool_choice.get("function", {}).get("name")
    return None


def inject_tool_prompt(messages: Sequence[Dict[str, Any]],
                       tools: Sequence[Dict[str, Any]],
                       tool_choice) -> List[Dict[str, Any]]:
    """Prepend/extend the system message with the tool contract."""
    text = TOOL_PROMPT.format(tools=_tool_lines(tools))
    if tool_choice == "required":
        text += TOOL_REQUIRED
    name = forced_name(tool_choice)
    if name:
        text += TOOL_NAMED.format(name=name)
    return _with_system_suffix(messages, text)


def inject_json_prompt(messages: Sequence[Dict[str, Any]],
                       response_format: Dict[str, Any],
                       with_tools: bool = False) -> List[Dict[str, Any]]:
    """``with_tools`` scopes the constraint to non-tool-call replies so the
    two output contracts (tool_calls JSON vs. content JSON) don't clash."""
    if response_format.get("type") == "json_schema":
        schema = response_format.get("json_schema", {}).get("schema", {})
        text = JSON_SCHEMA_PROMPT.format(schema=json.dumps(schema))
    else:
        text = JSON_PROMPT
    if with_tools:
        text = JSON_WITH_TOOLS_PREFIX + text
    return _with_system_suffix(messages, text)


def _with_system_suffix(messages: Sequence[Dict[str, Any]],
                        suffix: str) -> List[Dict[str, Any]]:
    out = [dict(m) for m in messages]
    for m in out:
        if m.get("role") == "system":
            m["content"] = f"{m.get('content', '')}\n\n{suffix}"
            return out
    return [{"role": "system", "content": suffix}] + out


def normalize_messages(messages: Sequence[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Render OpenAI tool-protocol messages into template-friendly text:
    an assistant turn carrying `tool_calls` becomes its JSON contract form
    (so the model sees its own past calls the way it was taught to emit
    them), and `role:"tool"` results keep their role with the tool name
    prefixed."""
    out: List[Dict[str, Any]] = []
    for m in messages:
        role = m.get("role", "user")
        if role == "assistant" and m.get("tool_calls"):
            calls = [{"name": c.get("function", {}).get("name", ""),
                      "arguments": _parse_args(
                          c.get("function", {}).get("arguments"))}
                     for c in m["tool_calls"]]
            out.append({"role": "assistant",
                        "content": json.dumps({"tool_calls": calls})})
        elif role == "tool":
            name = m.get("name", "")
            prefix = f"[{name}] " if name else ""
            out.append({"role": "tool",
                        "content": f"{prefix}{m.get('content', '')}"})
        else:
            out.append({"role": role, "content": m.get("content", "") or ""})
    return out


def _parse_args(arguments) -> Dict[str, Any]:
    if isinstance(arguments, dict):
        return arguments
    if isinstance(arguments, str):
        try:
            parsed = json.loads(arguments)
            return parsed if isinstance(parsed, dict) else {"value": parsed}
        except ValueError:
            return {"raw": arguments}
    return {}


# ---------------------------------------------------------------- parsing

def extract_json_value(text: str) -> Optional[Tuple[Any, Tuple[int, int]]]:
    """First balanced JSON object/array in ``text`` → (value, (start, end)).

    A bracket scanner (string/escape aware) finds candidate spans; only
    spans that json-parse count, so ``{"a": 1} trailing prose`` and fenced
    ```json blocks both work without regex fragility."""
    for start, opener in ((i, c) for i, c in enumerate(text) if c in "{["):
        closer = "}" if opener == "{" else "]"
        depth = 0
        in_str = False
        escape = False
        for j in range(start, len(text)):
            c = text[j]
            if escape:
                escape = False
            elif c == "\\":
                escape = in_str
            elif c == '"':
                in_str = not in_str
            elif not in_str:
                if c in "{[":
                    depth += 1
                elif c in "]}":
                    depth -= 1
                    if depth == 0:
                        if c != closer:
                            break  # mismatched nesting; try the next start
                        try:
                            return (json.loads(text[start:j + 1]),
                                    (start, j + 1))
                        except ValueError:
                            break
        # unbalanced from this start; try the next opener
    return None


def _value_end(text: str, i: int) -> int:
    """Index one past the JSON value starting at ``i`` (after any leading
    whitespace), or -1 while it is still incomplete. String/escape aware."""
    while i < len(text) and text[i].isspace():
        i += 1
    if i >= len(text):
        return -1
    c = text[i]
    if c in "{[":
        depth = 0
        in_str = escape = False
        for j in range(i, len(text)):
            ch = text[j]
            if escape:
                escape = False
            elif ch == "\\":
                escape = in_str
            elif ch == '"':
                in_str = not in_str
            elif not in_str:
                if ch in "{[":
                    depth += 1
                elif ch in "]}":
                    depth -= 1
                    if depth == 0:
                        return j + 1
        return -1
    if c == '"':
        escape = False
        for j in range(i + 1, len(text)):
            if escape:
                escape = False
            elif text[j] == "\\":
                escape = True
            elif text[j] == '"':
                return j + 1
        return -1
    m = re.match(r"[^\s,\]}]+", text[i:])
    if m is None:
        return i        # no value at all (e.g. "arguments":}) — empty span
    end = i + m.end()
    return end if end < len(text) else -1   # a primitive may still grow


_WRAP_RE = re.compile(r'\{\s*"tool_calls"\s*:\s*\[\s*')
_ELEM_RE = re.compile(
    r'\{\s*"name"\s*:\s*"([^"\\]+)"\s*,\s*"(?:arguments|parameters)"\s*:')
_LIST_RE = re.compile(r"\[\s*")


class ToolCallStreamer:
    """Incremental tool-call detection for SSE streaming.

    The buffered path (server._run) holds the WHOLE generation before
    answering a `stream=true` tools request; OpenAI semantics instead
    stream `tool_calls` deltas — name first, then the argument text in
    fragments — so long argument generations are visible as they decode
    (round-3 weakness: seconds of silence). This feeds on text deltas and
    COMMITS to a call as soon as the envelope prefix is unambiguous
    ({"tool_calls": [{"name": <known tool>, "arguments": …); from there
    the raw argument value streams out in fragments (clients concatenate
    and json-parse, the OpenAI wire contract). Unknown tool names or
    non-envelope JSON are released as plain content once balanced —
    matching parse_tool_calls' strictness.

    Events from feed()/finish():
      ("content", text) | ("tool_start", index, name) |
      ("tool_args", index, fragment)
    """

    def __init__(self, tools: Sequence[Dict[str, Any]]) -> None:
        self._known = set(tool_names(tools))
        self._buf = ""
        self._pos = 0            # next unconsumed char
        self._state = "scan"     # scan | held | args | between | done
        self._mode = ""          # wrap | list | bare (valid once committed)
        self._open = 0           # index of the held candidate's opener
        self._args_start = 0
        self._emit_to = 0        # args chars already emitted
        self.calls = 0           # committed tool calls (index = calls - 1)

    @property
    def committed(self) -> bool:
        return self.calls > 0

    def feed(self, delta: str) -> List[tuple]:
        self._buf += delta
        events: List[tuple] = []
        progressed = True
        while progressed:
            progressed = False
            before = (self._state, self._pos, self._emit_to, self.calls)
            handler = getattr(self, "_st_" + self._state)
            handler(events)
            progressed = before != (self._state, self._pos, self._emit_to,
                                    self.calls)
        return events

    def finish(self) -> List[tuple]:
        events: List[tuple] = []
        if self._state in ("scan", "held"):
            tail = self._buf[self._pos:]
            if tail:
                events.append(("content", tail))
        elif self._state == "args" and len(self._buf) > self._emit_to:
            # truncated generation mid-arguments: flush best-effort
            events.append(("tool_args", self.calls - 1,
                           self._buf[self._emit_to:]))
        self._pos = len(self._buf)
        return events

    # -- states ------------------------------------------------------------

    def _st_scan(self, events: List[tuple]) -> None:
        nxt = min((i for i in (self._buf.find("{", self._pos),
                               self._buf.find("[", self._pos)) if i >= 0),
                  default=-1)
        if nxt < 0:
            # hold back a trailing partial char? deltas are whole chars;
            # emit everything before any opener
            if self._pos < len(self._buf):
                events.append(("content", self._buf[self._pos:]))
                self._pos = len(self._buf)
            return
        if nxt > self._pos:
            events.append(("content", self._buf[self._pos:nxt]))
        self._pos = self._open = nxt
        self._state = "held"

    def _try_commit(self) -> Optional[str]:
        """Match an envelope prefix at the held opener. Returns the matched
        mode, "dead" when the candidate can never be an envelope, or None
        while undecided (needs more text)."""
        rest = self._buf[self._open:]
        for mode, pre in (("wrap", _WRAP_RE), ("list", _LIST_RE),
                          ("bare", None)):
            off = 0
            if pre is not None:
                m = pre.match(rest)
                if not m:
                    continue
                off = m.end()
                if mode == "list" and not rest[off:off + 1] == "{":
                    if rest[off:off + 1]:
                        continue            # a list, but not of objects
                    return None             # may still grow into one
            em = _ELEM_RE.match(rest[off:])
            if em:
                if em.group(1) not in self._known:
                    return "dead"           # hallucinated tool → content
                self._mode = mode
                self._elem_name = em.group(1)
                self._args_start = self._open + off + em.end()
                return mode
        return None

    # whitespace-stripped envelope heads a held candidate must stay
    # prefix-compatible with; divergence means it can NEVER commit
    _HEADS = ('{"tool_calls":[{"name":"', '{"name":"', '[{"name":"')

    def _could_still_commit(self) -> bool:
        text = re.sub(r"\s+", "", self._buf[self._open:])
        for head in self._HEADS:
            n = min(len(head), len(text))
            if text[:n] == head[:n]:
                return True
        return False

    def _st_held(self, events: List[tuple]) -> None:
        got = self._try_commit()
        if got is not None and got != "dead":
            self.calls += 1
            events.append(("tool_start", self.calls - 1, self._elem_name))
            self._emit_to = self._args_start
            self._state = "args"
            return
        if got == "dead" or not self._could_still_commit():
            # never an envelope (hallucinated name, or prose like 'if (x) {'
            # whose '{' balances late or never): release the opener and
            # rescan from the next char — ordinary streamed content must
            # not go silent waiting for a balance that may never come
            events.append(("content", self._buf[self._pos:self._open + 1]))
            self._pos = self._open + 1
            self._state = "scan"
            return
        # still prefix-compatible with an envelope (a bounded region — the
        # commit regex needs only the head + tool name): hold; a candidate
        # that BALANCES while still compatible (e.g. {"name":"x"} with no
        # arguments) is plain JSON content
        end = _value_end(self._buf, self._open)
        if end < 0:
            return
        events.append(("content", self._buf[self._pos:end]))
        self._pos = end
        self._state = "scan"

    def _st_args(self, events: List[tuple]) -> None:
        # skip whitespace before the value so fragment streaming can key on
        # the value's first character (dropped from fragments — the
        # concatenation stays valid JSON)
        while (self._args_start < len(self._buf)
               and self._buf[self._args_start].isspace()):
            self._args_start += 1
        if self._emit_to < self._args_start:
            self._emit_to = self._args_start
        end = _value_end(self._buf, self._args_start)
        if end < 0:
            # structured values are prefix-safe to stream; primitives wait
            head = self._buf[self._args_start:self._args_start + 1]
            if head in '{["' and len(self._buf) > self._emit_to:
                events.append(("tool_args", self.calls - 1,
                               self._buf[self._emit_to:]))
                self._emit_to = len(self._buf)
            return
        if end > self._emit_to:
            events.append(("tool_args", self.calls - 1,
                           self._buf[self._emit_to:end]))
        self._pos = self._emit_to = end
        self._state = "between"

    def _st_between(self, events: List[tuple]) -> None:
        """After an argument value: either another element follows (wrap/
        list modes) or the envelope closes; trailing text is swallowed
        (the buffered path likewise reports content=None for calls)."""
        rest = self._buf[self._pos:]
        if self._mode in ("wrap", "list"):
            m = re.match(r"\s*\}\s*,\s*", rest)
            if m:
                em = _ELEM_RE.match(rest[m.end():])
                if em:
                    if em.group(1) not in self._known:
                        self._state = "done"    # partial envelope: stop
                        return
                    self.calls += 1
                    events.append(("tool_start", self.calls - 1, em.group(1)))
                    self._args_start = self._pos + m.end() + em.end()
                    self._emit_to = self._args_start
                    self._state = "args"
                return
        if re.match(r"\s*\}\s*\]\s*\}" if self._mode == "wrap" else
                    r"\s*\}\s*\]" if self._mode == "list" else r"\s*\}",
                    rest):
            self._state = "done"

    def _st_done(self, events: List[tuple]) -> None:
        self._pos = len(self._buf)


def parse_tool_calls(text: str, tools: Sequence[Dict[str, Any]]
                     ) -> Optional[List[Dict[str, Any]]]:
    """Structured tool calls in ``text``, or None when it is plain content.

    Returns the OpenAI wire shape: [{"id", "type": "function",
    "function": {"name", "arguments": <json string>}}]."""
    found = extract_json_value(text)
    if found is None:
        return None
    value, _ = found
    if isinstance(value, dict) and isinstance(value.get("tool_calls"), list):
        raw_calls = value["tool_calls"]
    elif isinstance(value, dict) and "name" in value and (
            "arguments" in value or "parameters" in value):
        raw_calls = [value]
    elif isinstance(value, list) and value and all(
            isinstance(v, dict) and "name" in v for v in value):
        raw_calls = value
    else:
        return None
    known = set(tool_names(tools))
    calls = []
    for rc in raw_calls:
        if not isinstance(rc, dict):
            return None
        name = rc.get("name")
        if name not in known:
            return None   # hallucinated tool: treat the text as content
        args = rc.get("arguments", rc.get("parameters", {}))
        calls.append({
            "id": f"call_{uuid.uuid4().hex[:12]}",
            "type": "function",
            "function": {"name": name,
                         "arguments": json.dumps(_parse_args(args))},
        })
    return calls or None
