"""OpenAI tool calling + JSON mode for the serving layer.

Capability parity with the NIM tool-calling surface the reference's agent
notebooks consume (`tools` / `tool_choice` / `tool_calls` /
`response_format`; ref: RAG/notebooks/langchain/
Agent_use_tools_leveraging_NVIDIA_AI_endpoints.ipynb and
NIM_tool_call_HumanInTheLoop_MultiAgents.ipynb bind tools through the
OpenAI schema and read `message.tool_calls` back).

Mechanism: tools are rendered into the system prompt as JSON schemas with
a strict output contract (the llama-3 style of tool use — the template
teaches the model to answer with a single JSON object when it wants a
tool), and the generated text is parsed back into structured
`tool_calls`. Parsing is deliberately forgiving about the shapes tuned
models actually emit ({"name","arguments"} | {"name","parameters"} |
{"tool_calls":[...]} | a bare list), but strict about unknown tool names
— a hallucinated tool comes back as plain content, never as a bogus call.

JSON mode (`response_format={"type":"json_object"}`) rides the same
prompt+extract path: the first balanced JSON value in the output is the
response. Token-level grammar masking is intentionally NOT done here: the
engine fuses 8 decode steps per dispatch (the throughput design point,
scheduler.py), and a per-token host round trip to mask logits would undo
exactly that; the extract-or-retry loop lives one level up
(chains/extraction.py) where retries are cheap.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

TOOL_PROMPT = """\
You have access to the following tools. To call a tool, respond with ONLY \
a JSON object of the form {{"tool_calls": [{{"name": "<tool name>", \
"arguments": {{...}}}}]}} and nothing else. Call a tool only when it helps \
answer the request; otherwise reply normally in plain text.

Tools:
{tools}"""

TOOL_REQUIRED = ("\nYou MUST call one of the tools — a plain-text reply is "
                 "not acceptable for this request.")
TOOL_NAMED = ("\nYou MUST call the tool named {name!r} — no other tool and "
              "no plain-text reply.")

JSON_PROMPT = ("Respond with ONLY a single valid JSON object — no prose, "
               "no code fences.")
JSON_SCHEMA_PROMPT = ("Respond with ONLY a single valid JSON object matching "
                      "this JSON schema — no prose, no code fences:\n{schema}")
JSON_WITH_TOOLS_PREFIX = ("When you are NOT calling a tool, your reply must "
                          "follow this rule: ")


def _tool_lines(tools: Sequence[Dict[str, Any]]) -> str:
    lines = []
    for t in tools:
        fn = t.get("function", t)
        lines.append(json.dumps({
            "name": fn.get("name", ""),
            "description": fn.get("description", ""),
            "parameters": fn.get("parameters", {}),
        }))
    return "\n".join(lines)


def tool_names(tools: Sequence[Dict[str, Any]]) -> List[str]:
    return [t.get("function", t).get("name", "") for t in tools]


def forced_name(tool_choice) -> Optional[str]:
    """The tool name a {"type":"function","function":{"name":...}} choice
    pins, else None."""
    if isinstance(tool_choice, dict):
        return tool_choice.get("function", {}).get("name")
    return None


def inject_tool_prompt(messages: Sequence[Dict[str, Any]],
                       tools: Sequence[Dict[str, Any]],
                       tool_choice) -> List[Dict[str, Any]]:
    """Prepend/extend the system message with the tool contract."""
    text = TOOL_PROMPT.format(tools=_tool_lines(tools))
    if tool_choice == "required":
        text += TOOL_REQUIRED
    name = forced_name(tool_choice)
    if name:
        text += TOOL_NAMED.format(name=name)
    return _with_system_suffix(messages, text)


def inject_json_prompt(messages: Sequence[Dict[str, Any]],
                       response_format: Dict[str, Any],
                       with_tools: bool = False) -> List[Dict[str, Any]]:
    """``with_tools`` scopes the constraint to non-tool-call replies so the
    two output contracts (tool_calls JSON vs. content JSON) don't clash."""
    if response_format.get("type") == "json_schema":
        schema = response_format.get("json_schema", {}).get("schema", {})
        text = JSON_SCHEMA_PROMPT.format(schema=json.dumps(schema))
    else:
        text = JSON_PROMPT
    if with_tools:
        text = JSON_WITH_TOOLS_PREFIX + text
    return _with_system_suffix(messages, text)


def _with_system_suffix(messages: Sequence[Dict[str, Any]],
                        suffix: str) -> List[Dict[str, Any]]:
    out = [dict(m) for m in messages]
    for m in out:
        if m.get("role") == "system":
            m["content"] = f"{m.get('content', '')}\n\n{suffix}"
            return out
    return [{"role": "system", "content": suffix}] + out


def normalize_messages(messages: Sequence[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Render OpenAI tool-protocol messages into template-friendly text:
    an assistant turn carrying `tool_calls` becomes its JSON contract form
    (so the model sees its own past calls the way it was taught to emit
    them), and `role:"tool"` results keep their role with the tool name
    prefixed."""
    out: List[Dict[str, Any]] = []
    for m in messages:
        role = m.get("role", "user")
        if role == "assistant" and m.get("tool_calls"):
            calls = [{"name": c.get("function", {}).get("name", ""),
                      "arguments": _parse_args(
                          c.get("function", {}).get("arguments"))}
                     for c in m["tool_calls"]]
            out.append({"role": "assistant",
                        "content": json.dumps({"tool_calls": calls})})
        elif role == "tool":
            name = m.get("name", "")
            prefix = f"[{name}] " if name else ""
            out.append({"role": "tool",
                        "content": f"{prefix}{m.get('content', '')}"})
        else:
            out.append({"role": role, "content": m.get("content", "") or ""})
    return out


def _parse_args(arguments) -> Dict[str, Any]:
    if isinstance(arguments, dict):
        return arguments
    if isinstance(arguments, str):
        try:
            parsed = json.loads(arguments)
            return parsed if isinstance(parsed, dict) else {"value": parsed}
        except ValueError:
            return {"raw": arguments}
    return {}


# ---------------------------------------------------------------- parsing

def extract_json_value(text: str) -> Optional[Tuple[Any, Tuple[int, int]]]:
    """First balanced JSON object/array in ``text`` → (value, (start, end)).

    A bracket scanner (string/escape aware) finds candidate spans; only
    spans that json-parse count, so ``{"a": 1} trailing prose`` and fenced
    ```json blocks both work without regex fragility."""
    for start, opener in ((i, c) for i, c in enumerate(text) if c in "{["):
        closer = "}" if opener == "{" else "]"
        depth = 0
        in_str = False
        escape = False
        for j in range(start, len(text)):
            c = text[j]
            if escape:
                escape = False
            elif c == "\\":
                escape = in_str
            elif c == '"':
                in_str = not in_str
            elif not in_str:
                if c in "{[":
                    depth += 1
                elif c in "]}":
                    depth -= 1
                    if depth == 0:
                        if c != closer:
                            break  # mismatched nesting; try the next start
                        try:
                            return (json.loads(text[start:j + 1]),
                                    (start, j + 1))
                        except ValueError:
                            break
        # unbalanced from this start; try the next opener
    return None


def parse_tool_calls(text: str, tools: Sequence[Dict[str, Any]]
                     ) -> Optional[List[Dict[str, Any]]]:
    """Structured tool calls in ``text``, or None when it is plain content.

    Returns the OpenAI wire shape: [{"id", "type": "function",
    "function": {"name", "arguments": <json string>}}]."""
    found = extract_json_value(text)
    if found is None:
        return None
    value, _ = found
    if isinstance(value, dict) and isinstance(value.get("tool_calls"), list):
        raw_calls = value["tool_calls"]
    elif isinstance(value, dict) and "name" in value and (
            "arguments" in value or "parameters" in value):
        raw_calls = [value]
    elif isinstance(value, list) and value and all(
            isinstance(v, dict) and "name" in v for v in value):
        raw_calls = value
    else:
        return None
    known = set(tool_names(tools))
    calls = []
    for rc in raw_calls:
        if not isinstance(rc, dict):
            return None
        name = rc.get("name")
        if name not in known:
            return None   # hallucinated tool: treat the text as content
        args = rc.get("arguments", rc.get("parameters", {}))
        calls.append({
            "id": f"call_{uuid.uuid4().hex[:12]}",
            "type": "function",
            "function": {"name": name,
                         "arguments": json.dumps(_parse_args(args))},
        })
    return calls or None
