"""BaseExample — the plugin contract every RAG pipeline implements.

Parity with the reference ABC (ref: RAG/src/chain_server/base.py:22-68):
required `llm_chain` / `rag_chain` / `ingest_docs`; optional
`document_search` / `get_documents` / `delete_documents` degrade gracefully
when unimplemented (the server returns the same fallbacks the reference's
duck-typing produced).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Iterator, List, Sequence


class BaseExample(ABC):
    """A pluggable chain. Generation methods yield text deltas (the server
    wraps them into SSE chunks, ref server.py:350-376)."""

    @abstractmethod
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        """Answer from the model alone (use_knowledge_base=false path,
        ref basic_rag/langchain/chains.py:91-118)."""

    @abstractmethod
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        """Retrieve → augment → generate (ref chains.py:121-192)."""

    @abstractmethod
    def ingest_docs(self, filepath: str, filename: str) -> None:
        """Parse + chunk + embed + index an uploaded file (ref chains.py:54-88)."""

    # -------------------------------------------------- optional operations

    def document_search(self, query: str, num_docs: int = 4) -> List[Dict[str, Any]]:
        """Top-k chunks with scores (ref utils/document_search via
        server.py:418-438). Default: not supported."""
        raise NotImplementedError

    def get_documents(self) -> List[str]:
        """Uploaded source filenames (ref server.py:441-464)."""
        raise NotImplementedError

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        """Remove all chunks of the named files (ref server.py:467-491)."""
        raise NotImplementedError
