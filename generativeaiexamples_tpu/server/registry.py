"""Explicit example registry — replaces os.walk duck-typing discovery.

The reference discovers its chain by walking `EXAMPLE_PATH` and duck-typing
any class with {ingest_docs, llm_chain, rag_chain} (ref: server.py:203-238).
In-tree chains make that indirection unnecessary: examples register by name,
the served one is chosen by the ``EXAMPLE`` env var (compose parity with
`EXAMPLE_PATH`, ref basic_rag/langchain/docker-compose.yaml:20-23).
"""

from __future__ import annotations

import importlib
import logging
import os
from typing import Callable, Dict, Optional

from generativeaiexamples_tpu.server.base import BaseExample

logger = logging.getLogger(__name__)

_REGISTRY: Dict[str, Callable[..., BaseExample]] = {}

# name → module that registers it on import (lazy, so chains' deps load only
# when selected)
_KNOWN = {
    "basic_rag": "generativeaiexamples_tpu.chains.basic_rag",
    "multi_turn_rag": "generativeaiexamples_tpu.chains.multi_turn_rag",
    "query_decomposition_rag": "generativeaiexamples_tpu.chains.query_decomposition",
    "structured_data_rag": "generativeaiexamples_tpu.chains.structured_data",
    "multimodal_rag": "generativeaiexamples_tpu.chains.multimodal",
    "agentic_rag": "generativeaiexamples_tpu.chains.agentic_rag",
    "knowledge_graph_rag": "generativeaiexamples_tpu.chains.knowledge_graph_rag",
    "text_to_sql": "generativeaiexamples_tpu.chains.text_to_sql",
    "router_rag": "generativeaiexamples_tpu.chains.router_rag",
    "asr_stream_rag": "generativeaiexamples_tpu.chains.asr_stream_rag",
}


def register_example(name: str):
    def wrap(factory: Callable[..., BaseExample]):
        _REGISTRY[name] = factory
        return factory
    return wrap


def get_example(name: Optional[str] = None, **kwargs) -> BaseExample:
    """Instantiate the selected example (env ``EXAMPLE``, default basic_rag)."""
    name = name or os.environ.get("EXAMPLE", "basic_rag")
    if name not in _REGISTRY:
        module = _KNOWN.get(name)
        if module is None:
            raise KeyError(f"unknown example {name!r}; known: {sorted(_KNOWN)}")
        try:
            importlib.import_module(module)
        except ModuleNotFoundError as exc:
            if exc.name != module:  # a transitive dep is missing, not the example
                raise
            raise KeyError(
                f"example {name!r} is not implemented yet "
                f"(module {module} missing)") from exc
    if name not in _REGISTRY:
        raise KeyError(f"module for {name!r} imported but did not register")
    logger.info("serving example: %s", name)
    return _REGISTRY[name](**kwargs)
