"""Chain server: REST + SSE orchestration API with pluggable example chains.

The TPU stack's L6 (ref: RAG/src/chain_server/server.py): same endpoint set
(/health, /generate, /search, /documents GET/POST/DELETE) and SSE chunk
contract, rebuilt on aiohttp with an explicit plugin registry instead of
os.walk duck-typing (ref server.py:203-238).
"""

from generativeaiexamples_tpu.server.base import BaseExample  # noqa: F401
from generativeaiexamples_tpu.server.registry import get_example, register_example  # noqa: F401
