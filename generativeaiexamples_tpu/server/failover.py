"""Multi-worker serving resilience: health-tracked failover with mid-stream
resume.

Closes SURVEY §5.3's multi-host gap (the round-3 partial): the reference
leans on compose healthchecks + `restart: always` + generous client retries
(ref: RAG/examples/local_deploy/docker-compose-nim-ms.yaml:23-28,
docker-compose-vectordb.yaml:90,108) — a worker death still kills every
in-flight generation. Here the chain-server side heals mid-stream:

  * ``FailoverLLM`` speaks OpenAI ``/v1`` to a POOL of engine workers
    (e.g. one per TPU slice host). A request streams from one worker; if
    the connection dies or the stream reports an engine error, the client
    RESUBMITS to a surviving worker carrying the text already emitted
    (``continue_text`` — the engine renders template + prefix and decodes
    onward, the same prompt+generated resume shape its own scheduler uses
    for preemptions, engine/server.py). The consumer's iterator never
    notices: no duplicate text, no dropped stream.
  * Failed workers are circuit-broken for a cooldown and re-admitted only
    after ``/health`` passes — meanwhile deploy/supervisor.py restarts the
    dead process (its §5.3 role), so the pool self-heals.

The pool is selected by APP_LLM_SERVER_URL containing a comma-separated
URL list (chains/llm_client.py get_llm) — zero changes to any chain.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Dict, Iterator, List, Sequence

from generativeaiexamples_tpu.core.config import http_timeout
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import slo as slo_mod

logger = logging.getLogger(__name__)

_PRESSURE_GAUGE = {"ok": 0, "warn": 1, "critical": 2}


class _Worker:
    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        self.down_until = 0.0
        # last SLO pressure the worker reported on /health (observability/
        # slo.py rides the liveness body): "" until first probed. A worker
        # can be alive-but-burning — the pool surfaces that distinction.
        self.slo_pressure = ""

    def healthy(self, timeout: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(f"{self.url}/health",
                                        timeout=timeout) as resp:
                ok = 200 <= resp.status < 300
                if ok:
                    try:
                        body = json.loads(resp.read().decode("utf-8"))
                        self.slo_pressure = str(
                            body.get("slo_pressure", "") or "")
                    except (ValueError, UnicodeDecodeError) as exc:
                        logger.debug("health body from %s unparsable: %s",
                                     self.url, exc)
                    if self.slo_pressure in _PRESSURE_GAUGE:
                        # per-worker pressure on the POOL CLIENT's own
                        # /metrics (0/1/2) — the operator view of
                        # alive-but-burning workers, refreshed by the
                        # probes the serving path already makes
                        REGISTRY.gauge(
                            "failover_worker_slo_pressure",
                            labels={"worker": self.url},
                        ).set(_PRESSURE_GAUGE[self.slo_pressure])
                    if self.slo_pressure == "critical":
                        logger.warning("worker %s healthy but reports "
                                       "critical SLO pressure", self.url)
                return ok
        except Exception as exc:
            # an unreachable worker is the EXPECTED case this probe exists
            # for — debug keeps the recovery loop quiet but traceable
            logger.debug("health probe %s failed: %s", self.url, exc)
            return False


class FailoverLLM:
    """Drop-in for RemoteLLM (chains/llm_client.py) over several workers."""

    def __init__(self, urls: Sequence[str], model: str,
                 cooldown_s: float = 10.0, max_attempts: int = 4) -> None:
        if not urls:
            raise ValueError("FailoverLLM needs at least one worker URL")
        self._workers = [_Worker(u) for u in urls]
        self.model = model
        self.cooldown_s = cooldown_s
        self.max_attempts = max_attempts
        self._rr = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- selection

    def _candidates(self) -> List[_Worker]:
        """Round-robin order, circuit-broken workers last (re-probed —
        the supervisor may have restarted them)."""
        with self._lock:
            self._rr += 1
            order = (self._workers[self._rr % len(self._workers):]
                     + self._workers[: self._rr % len(self._workers)])
        now = time.monotonic()
        up = [w for w in order if w.down_until <= now]
        recovering = [w for w in order if w.down_until > now]
        return up + recovering

    def _mark_down(self, w: _Worker) -> None:
        w.down_until = time.monotonic() + self.cooldown_s
        logger.warning("engine worker %s marked down for %.0fs", w.url,
                       self.cooldown_s)

    # --------------------------------------------------------------- serving

    def chat(self, messages: Sequence[Dict[str, str]], max_tokens: int = 256,
             temperature: float = 0.7, top_p: float = 1.0,
             top_k: int = 0, response_format: Dict = None) -> Iterator[str]:
        """Streaming chat that survives worker death mid-generation.
        ``response_format`` rides through to the engine — under a
        json_schema grammar the resumed stream is byte-exact (the engine
        walks the grammar over the continuation prefix)."""
        import httpx

        emitted: List[str] = []
        last_err: Exception = RuntimeError("no engine worker available")
        for attempt in range(self.max_attempts):
            cands = self._candidates()
            w = cands[0]
            if w.down_until > time.monotonic() and not w.healthy():
                last_err = RuntimeError(f"{w.url} unhealthy")
                continue
            payload = {"model": self.model, "messages": list(messages),
                       "max_tokens": max_tokens, "temperature": temperature,
                       "top_p": top_p, "top_k": top_k, "stream": True}
            if response_format:
                payload["response_format"] = dict(response_format)
            if emitted:
                payload["continue_text"] = "".join(emitted)
                logger.info("resuming stream on %s at %d chars", w.url,
                            len(payload["continue_text"]))
            try:
                # SLO class + remaining deadline + traceparent, same as
                # RemoteLLM — a failover RESUME carries the (shrunken)
                # remaining budget, so the survivor judges against the
                # deadline the original admission stamped
                with httpx.stream("POST", f"{w.url}/v1/chat/completions",
                                  json=payload,
                                  headers=slo_mod.outbound_headers(),
                                  timeout=http_timeout(120.0)) as resp:
                    if resp.status_code >= 500:
                        raise httpx.TransportError(
                            f"HTTP {resp.status_code}")
                    resp.raise_for_status()   # 4xx: deterministic — raise
                    truncated = True
                    for line in resp.iter_lines():
                        if not line.startswith("data: "):
                            continue
                        data = line[len("data: "):]
                        if data.strip() == "[DONE]":
                            truncated = False
                            break
                        chunk = json.loads(data)
                        choices = chunk.get("choices") or [{}]
                        if (chunk.get("error")
                                or choices[0].get("finish_reason") == "error"):
                            # the engine is ALIVE and reporting a request-
                            # level failure: retrying the same payload is
                            # pointless and would circuit-break a healthy
                            # worker — surface it
                            raise RuntimeError(
                                f"engine error: {chunk.get('error')}")
                        content = choices[0].get("delta", {}).get("content")
                        if content:
                            emitted.append(content)
                            yield content
                    if not truncated:
                        return                          # clean completion
                # stream ended without [DONE]: the worker died mid-reply —
                # mark it down and resume on a survivor
                raise httpx.TransportError(f"{w.url} stream truncated")
            except (httpx.TransportError, httpx.StreamError,
                    json.JSONDecodeError, ConnectionError, OSError) as exc:
                last_err = exc
                self._mark_down(w)
        raise RuntimeError(
            f"LLM request failed across {self.max_attempts} attempts: "
            f"{last_err}")

    def chat_tools(self, messages: Sequence[Dict], tools: Sequence[Dict],
                   tool_choice="auto", **sampling) -> Dict:
        """Non-streamed tool turn: whole-request retry across the pool."""
        import httpx

        payload = {"model": self.model, "messages": list(messages),
                   "stream": False, **sampling}
        if tools:
            payload["tools"] = list(tools)
            payload["tool_choice"] = tool_choice
        last_err: Exception = RuntimeError("no engine worker available")
        for _ in range(self.max_attempts):
            w = self._candidates()[0]
            if w.down_until > time.monotonic() and not w.healthy():
                last_err = RuntimeError(f"{w.url} unhealthy")
                continue
            try:
                resp = httpx.post(f"{w.url}/v1/chat/completions",
                                  json=payload,
                                  headers=slo_mod.outbound_headers(),
                                  timeout=http_timeout(120.0))
                if resp.status_code >= 500:
                    raise httpx.TransportError(f"HTTP {resp.status_code}")
                resp.raise_for_status()       # 4xx: deterministic — raise
                return resp.json()["choices"][0]["message"]
            except (httpx.TransportError, httpx.StreamError,
                    json.JSONDecodeError, ConnectionError, OSError) as exc:
                last_err = exc
                self._mark_down(w)
        raise RuntimeError(f"tool request failed across the pool: {last_err}")
