"""Multi-worker routing frontend: role discovery, SLO-aware least-loaded
dispatch, disaggregated prefill→decode handoff, and mid-stream failover.

Generalizes the round-3 health-tracked failover pool into the serving
frontend ROADMAP item 1 calls for (the placement/phase-splitting axis RAGO
identifies as dominant for RAG serving): the reference leans on compose
healthchecks + ``restart: always`` + client retries (ref: RAG/examples/
local_deploy/docker-compose-nim-ms.yaml:23-28), one static worker behind
one URL. Here the chain-server side routes:

  * **Role discovery.** Every worker's ``/health`` body carries its
    ``engine_role`` (core/config.py ``APP_ENGINE_ROLE``) plus live load —
    queue depth, slot fill, SLO pressure (engine/server.py health). The
    pool learns the topology from the probes it already makes; a worker
    with no role field is a plain unified worker (old engines keep
    working).
  * **Least-loaded dispatch.** Selection is scored, not round-robin:
    ``(running + prefilling + waiting + locally-dispatched) / batch`` plus
    an SLO-pressure penalty (the PR-4 headroom/shed signals surfaced on
    /health) — an alive-but-burning worker is dispreferred before it ever
    breaches. ``dispatched`` counts this client's own sends since the last
    probe, so a burst between probes still spreads.
  * **Disaggregated serving.** When the pool holds prefill- AND decode-role
    workers, a chat streams in two phases: POST ``/v1/kv/prefill`` on the
    least-loaded prefill worker (chunked prefill + KV-page export), then
    hand the payload to the least-loaded decode replica's
    ``/v1/kv/handoff`` and stream the completion. Long prefills never
    contend with decode steps for a chip — the structural fix for the
    prefill/decode interference the single-chip mixed dispatch (PR 5) can
    only soften.
  * **Failure path preserved.** A worker death mid-stream circuit-breaks it
    for a cooldown and RESUMES on survivors carrying the emitted prefix
    (``continue_text`` — re-prefilled through the same route, so a
    disaggregated resume re-prefills on a prefill worker and decodes on
    another replica). The consumer's iterator never notices: no duplicate
    text, no dropped stream.

The pool is selected by APP_LLM_SERVER_URL containing a comma-separated
URL list (chains/llm_client.py get_llm) — zero changes to any chain.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import urllib.request
import uuid
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence

from generativeaiexamples_tpu.core import kv_wire as kv_wire_mod
from generativeaiexamples_tpu.core.config import env_float as _env_float
from generativeaiexamples_tpu.core.config import env_int as _env_int
from generativeaiexamples_tpu.core.config import http_timeout
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import chaos as chaos_mod
from generativeaiexamples_tpu.observability import otel
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability import usage as usage_mod
from generativeaiexamples_tpu.observability.lockwatch import tracked_lock
from generativeaiexamples_tpu.observability.trace import TRACE
from generativeaiexamples_tpu.server import resilience

logger = logging.getLogger(__name__)


class StreamEvacuated(Exception):
    """The serving worker ended the stream with finish_reason "evacuated"
    (graceful drain / SIGTERM / watchdog trip — engine/scheduler.py
    _do_evacuate): its mid-decode snapshot is waiting at
    ``/v1/kv/evacuation/<rid>``. NOT an error and NOT a truncation — the
    router pulls the snapshot and resumes token-identically on a peer,
    falling back to the ``continue_text`` re-prefill only when the pull
    fails (hard death, never-snapshotable slot)."""


# the process's routing frontend, registered at FailoverLLM construction:
# GET /debug/fleet (server/common.py) answers from whichever router this
# process last built — the fleet view lives where the probes live
_ROUTER: Optional["FailoverLLM"] = None


def register_router(router: Optional["FailoverLLM"]) -> None:
    global _ROUTER
    _ROUTER = router


def current_router() -> Optional["FailoverLLM"]:
    return _ROUTER

# numeric per-worker /health fields the router re-exports on its OWN
# /metrics as `fleet_worker_<field>{worker="<url>"}` gauges — the
# federated view: one scrape of the router answers "which replica holds
# the cache / burns the chip" without scraping N workers
_FLEET_GAUGE_FIELDS = ("occupancy", "prefix_hit_frac", "mfu",
                       "hbm_read_util", "padding_waste_frac", "recompiles",
                       "waiting", "kv_pages_free", "kv_spill_used_bytes",
                       "kv_spill_budget_bytes", "kv_tier_bytes",
                       "kv_tier_entries")

_PRESSURE_GAUGE = {"ok": 0, "warn": 1, "critical": 2}
# least-loaded scoring: an alive-but-burning worker yields to a healthy one
# unless every alternative is deeply queued (critical ≈ 4 extra batches)
_PRESSURE_PENALTY = {"": 0.0, "ok": 0.0, "warn": 1.0, "critical": 4.0}


class _Worker:
    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        self.down_until = 0.0
        # circuit-breaker half-open state (server/resilience.py doctrine):
        # set when the worker is marked down; once the cooldown expires
        # exactly ONE thread (probe_lock try-acquire) runs the canary
        # health probe — the rest keep treating the worker as down until
        # the probe passes, so recovery is a single request, not a
        # stampede of everything that queued up during the outage
        self.half_open = False
        self.probe_lock = tracked_lock("failover.probe_lock")
        # discovered from /health (engine/server.py health handler): the
        # worker's serving role and live load. "" role = not yet probed;
        # a health body with no engine_role field is a unified worker.
        self.role = ""
        self.running = 0
        self.prefilling = 0
        self.waiting = 0
        self.batch = 0
        self.probed_at = 0.0          # monotonic of the last good probe
        # requests THIS client routed here since the last probe: keeps a
        # burst between probes spreading instead of piling on one worker
        self.dispatched = 0
        self.total_dispatched = 0     # never reset (bench imbalance reads it)
        # last SLO pressure the worker reported on /health (observability/
        # slo.py rides the liveness body): "" until first probed. A worker
        # can be alive-but-burning — the pool surfaces that distinction.
        self.slo_pressure = ""
        # fleet usage plane (observability/usage.py): the per-tenant
        # rollup, chip-utilization card, prefix-cache coverage, and
        # watchdog state the worker's /health body piggybacks on the
        # probes this pool already makes — /debug/fleet aggregates these
        self.kv_pages_free = 0
        self.prefix_hit_frac = 0.0
        # host spill/prefix-tier occupancy (engine/kv_tier.py): budget
        # headroom rides every probe so capacity is visible BEFORE the
        # router sends preemption-heavy load; kv_tier_hot is the worker's
        # advertised hottest prefix hashes (h0 hex) — what promote
        # routing matches a learned conversation hash against
        self.kv_spill_used_bytes = 0
        self.kv_spill_budget_bytes = 0
        self.kv_tier_bytes = 0
        self.kv_tier_entries = 0
        self.kv_tier_hot: frozenset = frozenset()
        # KV-wire capability advert (engine/server.py health): True once
        # the worker declares it accepts the binary frame on
        # /v1/kv/handoff. Workers predating the binary wire carry no
        # field → False → the router relays/transcodes to JSON base64.
        self.kv_binary = False
        self.perf: Dict[str, object] = {}
        self.usage: Dict[str, Dict[str, float]] = {}
        self.watchdog: Optional[Dict[str, object]] = None

    def healthy(self, timeout: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(f"{self.url}/health",
                                        timeout=timeout) as resp:
                ok = 200 <= resp.status < 300
                if ok:
                    try:
                        body = json.loads(resp.read().decode("utf-8"))
                        self.role = str(body.get("engine_role", "")
                                        or "unified")
                        self.running = int(body.get("running", 0) or 0)
                        self.prefilling = int(body.get("prefilling", 0) or 0)
                        self.waiting = int(body.get("waiting", 0) or 0)
                        self.batch = int(body.get("batch", 0) or 0)
                        self.slo_pressure = str(
                            body.get("slo_pressure", "") or "")
                        # fleet piggyback: usage/cache/perf rollups ride
                        # the same probe (engine/server.py health)
                        self.kv_pages_free = int(
                            body.get("kv_pages_free", 0) or 0)
                        self.prefix_hit_frac = float(
                            body.get("prefix_hit_frac", 0.0) or 0.0)
                        self.kv_spill_used_bytes = int(
                            body.get("kv_spill_used_bytes", 0) or 0)
                        self.kv_spill_budget_bytes = int(
                            body.get("kv_spill_budget_bytes", 0) or 0)
                        self.kv_tier_bytes = int(
                            body.get("kv_tier_bytes", 0) or 0)
                        self.kv_tier_entries = int(
                            body.get("kv_tier_entries", 0) or 0)
                        hot = body.get("kv_tier_hot")
                        self.kv_tier_hot = (
                            frozenset(str(h) for h in hot)
                            if isinstance(hot, (list, tuple))
                            else frozenset())
                        wire = body.get("kv_wire")
                        self.kv_binary = (isinstance(wire, (list, tuple))
                                          and "binary" in wire)
                        perf = body.get("perf")
                        self.perf = dict(perf) if isinstance(perf, dict) \
                            else {}
                        rollup = body.get("usage_by_tenant")
                        self.usage = dict(rollup) \
                            if isinstance(rollup, dict) else {}
                        wd = body.get("watchdog")
                        self.watchdog = dict(wd) if isinstance(wd, dict) \
                            else None
                    except (ValueError, UnicodeDecodeError, TypeError) as exc:
                        logger.debug("health body from %s unparsable: %s",
                                     self.url, exc)
                        self.role = self.role or "unified"
                    self.probed_at = time.monotonic()
                    self.dispatched = 0
                    self._export_fleet_gauges()
                    if self.slo_pressure in _PRESSURE_GAUGE:
                        # per-worker pressure on the POOL CLIENT's own
                        # /metrics (0/1/2) — the operator view of
                        # alive-but-burning workers, refreshed by the
                        # probes the serving path already makes
                        REGISTRY.gauge(
                            "failover_worker_slo_pressure",
                            labels={"worker": self.url},
                        ).set(_PRESSURE_GAUGE[self.slo_pressure])
                    if self.slo_pressure == "critical":
                        logger.warning("worker %s healthy but reports "
                                       "critical SLO pressure", self.url)
                return ok
        except Exception as exc:
            # an unreachable worker is the EXPECTED case this probe exists
            # for — debug keeps the recovery loop quiet but traceable
            logger.debug("health probe %s failed: %s", self.url, exc)
            return False

    @property
    def occupancy(self) -> float:
        """Live slot fill from the last probe (running / batch)."""
        return self.running / self.batch if self.batch else 0.0

    def card(self, now: Optional[float] = None) -> Dict[str, object]:
        """This worker's row of the fleet view (/debug/fleet): role,
        load, cache affinity, chip utilization, watchdog state, and the
        per-tenant usage rollup — everything the probe cycle carried."""
        now = time.monotonic() if now is None else now
        return {
            "role": self.role or "unified",
            "down": self.down_until > now,
            "probe_age_s": (round(now - self.probed_at, 3)
                            if self.probed_at else None),
            "score": round(self.score, 4),
            "occupancy": round(self.occupancy, 4),
            "running": self.running,
            "prefilling": self.prefilling,
            "waiting": self.waiting,
            "batch": self.batch,
            "kv_pages_free": self.kv_pages_free,
            "prefix_hit_frac": self.prefix_hit_frac,
            "kv_spill_used_bytes": self.kv_spill_used_bytes,
            "kv_spill_budget_bytes": self.kv_spill_budget_bytes,
            "kv_tier_bytes": self.kv_tier_bytes,
            "kv_tier_entries": self.kv_tier_entries,
            "kv_tier_hot": sorted(self.kv_tier_hot),
            "slo_pressure": self.slo_pressure,
            "dispatched": self.total_dispatched,
            "watchdog": self.watchdog,
            **{k: self.perf.get(k) for k in ("mfu", "hbm_read_util",
                                             "measured_age_s",
                                             "padding_waste_frac",
                                             "recompiles")},
            "usage_by_tenant": self.usage,
        }

    def _export_fleet_gauges(self) -> None:
        """Mirror this worker's numeric probe fields onto the ROUTER
        process's /metrics as `fleet_worker_<field>{worker=...}` — the
        federated re-export (label cardinality bounded by the pool
        size). Runs on every good probe, so the gauges track the same
        refresh cycle the routing decisions use."""
        card = self.card()
        for field in _FLEET_GAUGE_FIELDS:
            value = card.get(field)
            if isinstance(value, bool) or not isinstance(value,
                                                         (int, float)):
                continue
            REGISTRY.gauge(f"fleet_worker_{field}",
                           labels={"worker": self.url}).set(value)
        # liveness marker: the other fleet_worker_* gauges HOLD their
        # last probed value (a dead worker's series would otherwise read
        # as a healthy one forever) — scrape consumers must join on this
        REGISTRY.gauge("fleet_worker_up",
                       labels={"worker": self.url}).set(1.0)

    @property
    def queue_depth(self) -> int:
        """This worker's advertised load in requests: everything queued,
        prefilling, or running from the last probe, plus this client's
        own un-probed dispatches — the ONE load expression the
        least-loaded score and the cost-modeled hedge trigger both read
        (a signal added to one must reach the other)."""
        return (self.running + self.prefilling + self.waiting
                + self.dispatched)

    @property
    def score(self) -> float:
        """Lower = less loaded. Queue depth normalized by slot capacity,
        plus the SLO-pressure penalty — the headroom/pressure signals from
        the PR-4 SLO plane, read straight off /health."""
        cap = float(self.batch or 8)
        return (self.queue_depth / cap
                + _PRESSURE_PENALTY.get(self.slo_pressure, 0.0))


class FailoverLLM:
    """Routing frontend over a pool of engine workers — drop-in for
    RemoteLLM (chains/llm_client.py). Unified pools behave like the round-3
    failover client (now least-loaded instead of round-robin); pools with
    prefill-/decode-role workers serve disaggregated."""

    def __init__(self, urls: Sequence[str], model: str,
                 cooldown_s: Optional[float] = None, max_attempts: int = 4,
                 refresh_s: Optional[float] = None,
                 hedge_s: Optional[float] = None,
                 policy: Optional[resilience.ResiliencePolicy] = None,
                 kv_wire: Optional[str] = None,
                 affinity_slack: Optional[float] = None) -> None:
        if not urls:
            raise ValueError("FailoverLLM needs at least one worker URL")
        self._workers = [_Worker(u) for u in urls]
        self.model = model
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else _env_float("APP_ROUTER_COOLDOWN_S", 10.0))
        self.max_attempts = max_attempts
        if refresh_s is None:
            refresh_s = _env_float("APP_ROUTER_REFRESH_S", 2.0)
        self.refresh_s = refresh_s
        # KV transport negotiation (core/kv_wire.py): "auto" (default)
        # requests the binary zero-copy frame from prefill workers and
        # relays it verbatim to binary-capable decode replicas,
        # transcoding to JSON base64 only for workers that never
        # advertised the frame; "json" forces the PR 6 compat wire
        # everywhere (bench A/Bs the two); "binary" refuses to transcode
        # (mixed-version pools fail loudly instead of silently paying
        # base64 — an operator assertion, not a serving default).
        self.kv_wire = (kv_wire if kv_wire is not None
                        else os.environ.get("APP_ROUTER_KV_WIRE",
                                            "auto").strip().lower() or "auto")
        if self.kv_wire not in ("auto", "json", "binary"):
            raise ValueError(f"kv_wire must be auto|json|binary, "
                             f"got {self.kv_wire!r}")
        # prefix-affinity stickiness: same-prefix conversations rendezvous-
        # hash to a preferred replica (see _pick); the slack bounds how
        # much WORSE (in least-loaded score units ≈ batches of queue
        # depth) the preferred replica may look before load wins.
        # Negative disables affinity outright.
        self.affinity_slack = (
            affinity_slack if affinity_slack is not None
            else _env_float("APP_ROUTER_AFFINITY_SLACK", 1.0))
        self.affinity_chars = int(_env_float("APP_ROUTER_AFFINITY_CHARS",
                                             512.0))
        # live-migration resume (APP_ROUTER_SNAPSHOT_RESUME, default on):
        # when a stream ends "evacuated" or dies mid-generation, try to
        # pull the worker's mid-decode snapshot and resume it
        # TOKEN-IDENTICALLY on a peer before falling back to the
        # continue_text re-prefill. "off" restores the PR 10 behavior
        # (re-prefill always) — the bench A/B arm.
        self.snapshot_resume = (os.environ.get(
            "APP_ROUTER_SNAPSHOT_RESUME", "").strip().lower() or "on") \
            != "off"
        # hedged KV-handoff opens (server/resilience.hedged_call): when the
        # primary decode replica hasn't opened the stream within hedge_s,
        # dispatch the SAME payload to the second-least-loaded replica and
        # stream whichever opens first. 0 (default) = off — hedging is
        # tail-latency insurance, priced at one duplicate dispatch.
        self.hedge_s = (hedge_s if hedge_s is not None
                        else _env_float("APP_ROUTER_HEDGE_S", 0.0))
        # cost-modeled hedging (engine/qos.py, PR 15): with the QoS plane
        # armed (APP_QOS=fair) the static hedge_s becomes the BASE of a
        # per-dispatch delay scaled by the primary replica's advertised
        # queue depth and floored at the router's own measured typical
        # handoff-open time (router_handoff_s p50) — a loaded-but-healthy
        # primary gets the time its queue legitimately needs before a
        # duplicate leg burns a second replica's cycles; hedges still
        # bill the tenant exactly as before. off = the static delay,
        # byte-identical to the PR 10 behavior.
        self._qos_hedge = (os.environ.get("APP_QOS", "")
                           .strip().lower() == "fair")
        # the shared retry policy: jittered backoff between attempts, a
        # per-pool retry BUDGET (token bucket — a retry storm cannot
        # amplify an outage beyond 1 + ratio), and the SLO-deadline
        # cutoff (a request past its deadline is shed, not retried)
        self._policy = policy if policy is not None else \
            resilience.ResiliencePolicy(
                "router", max_attempts=max_attempts,
                base_s=_env_float("APP_ROUTER_BACKOFF_S", 0.05),
                cap_s=_env_float("APP_ROUTER_BACKOFF_CAP_S", 2.0),
                budget=resilience.RetryBudget(
                    "router",
                    ratio=_env_float("APP_ROUTER_RETRY_RATIO", 0.5),
                    burst=_env_float("APP_ROUTER_RETRY_BURST", 10.0)))
        self._discovered = False
        self._discover_lock = tracked_lock("failover._discover_lock")
        # guards SELECTION state (score reads + dispatched increments) for
        # concurrent chat threads; health probes stay outside it (HTTP
        # under a lock is a tpulint-enforced hazard)
        self._lock = tracked_lock("failover._lock")
        # conversation -> prefix-hash map for promote routing (engine/
        # kv_tier.py fleet loop): the affinity key of a dispatched chat
        # maps to the h0 hash the serving worker stamped on X-KV-Prefix;
        # the next turn of that conversation can then be matched against
        # workers' advertised kv_tier_hot sets. Bounded LRU — the router
        # must never grow state per conversation without bound.
        self._prefix_hot: "OrderedDict[str, str]" = OrderedDict()
        self._prefix_hot_cap = _env_int("APP_ROUTER_PREFIX_MAP_CAP", 4096)
        # the fleet view (GET /debug/fleet) answers from this router
        register_router(self)

    # ------------------------------------------------------------- selection

    def _ensure_roles(self) -> None:
        """One-time topology discovery: probe every worker once so the
        first request already routes by role (later probes refresh lazily
        on the serving path)."""
        if self._discovered:
            return
        with self._discover_lock:
            if self._discovered:
                return
            for w in self._workers:
                if not w.healthy():
                    self._mark_down(w)
            self._discovered = True

    def topology(self, detail: bool = False) -> Dict[str, list]:
        """Discovered role → worker map (bench + debugging surface).
        Default shape is role → [url, ...]; ``detail=True`` lists each
        worker's routing card instead — load, ``prefix_hit_frac`` (the
        item-1 affinity signal, per replica), chip utilization — so the
        affinity work reads its signal off the same surface."""
        self._ensure_roles()
        out: Dict[str, list] = {}
        now = time.monotonic()
        for w in self._workers:
            entry = {"url": w.url, **w.card(now)} if detail else w.url
            out.setdefault(w.role or "unified", []).append(entry)
        return out

    def fleet(self, max_probe_age_s: Optional[float] = None
              ) -> Dict[str, object]:
        """The ``GET /debug/fleet`` body: every worker's probe card
        (role, occupancy, MFU, padding waste, prefix-hit frac,
        recompiles, watchdog state) plus the FLEET-SUMMED per-tenant
        usage rollup — one logical chat's prefill-worker and
        decode-replica legs land in one tenant row (usage rides the
        handoff, so both workers bill the same key).

        Probes refresh lazily on the serving path; a fleet read
        re-probes only workers whose view is older than
        ``max_probe_age_s`` (default: the router's refresh interval), so
        polling /debug/fleet during an incident costs at most one probe
        round, not a stampede."""
        self._ensure_roles()
        stale_after = (self.refresh_s if max_probe_age_s is None
                       else max_probe_age_s)
        now = time.monotonic()
        for w in self._workers:
            if w.down_until > now:
                continue
            if now - w.probed_at > stale_after and not w.healthy():
                self._mark_down(w)
        now = time.monotonic()
        workers = {w.url: w.card(now) for w in self._workers}
        up = [c for c in workers.values() if not c["down"]]
        return {
            "workers": workers,
            "roles": {role: [w.url for w in self._workers
                             if (w.role or "unified") == role]
                      for role in {(w.role or "unified")
                                   for w in self._workers}},
            # summed over EVERY worker's last-known rollup, down ones
            # included: the vectors are cumulative, so dropping a
            # circuit-broken worker would make fleet totals DIP during
            # the outage and jump back on recovery — a differencing
            # consumer (quota accounting) would see phantom swings
            "tenants": usage_mod.merge_rollups(
                c.get("usage_by_tenant") or {} for c in workers.values()),
            "workers_up": len(up),
            "workers_down": len(workers) - len(up),
            "generated_unix": round(time.time(), 3),
        }

    def dispatch_counts(self) -> Dict[str, Dict[str, object]]:
        """Per-worker lifetime dispatch counts + roles (bench reads the
        decode-replica imbalance from these)."""
        return {w.url: {"role": w.role or "unified",
                        "dispatched": w.total_dispatched}
                for w in self._workers}

    def _affinity_key(self, messages: Sequence[Dict]) -> str:
        """Stable key over the conversation's LEADING PREFIX BLOCKS — the
        part of the prompt whose KV a replica's prefix cache would hold.
        Keyed on the OPENING — every message up to and INCLUDING the
        first user message (truncated to ``affinity_chars``): a
        returning conversation grows at the TAIL, so turn 1 ([user1],
        or [system, user1]) and every later turn ([…, asst1, user2])
        truncate to the SAME head — and conversations sharing a long
        system prompt + opening collide deliberately (their shared
        prefix is exactly what one replica's cache can serve; the slack
        bounds the pileup). The volatile latest turn must never enter
        the key — hashing the whole serialization (or a fixed message
        COUNT) would remap a conversation between turns. Returns ""
        when affinity is disabled."""
        if self.affinity_slack < 0:
            return ""
        try:
            head_msgs = []
            for m in messages:
                head_msgs.append(m)
                if str(m.get("role", "")) == "user":
                    break
            head = json.dumps([[str(m.get("role", "")),
                                str(m.get("content", ""))]
                               for m in head_msgs])[:self.affinity_chars]
        except Exception:   # tpulint: disable=except-swallow -- non-dict message shapes (tool parts) just forgo stickiness; routing correctness never depends on the key
            return ""
        return hashlib.blake2b(head.encode("utf-8", "replace"),
                               digest_size=8).hexdigest()

    @staticmethod
    def _rendezvous(key: str, workers: List[_Worker]) -> _Worker:
        """Highest-random-weight (rendezvous) hash: every router in a
        fleet maps ``key`` to the same preferred worker with no shared
        state, and removing a worker only remaps the keys that pointed at
        it — the property that keeps prefix caches warm through pool
        changes (a modulo ring would reshuffle nearly everything)."""
        return max(workers,
                   key=lambda w: hashlib.blake2b(
                       f"{key}|{w.url}".encode(), digest_size=8).digest())

    def _learn_prefix(self, affinity_key: str, h0: str) -> None:
        """Record which token-hash prefix (h0, from the worker's
        X-KV-Prefix response header) a conversation's affinity key maps
        to — promote routing consults this on the conversation's NEXT
        turn. Bounded LRU; empty header (tier off worker-side) learns
        nothing."""
        if not affinity_key or not h0:
            return
        with self._lock:
            self._prefix_hot[affinity_key] = h0
            self._prefix_hot.move_to_end(affinity_key)
            while len(self._prefix_hot) > self._prefix_hot_cap:
                self._prefix_hot.popitem(last=False)

    def _pick(self, roles: Sequence[str],
              exclude: Sequence[str] = (),
              charge: bool = True,
              affinity_key: str = "",
              rid: str = "") -> Optional[_Worker]:   # tpulint: hot-path
        """Least-loaded healthy worker among ``roles``. Stale load views
        refresh via /health on the way (bounded by the probe timeout);
        circuit-broken workers re-probe only once their cooldown expires
        (the supervisor may have restarted them). ``charge=False``
        selects WITHOUT counting a dispatch — for a hedge candidate that
        only launches if the primary is slow; the actual launch charges
        it via :meth:`_charge` so scores and router_dispatches never
        record dispatches that didn't happen.

        ``affinity_key`` adds prefix-cache stickiness (ROADMAP item 1/3):
        the key's rendezvous-preferred worker wins over the least-loaded
        one as long as its score is within ``affinity_slack`` — scaled up
        by the replica's live ``prefix_hit_frac`` gauge (a replica
        demonstrably serving its cache earns more slack, because sending
        its conversations elsewhere costs a full re-prefill). Past the
        slack, load wins: affinity must never starve the least-loaded
        invariant (``router_affinity_total{outcome}`` counts both)."""
        self._ensure_roles()
        now = time.monotonic()
        cands = [w for w in self._workers
                 if (w.role or "unified") in roles and w.url not in exclude]
        up = [w for w in cands if w.down_until <= now]
        # half-open recovery: a worker past its cooldown needs ONE passing
        # canary probe before traffic returns. probe_lock try-acquire makes
        # it single-flight — concurrent picks skip the worker this pass
        # instead of stampeding everything that queued during the outage
        # onto a replica that may still be booting.
        for w in list(up):
            if not w.half_open:
                continue
            if w.probe_lock.acquire(blocking=False):
                try:
                    if w.healthy():
                        w.half_open = False
                        logger.info("worker %s passed half-open probe; "
                                    "re-admitted", w.url)
                    else:
                        self._mark_down(w)
                finally:
                    w.probe_lock.release()
            if w.half_open:
                up.remove(w)
        for w in up:
            if now - w.probed_at > self.refresh_s and not w.healthy():
                self._mark_down(w)
        # re-filter by ROLE as well as liveness: a refresh above may have
        # just discovered that a worker admitted under a stale/unknown role
        # actually serves a different one (e.g. a prefill worker that was
        # down at discovery) — dispatching to it would draw a deterministic
        # role 409, not a retryable transport error
        up = [w for w in up if w.down_until <= time.monotonic()
              and (w.role or "unified") in roles]
        if not up:
            # every candidate is cooling down: re-probe rather than fail —
            # a restarted worker re-admits the moment /health passes
            for w in cands:
                if w.healthy() and (w.role or "unified") in roles:
                    w.down_until = 0.0
                    w.half_open = False   # the probe WAS the canary
                    up.append(w)
        if not up:
            return None
        affinity_outcome = ""
        route_outcome = ""
        with self._lock:
            best = min(up, key=lambda w: w.score)
            if affinity_key and len(up) > 1:
                pref = self._rendezvous(affinity_key, up)
                slack = self.affinity_slack * (1.0 + pref.prefix_hit_frac)
                # prefix-tier promote routing (engine/kv_tier.py fleet
                # loop): when this conversation's learned token-hash
                # prefix is advertised hot by a replica OTHER than the
                # rendezvous pick, dispatching there PROMOTES host-cached
                # KV instead of re-prefilling — worth the same slack the
                # text-opening affinity earns. The token hash is exact
                # where the rendezvous key is heuristic, so it wins ties.
                h0 = self._prefix_hot.get(affinity_key, "")
                promote = None
                if h0 and h0 not in pref.kv_tier_hot:
                    adv = [w for w in up if h0 in w.kv_tier_hot]
                    if adv:
                        promote = min(adv, key=lambda w: w.score)
                if (promote is not None
                        and promote.score <= best.score + slack):
                    best = promote
                    route_outcome = "promote"
                elif pref.score <= best.score + slack:
                    best = pref
                    affinity_outcome = "pinned"
                    route_outcome = "affinity"
                else:
                    affinity_outcome = "overridden"
                    route_outcome = "load"
            if charge:
                best.dispatched += 1
                best.total_dispatched += 1
        if affinity_outcome:
            REGISTRY.counter("router_affinity_total",
                             labels={"outcome": affinity_outcome}).inc()
        if route_outcome:
            REGISTRY.counter("router_prefix_route_total",
                             labels={"outcome": route_outcome}).inc()
        if charge:
            REGISTRY.counter("router_dispatches",
                             labels={"worker": best.url,
                                     "role": best.role or "unified"}).inc()
        if TRACE.enabled:
            # placement decisions ride the same canonical stream the
            # scheduler writes: a replayed trace reconstructs WHERE each
            # request went and WHY (ops/simulate.py what-if routing); the
            # rid keys the forensics cross-worker join without requiring
            # span export to be configured
            TRACE.emit("route", rid=rid, worker=best.url,
                       role=best.role or "unified",
                       outcome=route_outcome or "load",
                       affinity=affinity_outcome, charged=bool(charge),
                       score=round(best.score, 4), pool=len(up))
        return best

    def _charge(self, w: _Worker, rid: str = "") -> None:
        """Count a dispatch against a worker selected with charge=False —
        called at the instant its hedge leg actually launches."""
        with self._lock:
            w.dispatched += 1
            w.total_dispatched += 1
        REGISTRY.counter("router_dispatches",
                         labels={"worker": w.url,
                                 "role": w.role or "unified"}).inc()
        if TRACE.enabled:
            TRACE.emit("hedge", rid=rid, worker=w.url,
                       role=w.role or "unified")

    def _has_disagg(self) -> bool:
        """Serve disaggregated iff the pool holds at least one prefill-role
        AND one decode-role worker not currently circuit-broken."""
        self._ensure_roles()
        now = time.monotonic()
        alive = [w for w in self._workers if w.down_until <= now]
        return (any(w.role == "prefill" for w in alive)
                and any(w.role == "decode" for w in alive))

    def _mark_down(self, w: _Worker) -> None:
        w.down_until = time.monotonic() + self.cooldown_s
        # once the cooldown expires the worker is HALF-OPEN: one canary
        # health probe (single-flight) must pass before traffic returns
        w.half_open = True
        # the federated gauges keep the worker's last probed values; the
        # up marker flips so a scrape can tell stale-because-dead from
        # live (the /debug/fleet card carries the same `down` flag)
        REGISTRY.gauge("fleet_worker_up", labels={"worker": w.url}).set(0.0)
        logger.warning("engine worker %s marked down for %.0fs", w.url,
                       self.cooldown_s)

    # --------------------------------------------------------------- serving

    def chat(self, messages: Sequence[Dict[str, str]], max_tokens: int = 256,
             temperature: float = 0.7, top_p: float = 1.0,
             top_k: int = 0, response_format: Dict = None) -> Iterator[str]:
        """Streaming chat that survives worker death mid-generation and
        serves disaggregated when the pool topology allows.
        ``response_format`` rides through to the engine on BOTH routes —
        under a json_schema grammar the resumed stream is byte-exact (the
        engine walks the grammar over the continuation prefix), and on
        disaggregated routes the grammar spec + walked state now ride the
        KV handoff's scalar passthrough (docs/performance.md).

        One ``X-Request-Id`` is minted per logical request and stamped on
        EVERY dispatch this call makes — the prefill→handoff pair, every
        failover retry/resume — so each worker's ``/debug/requests``
        timeline for the request shares the router's key."""
        rid = uuid.uuid4().hex[:12]
        self._policy.note_request()   # first attempt: retry-budget deposit
        akey = self._affinity_key(messages)
        if TRACE.enabled:
            # anchor the router-axis forensics partition at acceptance:
            # every later leg stamps its own end + duration, so the legs
            # partition [accept, last leg] on this process's mono clock
            TRACE.emit("router_leg", rid=rid, leg="accept", dur_s=0.0)
        if self._has_disagg():
            yield from self._chat_disagg(messages, max_tokens, temperature,
                                         top_p, top_k, response_format, rid,
                                         akey)
        else:
            yield from self._chat_unified(messages, max_tokens, temperature,
                                          top_p, top_k, response_format,
                                          rid=rid, affinity_key=akey)

    def _headers(self, rid: str,
                 span: Optional[otel.Span] = None) -> Dict[str, str]:
        """Outbound dispatch headers: SLO class + remaining deadline, the
        router's request id, and (when tracing) the W3C traceparent of the
        router's root span — the engine workers' spans become children, so
        one trace covers router → prefill → KV export → decode → first
        token."""
        headers = slo_mod.outbound_headers()
        headers["X-Request-Id"] = rid
        # usage plane: the ambient tenant identity (set by the chain
        # server from the inbound request) rides EVERY dispatch of a
        # logical request — prefill, handoff, retries, hedges — so each
        # worker bills the same tenant
        tenant = usage_mod.current_tenant()
        if tenant:
            headers["X-Tenant-Id"] = tenant
        otel.inject_traceparent(headers, span=span)
        return headers

    def _payload(self, messages, max_tokens, temperature, top_p, top_k,
                 response_format, emitted: List[str],
                 stream: bool) -> Dict[str, object]:
        payload: Dict[str, object] = {
            "model": self.model, "messages": list(messages),
            "max_tokens": max_tokens, "temperature": temperature,
            "top_p": top_p, "top_k": top_k}
        if stream:
            payload["stream"] = True
        if response_format:
            payload["response_format"] = dict(response_format)
        if emitted:
            payload["continue_text"] = "".join(emitted)
        return payload

    def _pump_sse(self, resp, emitted: List[str]) -> Iterator[str]:
        """Drain one OpenAI SSE stream, yielding content deltas and
        recording them in ``emitted`` (the resume prefix). Raises
        TransportError when the stream dies before [DONE] — the caller
        fails over; an engine-reported request error raises RuntimeError
        (retrying the same payload is pointless and would circuit-break a
        healthy worker)."""
        import httpx

        truncated = True
        for line in resp.iter_lines():
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data.strip() == "[DONE]":
                truncated = False
                break
            chunk = json.loads(data)
            choices = chunk.get("choices") or [{}]
            if (chunk.get("error")
                    or choices[0].get("finish_reason") == "error"):
                raise RuntimeError(f"engine error: {chunk.get('error')}")
            if choices[0].get("finish_reason") == "evacuated":
                # live migration: the worker is rotating out and parked
                # this stream's mid-decode snapshot — resume it on a
                # peer (the caller pulls /v1/kv/evacuation/<rid>)
                raise StreamEvacuated()
            content = choices[0].get("delta", {}).get("content")
            if content:
                emitted.append(content)
                yield content
        if truncated:
            raise httpx.TransportError("stream truncated")

    def _chat_unified(self, messages, max_tokens, temperature, top_p,
                      top_k, response_format,
                      emitted: Optional[List[str]] = None,
                      rid: Optional[str] = None, span=None,
                      attempt_base: int = 0,
                      affinity_key: str = "") -> Iterator[str]:
        """The round-3 failover path over unified/decode workers, selection
        upgraded from round-robin to least-loaded. ``emitted`` carries a
        prefix already delivered to the consumer (a disaggregated route
        falling back mid-stream) — it rides as ``continue_text`` so the
        stream resumes instead of restarting. ``rid``/``span`` ride from
        the calling route so retries and fallbacks keep one request id and
        one trace; ``attempt_base`` carries the calling route's spent
        attempts so a fallback's first dispatch still bills as the
        logical request's retry (usage plane)."""
        import httpx

        emitted = [] if emitted is None else emitted
        rid = rid or uuid.uuid4().hex[:12]
        last_err: Exception = RuntimeError("no engine worker available")
        for attempt in range(self.max_attempts):
            if attempt and not self._policy.before_retry(attempt):
                # denied by the shared policy: retry budget spent (a storm
                # must not amplify the outage) or the request's remaining
                # SLO deadline cannot survive the backoff — shed, not
                # retried (retries_denied_total{pool,reason})
                break
            w = self._pick(("unified", "decode", ""),
                           affinity_key=affinity_key, rid=rid)
            if w is None:
                last_err = RuntimeError("no unified/decode worker up")
                continue
            if attempt + attempt_base:
                # billed only once a worker is actually dispatched to —
                # a total outage burns no fleet capacity, so it bills none
                usage_mod.USAGE.bill_retry()
            payload = self._payload(messages, max_tokens, temperature,
                                    top_p, top_k, response_format, emitted,
                                    stream=True)
            if emitted:
                # this dispatch IS a resume, the recompute way: the
                # emitted prefix re-prefills on the new worker
                self._note_reprefill_resume()
                logger.info("resuming stream on %s at %d chars", w.url,
                            len(str(payload["continue_text"])))
            evacuated = False
            try:
                # chaos seam (observability/chaos.py): inside the try so an
                # injected reset/5xx takes the SAME failover path a real
                # one would; APP_CHAOS=off is one attribute read
                if chaos_mod.CHAOS.enabled:
                    chaos_mod.CHAOS.http_fault("router.chat")
                # SLO class + remaining deadline + traceparent, same as
                # RemoteLLM — a failover RESUME carries the (shrunken)
                # remaining budget, so the survivor judges against the
                # deadline the original admission stamped
                t_disp = time.monotonic()
                with httpx.stream("POST", f"{w.url}/v1/chat/completions",
                                  json=payload,
                                  headers=self._headers(rid, span),
                                  timeout=http_timeout(120.0)) as resp:
                    if resp.status_code >= 500:
                        raise httpx.TransportError(
                            f"HTTP {resp.status_code}")
                    resp.raise_for_status()   # 4xx: deterministic — raise
                    # promote routing learns conversation -> prefix hash
                    # from the worker's stamp (engine/kv_tier.py)
                    self._learn_prefix(affinity_key,
                                       resp.headers.get("x-kv-prefix", ""))
                    try:
                        yield from self._pump_sse(resp, emitted)
                        if TRACE.enabled:
                            TRACE.emit("router_leg", rid=rid, leg="stream",
                                       dur_s=round(
                                           time.monotonic() - t_disp, 6),
                                       worker=w.url)
                        return                # clean completion
                    except StreamEvacuated:
                        evacuated = True      # resume below, outside the cm
            except (httpx.TransportError, httpx.StreamError,
                    json.JSONDecodeError, ConnectionError, OSError) as exc:
                last_err = exc
                self._mark_down(w)
                if emitted:
                    # mid-stream death: prefer a snapshot resume whenever
                    # the failing worker can still answer ONE export (a
                    # drained-but-alive worker, a watchdog-tripped worker
                    # whose HTTP plane survives) — the re-prefill below
                    # stays the hard-death fallback
                    opened = self._open_snapshot_resume(w, rid, emitted,
                                                        span)
                    if opened is not None:
                        ok = yield from self._pump_snapshot_resume(
                            opened, emitted)
                        if ok:
                            return
                continue
            if evacuated:
                # graceful evacuation: the worker parked this stream's
                # snapshot. NOT circuit-broken — the pull needs its HTTP
                # plane, and its own /health 503 routes new traffic away.
                opened = self._open_snapshot_resume(w, rid, emitted, span)
                if opened is not None:
                    ok = yield from self._pump_snapshot_resume(opened,
                                                               emitted)
                    if ok:
                        return
                last_err = RuntimeError(
                    f"worker {w.url} evacuated mid-stream")
        raise RuntimeError(
            f"LLM request failed across {self.max_attempts} attempts: "
            f"{last_err}")

    def _chat_disagg(self, messages, max_tokens, temperature, top_p,
                     top_k, response_format, rid: str,
                     affinity_key: str = "") -> Iterator[str]:   # tpulint: hot-path
        """Two-phase disaggregated serving: prefill (KV export) on the
        least-loaded prefill worker, decode on the least-loaded decode
        replica. A failure in either phase circuit-breaks that worker and
        re-runs the route; resumes fold the emitted prefix into the next
        prefill (``continue_text``), so a decode-replica death re-prefills
        elsewhere and continues the stream seamlessly. If the
        disaggregated topology collapses mid-retry (all prefill or all
        decode workers down), the attempt falls back to the unified path
        with the same resume prefix.

        The router owns the ROOT span of the disaggregated trace
        (manually managed — this is a generator, a ``with`` block would
        leak the contextvar into the consumer between yields): its
        traceparent is injected into BOTH dispatches, so the workers'
        ``engine:kv_prefill`` / ``engine:kv_handoff`` spans join one
        trace, and the span carries the route's own attribution — payload
        bytes, page count, per-phase wall — directly pricing the HTTP
        base64 KV seam per request."""
        import httpx

        emitted: List[str] = []
        last_err: Exception = RuntimeError("no engine worker available")
        span = otel.start_span("router:chat_disagg",
                               attributes={"request_id": rid})
        try:
            for attempt in range(self.max_attempts):
                if attempt and not self._policy.before_retry(attempt):
                    break   # budget spent or deadline unmeetable: shed
                if not self._has_disagg():
                    # topology collapsed mid-retry: the unified path
                    # carries the already-yielded prefix so the stream
                    # RESUMES, never restarts (no duplicated text at the
                    # consumer) — same rid, same trace
                    if span is not None:
                        span.set_attribute("router.fell_back_unified", True)
                    yield from self._chat_unified(messages, max_tokens,
                                                  temperature, top_p, top_k,
                                                  response_format,
                                                  emitted=emitted,
                                                  rid=rid, span=span,
                                                  attempt_base=attempt,
                                                  affinity_key=affinity_key)
                    return
                # affinity applies to BOTH phases: today the prefix cache
                # that skips recompute lives on the PREFILL worker (decode
                # imports KV into fresh pages), so a returning
                # conversation must land on the prefill worker holding its
                # history; the decode pin (below) keeps the conversation's
                # decode-side placement stable for the item-3 KV tier
                pw = self._pick(("prefill",), affinity_key=affinity_key,
                                rid=rid)
                if pw is None:
                    last_err = RuntimeError("no prefill worker up")
                    continue
                if attempt:
                    # a retry bills once it reaches a worker (see the
                    # unified loop) — attempts that found no one up don't
                    usage_mod.USAGE.bill_retry()
                payload = self._payload(messages, max_tokens, temperature,
                                        top_p, top_k, response_format,
                                        emitted, stream=False)
                if emitted:
                    # a disaggregated resume re-prefills the emitted
                    # prefix through the prefill phase — the recompute
                    # recovery mode (snapshot resumes count separately)
                    self._note_reprefill_resume()
                t_pf = time.monotonic()
                try:
                    if chaos_mod.CHAOS.enabled:
                        chaos_mod.CHAOS.http_fault("router.prefill")
                    pf_headers = self._headers(rid, span)
                    if self.kv_wire != "json":
                        # negotiate the binary zero-copy frame; an old
                        # prefill worker ignores the Accept and answers
                        # JSON base64 — both decode below
                        pf_headers["Accept"] = \
                            kv_wire_mod.KV_FRAMES_CONTENT_TYPE
                    resp = httpx.post(f"{pw.url}/v1/kv/prefill",
                                      json=payload,
                                      headers=pf_headers,
                                      timeout=http_timeout(120.0))
                    if resp.status_code >= 500:
                        raise httpx.TransportError(
                            f"HTTP {resp.status_code}")
                    resp.raise_for_status()   # 4xx: deterministic — raise
                    self._learn_prefix(affinity_key,
                                       resp.headers.get("x-kv-prefix", ""))
                    handoff_body = resp.content
                    handoff_binary = kv_wire_mod.is_kv_frames(
                        handoff_body,
                        resp.headers.get("content-type", ""))
                    # scalar metadata for span attrs only: the binary peek
                    # reads the header, never the segment megabytes; the
                    # JSON compat body is parsed ONLY when tracing wants
                    # kv.pages — the body itself is relayed verbatim, and
                    # a per-request multi-MB json parse for an attribute
                    # nobody records would be pure overhead
                    handoff_meta: Dict = {}
                    if handoff_binary:
                        handoff_meta = kv_wire_mod.peek_kv_frames_meta(
                            handoff_body)
                    elif span is not None:
                        handoff_meta = resp.json()
                    if self.kv_wire == "binary" and not handoff_binary:
                        # the operator asserted a homogeneous binary pool;
                        # an old prefill worker answering JSON violates it
                        # DETERMINISTICALLY — fail the request loudly now
                        # instead of burning max_attempts prefills
                        raise RuntimeError(
                            f"kv_wire=binary but prefill worker {pw.url} "
                            f"answered the JSON wire (no frame support)")
                except kv_wire_mod.KVWireError as exc:
                    # a frame the prefill worker produced but this router
                    # cannot parse is payload-suspect, not worker-death:
                    # count it with the handoff rejects and re-run the
                    # route for a fresh prefill
                    REGISTRY.counter("router_handoff_rejects_total").inc()
                    logger.warning("unparsable kv frame from %s; "
                                   "re-prefilling: %s", pw.url, exc)
                    last_err = exc
                    continue
                except (httpx.TransportError, httpx.StreamError,
                        json.JSONDecodeError, ConnectionError,
                        OSError) as exc:
                    last_err = exc
                    self._mark_down(pw)
                    continue
                # the KV transport's weight as a metric TREND, not just a
                # span attribute: what actually crossed the wire (binary
                # frame or JSON base64), priced per request on /metrics
                # (bench.py reports both wire forms in the disagg round)
                REGISTRY.histogram("router_kv_payload_bytes").observe(
                    float(len(handoff_body)))
                if TRACE.enabled:
                    TRACE.emit("router_leg", rid=rid, leg="prefill",
                               dur_s=round(time.monotonic() - t_pf, 6),
                               worker=pw.url,
                               bytes=len(handoff_body))
                if span is not None:
                    span.set_attribute("router.attempts", attempt + 1)
                    span.set_attribute("router.prefill_worker", pw.url)
                    span.set_attribute("router.prefill_s",
                                       round(time.monotonic() - t_pf, 6))
                    span.set_attribute("kv.payload_bytes",
                                       len(handoff_body))
                    span.set_attribute("kv.wire", "binary" if handoff_binary
                                       else "json-b64")
                    span.set_attribute(
                        "kv.pages", int(handoff_meta.get("n_pages", 0) or 0))
                # prefix-affinity stickiness: the conversation's leading-
                # block key pins a returning chat to the decode replica
                # whose prefix cache already holds its history (within the
                # least-loaded slack — _pick documents the trade)
                dw = self._pick(("decode",), affinity_key=affinity_key,
                                rid=rid)
                if dw is None:
                    last_err = RuntimeError("no decode worker up")
                    continue
                cands = [dw]
                if self.hedge_s > 0:
                    # hedged handoff: arm the second-least-loaded replica;
                    # it is dispatched only if the primary hasn't opened
                    # the stream within hedge_s (resilience.hedged_call).
                    # charge=False: arming is not dispatching — the leg is
                    # charged by _open_handoff iff it actually launches
                    dw2 = self._pick(("decode",), exclude=(dw.url,),
                                     charge=False, rid=rid)
                    if dw2 is not None:
                        cands.append(dw2)
                t0 = time.monotonic()
                winner = dw
                try:
                    cm, dresp, winner = self._open_handoff(
                        cands, handoff_body, handoff_binary, rid, span)
                except httpx.HTTPStatusError as exc:
                    if exc.response is not None \
                            and exc.response.status_code in (400, 409):
                        # the decode pool REFUSED the payload — 409 from
                        # geometry/dtype validation, 400 from binary-frame
                        # validation (truncation, crc32): the payload
                        # itself is suspect, the worker is fine. Re-run
                        # the route for a FRESH prefill instead of
                        # circuit-breaking a healthy replica.
                        REGISTRY.counter("router_handoff_rejects_total").inc()
                        logger.warning("decode pool rejected handoff "
                                       "payload (%d); re-prefilling: %s",
                                       exc.response.status_code, exc)
                        last_err = exc
                        continue
                    raise
                except kv_wire_mod.KVWireError as exc:
                    # transcoding for a JSON-only replica found the frame
                    # corrupt: same payload-suspect contract as the 400
                    REGISTRY.counter("router_handoff_rejects_total").inc()
                    logger.warning("kv frame failed transcode validation; "
                                   "re-prefilling: %s", exc)
                    last_err = exc
                    continue
                except (httpx.TransportError, httpx.StreamError,
                        json.JSONDecodeError, ConnectionError,
                        OSError) as exc:
                    last_err = exc
                    if len(cands) == 1:
                        # hedged opens mark their own failed legs via the
                        # on_error callback (incl. a loser masked by the
                        # winner); only the plain single-leg open is
                        # circuit-broken here
                        self._mark_down(dw)
                    continue
                evacuated = False
                died_mid_stream = False
                try:
                    # handoff latency: prefill payload in hand → decode
                    # stream open (admission imported the pages)
                    handoff_open = time.monotonic() - t0
                    REGISTRY.histogram("router_handoff_s").observe(
                        handoff_open)
                    t_stream = time.monotonic()
                    if TRACE.enabled:
                        TRACE.emit("router_leg", rid=rid,
                                   leg="handoff_open",
                                   dur_s=round(handoff_open, 6),
                                   worker=winner.url,
                                   hedged=len(cands) > 1,
                                   hedge_loser=(dw.url if winner is not dw
                                                else ""))
                    if span is not None:
                        span.set_attribute("router.decode_worker",
                                           winner.url)
                        span.set_attribute("router.handoff_open_s",
                                           round(handoff_open, 6))
                        if winner is not dw:
                            span.set_attribute("router.hedged", True)
                    if winner is not dw:
                        # the primary LOST its own hedge: it is slow, not
                        # down, so it is not circuit-broken here — but a
                        # chronically losing replica is an operator
                        # signal (its own watchdog owns detecting a
                        # genuinely wedged stream path via /health 503)
                        REGISTRY.counter("router_hedge_losses_total",
                                         labels={"worker": dw.url}).inc()
                    try:
                        yield from self._pump_sse(dresp, emitted)
                        if TRACE.enabled:
                            TRACE.emit("router_leg", rid=rid, leg="stream",
                                       dur_s=round(
                                           time.monotonic() - t_stream, 6),
                                       worker=winner.url)
                        return                # clean completion
                    except StreamEvacuated:
                        evacuated = True      # resume below, outside cm
                except (httpx.TransportError, httpx.StreamError,
                        json.JSONDecodeError, ConnectionError,
                        OSError) as exc:
                    last_err = exc
                    self._mark_down(winner)
                    died_mid_stream = bool(emitted)
                finally:
                    cm.__exit__(None, None, None)
                if evacuated or died_mid_stream:
                    # live migration: pull the decode replica's mid-decode
                    # snapshot and resume token-identically on a peer
                    # (evacuated = graceful rotation, worker stays
                    # un-broken; mid-stream death = best-effort pull, the
                    # re-prefill route below is the hard-death fallback)
                    opened = self._open_snapshot_resume(winner, rid,
                                                        emitted, span)
                    if opened is not None:
                        ok = yield from self._pump_snapshot_resume(
                            opened, emitted)
                        if ok:
                            return
                    if evacuated:
                        last_err = RuntimeError(
                            f"worker {winner.url} evacuated mid-stream")
            raise RuntimeError(
                f"LLM request failed across {self.max_attempts} attempts: "
                f"{last_err}")
        except Exception:
            # any failure leaving this route — attempt exhaustion, the
            # unified fallback exhausting ITS attempts, a mid-stream pump
            # error — must export an ERROR span, or trace-status filters
            # miss exactly the requests worth looking at. (GeneratorExit —
            # the consumer abandoning the stream — is not a server error
            # and passes through untouched.)
            if span is not None:
                span.status = "ERROR"
            raise
        finally:
            otel.end_span(span)

    def _open_handoff(self, cands: List[_Worker], handoff_body: bytes,
                      handoff_binary: bool, rid: str, span):
        """Open a /v1/kv/handoff SSE stream on one of ``cands`` and return
        ``(context_manager, response, worker)`` with the response already
        status-checked. One candidate = a plain open; two = a hedged open
        (resilience.hedged_call): the secondary launches only if the
        primary hasn't opened within ``hedge_s``, first success streams,
        the straggler's stream is closed the moment it lands.

        The payload relays in whatever wire form the PREFILL worker
        produced — the router never re-parses the megabytes. The one
        exception is a binary frame bound for a replica that never
        advertised frame support (``kv_wire`` on /health): under
        ``kv_wire="auto"`` it is transcoded to the JSON base64 compat
        form once, shared across hedge legs; under ``"binary"`` the
        mismatch raises (the operator asserted a homogeneous pool)."""
        import httpx

        # headers are built on the CALLER's thread: hedged legs run on
        # fresh threads with an empty contextvars context, where the SLO
        # admission (slo_mod.outbound_headers) would silently resolve to
        # nothing — and dropping the deadline header would disable
        # deadline accounting on every hedged-mode request
        headers = self._headers(rid, span)
        # tenant captured here for the same reason: the hedge-billing
        # call below runs on the hedge thread's empty context
        tenant = usage_mod.current_tenant()

        if handoff_binary and self.kv_wire == "binary" \
                and not all(w.kv_binary for w in cands):
            # the operator asserted a homogeneous binary pool: a JSON-only
            # replica in the candidate set is a deterministic topology
            # violation — RuntimeError propagates (no payload-suspect
            # retry loop, no silent transcode)
            raise RuntimeError(
                "kv_wire=binary but a selected decode replica never "
                "advertised frame support — transcode refused")

        transcoded: Dict[str, bytes] = {}
        transcode_lock = tracked_lock("failover.transcode_lock")

        def body_for(w: _Worker):
            if not handoff_binary or w.kv_binary:
                return (handoff_body,
                        kv_wire_mod.KV_FRAMES_CONTENT_TYPE
                        if handoff_binary else "application/json")
            # LAZY transcode for a legacy replica, at the moment its leg
            # actually dispatches — a hedge candidate that never launches
            # must not cost a megabyte re-encode per request (validates
            # the frame on the way; KVWireError → payload-suspect retry)
            with transcode_lock:
                if "json" not in transcoded:
                    transcoded["json"] = json.dumps(
                        kv_wire_mod.transcode_to_json(
                            handoff_body)).encode("utf-8")
                    REGISTRY.counter("router_kv_transcodes_total").inc()
            return transcoded["json"], "application/json"

        def open_one(w: _Worker):
            if w is not cands[0]:
                self._charge(w, rid=rid)   # hedge leg launched: NOW it counts
                usage_mod.USAGE.bill_hedge(tenant or None)
            if chaos_mod.CHAOS.enabled:
                chaos_mod.CHAOS.http_fault("router.handoff")
            body, ctype = body_for(w)
            cm = httpx.stream("POST", f"{w.url}/v1/kv/handoff",
                              content=body,
                              headers={**headers, "Content-Type": ctype},
                              timeout=http_timeout(120.0))
            resp = cm.__enter__()
            try:
                if resp.status_code >= 500:
                    raise httpx.TransportError(f"HTTP {resp.status_code}")
                resp.raise_for_status()   # 4xx: deterministic — raise
            except BaseException:
                cm.__exit__(None, None, None)
                raise
            return (cm, resp, w)

        if len(cands) == 1:
            return open_one(cands[0])

        def leg_failed(ix: int, exc: Exception) -> None:
            # a losing leg's TRANSPORT failure must still circuit-break
            # that worker — the winner masking it would leave a hard-down
            # primary in rotation (lowest score, re-picked every request).
            # A 409 stays un-broken (the payload is suspect, not the
            # worker), and so does a lazy-transcode KVWireError (a corrupt
            # FRAME failing validation on this leg's thread says nothing
            # about the replica it was bound for).
            if not isinstance(exc, (httpx.HTTPStatusError,
                                    kv_wire_mod.KVWireError)):
                self._mark_down(cands[ix])

        result, _ix = resilience.hedged_call(
            [lambda w=w: open_one(w) for w in cands],
            hedge_after_s=self._hedge_delay_s(cands[0]),
            cancel=lambda r: r[0].__exit__(None, None, None),
            on_error=leg_failed,
            name="router_handoff")
        return result

    def _hedge_delay_s(self, primary: _Worker) -> float:
        """Per-dispatch hedge trigger for ``primary``. Static
        ``hedge_s`` unless the QoS plane is armed; with APP_QOS=fair the
        delay is cost-modeled (engine/qos.py hedge_delay): scaled by the
        primary's advertised queue depth over its slot capacity — known
        load is not an anomaly — and floored at the router's own measured
        typical handoff-open time, so the trigger adapts to what "slow"
        actually means on this pool instead of a hand-tuned constant."""
        if not self._qos_hedge or self.hedge_s <= 0:
            return self.hedge_s
        open_h = REGISTRY.histogram("router_handoff_s")
        typical = open_h.percentile(50.0) if open_h.count >= 8 else None
        delay = resilience.hedge_delay(self.hedge_s, primary.queue_depth,
                                       primary.batch or 8,
                                       service_s=typical)
        REGISTRY.histogram("router_hedge_delay_s").observe(delay)
        return delay

    # ------------------------------------------- live-migration resume

    def _fetch_snapshot(self, w: _Worker, rid: str):
        """One pull of a failing/draining worker's mid-decode snapshot
        (GET /v1/kv/evacuation/<rid>). Returns ``(body, is_binary)`` or
        None — a dead worker, a 404 (never snapshotable / already
        pulled), or snapshot_resume=off all mean 'use the re-prefill
        fallback'. Deliberately ONE attempt with a short timeout: this
        sits on the recovery path of a stream a client is waiting on."""
        if not self.snapshot_resume:
            return None
        import httpx
        try:
            resp = httpx.get(
                f"{w.url}/v1/kv/evacuation/{rid}",
                headers={"Accept": kv_wire_mod.KV_FRAMES_CONTENT_TYPE,
                         "X-Request-Id": rid},
                timeout=http_timeout(20.0))
            if resp.status_code != 200:
                logger.info("no snapshot for %s on %s (HTTP %d); "
                            "re-prefilling", rid, w.url, resp.status_code)
                return None
            body = resp.content
            return body, kv_wire_mod.is_kv_frames(
                body, resp.headers.get("content-type", ""))
        except Exception as exc:   # tpulint: disable=except-swallow -- a dead worker answering nothing IS the expected fallback signal; the caller re-prefills
            logger.info("snapshot pull from %s failed (%s); re-prefilling",
                        w.url, exc)
            return None

    def _open_snapshot_resume(self, w: _Worker, rid: str,
                              emitted: List[str], span):
        """Pull ``w``'s snapshot for ``rid`` and open its continuation on
        a peer replica's /v1/kv/handoff. Returns ``(cm, resp, peer)``
        (stream already status-checked) or None — the caller then falls
        back to re-prefill. ``X-Resume-Chars`` tells the resume worker
        how much text this router actually delivered, so a pull that
        races the exporting worker's last emissions re-streams the gap
        instead of dropping it."""
        import httpx

        snap = self._fetch_snapshot(w, rid)
        if snap is None:
            return None
        body, binary = snap
        peer = self._pick(("unified", "decode", ""), exclude=(w.url,))
        if peer is None:
            logger.warning("snapshot for %s pulled but no peer is up; "
                           "re-prefilling", rid)
            return None
        ctype = (kv_wire_mod.KV_FRAMES_CONTENT_TYPE if binary
                 else "application/json")
        if binary and not peer.kv_binary:
            # legacy replica: one transcode to the JSON compat wire
            try:
                body = json.dumps(
                    kv_wire_mod.transcode_to_json(body)).encode("utf-8")
                ctype = "application/json"
                REGISTRY.counter("router_kv_transcodes_total").inc()
            except kv_wire_mod.KVWireError as exc:
                logger.warning("snapshot frame failed transcode (%s); "
                               "re-prefilling", exc)
                return None
        headers = self._headers(rid, span)
        headers["X-Resume-Chars"] = str(sum(len(s) for s in emitted))
        headers["Content-Type"] = ctype
        cm = httpx.stream("POST", f"{peer.url}/v1/kv/handoff",
                          content=body, headers=headers,
                          timeout=http_timeout(120.0))
        try:
            resp = cm.__enter__()
        except (httpx.TransportError, ConnectionError, OSError) as exc:
            logger.warning("snapshot resume open on %s failed: %s",
                           peer.url, exc)
            self._mark_down(peer)
            return None
        try:
            if resp.status_code >= 500:
                raise httpx.TransportError(f"HTTP {resp.status_code}")
            resp.raise_for_status()
        except Exception as exc:   # tpulint: disable=except-swallow -- any refusal (409 geometry, 400 frame, transport) downgrades to the re-prefill fallback; the snapshot is consumed either way
            cm.__exit__(None, None, None)
            logger.warning("snapshot resume on %s refused (%s); "
                           "re-prefilling", peer.url, exc)
            return None
        REGISTRY.counter("router_resume_total",
                         labels={"mode": "snapshot"}).inc()
        logger.info("resuming %s from snapshot on %s (%d chars already "
                    "delivered)", rid, peer.url,
                    sum(len(s) for s in emitted))
        if span is not None:
            span.set_attribute("router.snapshot_resume", peer.url)
        return cm, resp, peer

    def _pump_snapshot_resume(self, opened, emitted: List[str]):
        """Drain an opened snapshot-resume stream. Generator; its RETURN
        value (via ``yield from``) is True on clean completion — anything
        else sends the caller back to its retry loop with ``emitted``
        grown by whatever arrived (the re-prefill fallback resumes from
        there, so text is never dropped or duplicated)."""
        import httpx

        cm, resp, peer = opened
        try:
            yield from self._pump_sse(resp, emitted)
            return True
        except StreamEvacuated:
            # the resume target is itself rotating out: the snapshot is
            # consumed, so the retry loop's re-prefill (or a fresh
            # snapshot pull from THIS peer) takes over
            return False
        except (httpx.TransportError, httpx.StreamError,
                json.JSONDecodeError, ConnectionError, OSError):
            self._mark_down(peer)
            return False
        finally:
            cm.__exit__(None, None, None)

    def _note_reprefill_resume(self) -> None:
        """Count a resume dispatch that went the re-prefill way — the
        recompute-vs-transfer recovery split (`router_resume_total{mode}`)
        the live-migration plane is measured by."""
        REGISTRY.counter("router_resume_total",
                         labels={"mode": "reprefill"}).inc()

    def chat_tools(self, messages: Sequence[Dict], tools: Sequence[Dict],
                   tool_choice="auto", **sampling) -> Dict:
        """Non-streamed tool turn: whole-request retry across the pool's
        decode-capable workers (tool turns buffer server-side, so they
        stay on the single-worker path regardless of topology)."""
        import httpx

        payload = {"model": self.model, "messages": list(messages),
                   "stream": False, **sampling}
        if tools:
            payload["tools"] = list(tools)
            payload["tool_choice"] = tool_choice
        rid = uuid.uuid4().hex[:12]
        self._policy.note_request()
        last_err: Exception = RuntimeError("no engine worker available")
        for attempt in range(self.max_attempts):
            if attempt and not self._policy.before_retry(attempt):
                break   # budget spent or deadline unmeetable: shed
            w = self._pick(("unified", "decode", ""))
            if w is None:
                last_err = RuntimeError("no unified/decode worker up")
                continue
            if attempt:
                usage_mod.USAGE.bill_retry()
            try:
                if chaos_mod.CHAOS.enabled:
                    chaos_mod.CHAOS.http_fault("router.tools")
                resp = httpx.post(f"{w.url}/v1/chat/completions",
                                  json=payload,
                                  headers=self._headers(rid),
                                  timeout=http_timeout(120.0))
                if resp.status_code >= 500:
                    raise httpx.TransportError(f"HTTP {resp.status_code}")
                resp.raise_for_status()       # 4xx: deterministic — raise
                return resp.json()["choices"][0]["message"]
            except (httpx.TransportError, httpx.StreamError,
                    json.JSONDecodeError, ConnectionError, OSError) as exc:
                last_err = exc
                self._mark_down(w)
        raise RuntimeError(f"tool request failed across the pool: {last_err}")
