"""CLI entry: run the chain server.

    python -m generativeaiexamples_tpu.server [--example basic_rag] [--port 8081] [--tiny]

One server binary, example selected by flag or ``EXAMPLE`` env — compose
parity with the reference's one-image-many-examples pattern
(ref: chain_server/Dockerfile:42-48, EXAMPLE_PATH).
"""

from __future__ import annotations

import argparse
import logging
import os


def main() -> None:
    from generativeaiexamples_tpu.core.debug import install as _debug_install
    _debug_install()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--example", default=None, help="chain to serve")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8081)
    parser.add_argument("--tiny", action="store_true",
                        help="force the tiny deterministic model (tests/dev)")
    args = parser.parse_args()
    logging.basicConfig(level=os.environ.get("LOGLEVEL", "INFO").upper())

    if args.tiny:
        os.environ.pop("APP_ENGINE_CHECKPOINT_DIR", None)

    from generativeaiexamples_tpu.server.api import run_server
    from generativeaiexamples_tpu.server.registry import get_example

    example = get_example(args.example)
    run_server(example, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
