"""Unified resilience policy: jittered backoff, retry budgets, deadline-aware
retry cutoff, and hedged dispatch — ONE copy for every retry loop.

Before this module, retry/timeout/backoff discipline was scattered: the
failover router retried back-to-back with no delay, the supervisor's
restart backoff was ``min(2**restarts, 60)`` with no jitter (a crashed
stack restarts as a synchronized herd), and the event agent slept a linear
``retry_delay_s * attempt``. Each was individually defensible and jointly
incoherent — and none of them knew about the PR 4 SLO plane, so a request
whose deadline had already passed would still burn pool capacity on
retries nobody could use.

Policy pieces (each independently usable; :class:`ResiliencePolicy`
composes them for the router's loops):

  * **Full-jitter exponential backoff** (:func:`full_jitter_backoff`):
    ``uniform(0, min(cap, base * 2^attempt))`` — the AWS-architecture
    result: full jitter decorrelates a retry (or restart) herd better
    than equal or decorrelated jitter at the same mean delay.
  * **Retry budget** (:class:`RetryBudget`): a token bucket refilled by
    *first attempts* (``ratio`` tokens each, capped at ``burst``) and
    spent by retries. Under a sustained outage total retries across the
    pool are bounded by ``ratio × requests + burst`` — a retry storm can
    amplify an outage by at most ``1 + ratio``, instead of
    ``max_attempts``× (the classic metastable-failure amplifier).
  * **Deadline-aware cutoff**: a retry that cannot finish before the
    request's SLO deadline (observability/slo.py admission context) is
    shed, not attempted — the capacity goes to requests that can still
    meet their objective. ``retries_denied_total{pool,reason}`` counts
    every budget/deadline/attempt-cap denial.
  * **Hedged dispatch** (:func:`hedged_call`): launch the secondary when
    the primary hasn't produced a result within the hedge delay; first
    success wins, losers are handed to ``cancel``. The router uses this
    for KV-handoff opens against the second-least-loaded decode replica
    (``APP_ROUTER_HEDGE_S``) — tail-latency insurance priced at one
    duplicate dispatch, never a correctness mechanism.

Everything takes injectable ``rng``/``sleep``/``clock`` so tests pin exact
delays; metrics ride the shared REGISTRY.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import random
import threading
import time
from typing import Any, Callable, Optional, Sequence, Tuple

from generativeaiexamples_tpu.core.metrics import REGISTRY

logger = logging.getLogger(__name__)

_RNG = random.Random()


def full_jitter_backoff(attempt: int, base_s: float = 0.5,
                        cap_s: float = 60.0,
                        rng: Optional[random.Random] = None) -> float:
    """Delay before retry/restart number ``attempt`` (1-based): uniform in
    ``[0, min(cap_s, base_s * 2^(attempt-1))]`` — full jitter, so N
    processes backing off together spread instead of thundering in sync
    (the supervisor's restart herd, the router's retry burst)."""
    ceiling = min(float(cap_s), float(base_s) * (2.0 ** max(0, attempt - 1)))
    return (rng or _RNG).uniform(0.0, ceiling)


class RetryBudget:
    """Token-bucket retry budget for one pool.

    ``note_request()`` (every FIRST attempt) deposits ``ratio`` tokens,
    capped at ``burst``; ``try_spend()`` (every retry) consumes one token
    or refuses. The bucket starts full so cold-start blips retry freely;
    under a sustained outage the spend rate is bounded by the deposit
    rate — amplification ≤ 1 + ratio.
    """

    def __init__(self, name: str = "pool", ratio: float = 0.2,
                 burst: float = 10.0) -> None:
        self.name = name
        self.ratio = float(ratio)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def note_request(self) -> None:
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
        REGISTRY.counter("retry_budget_exhausted_total",
                         labels={"pool": self.name}).inc()
        return False


class ResiliencePolicy:
    """Retry gate for one pool's dispatch loops: attempt cap + retry budget
    + deadline cutoff + jittered backoff, in one call.

    Usage (the router's shape)::

        for attempt in range(policy.max_attempts):
            if attempt and not policy.before_retry(attempt):
                break                      # denied: budget/deadline/cap
            try:
                ... dispatch ...
                return
            except TransportError:
                continue

    ``before_retry`` returns False (recording why) instead of raising so
    the caller's existing last-error reporting stays intact.
    """

    def __init__(self, name: str, max_attempts: int = 4,
                 base_s: float = 0.05, cap_s: float = 2.0,
                 budget: Optional[RetryBudget] = None,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.name = name
        self.max_attempts = max(1, int(max_attempts))
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.budget = budget
        self._rng = rng or _RNG
        self._sleep = sleep

    def note_request(self) -> None:
        """Call once per logical request (first attempt): feeds the retry
        budget's token deposit."""
        if self.budget is not None:
            self.budget.note_request()

    def backoff_s(self, attempt: int) -> float:
        return full_jitter_backoff(attempt, self.base_s, self.cap_s,
                                   self._rng)

    def _deny(self, reason: str) -> bool:
        REGISTRY.counter("retries_denied_total",
                         labels={"pool": self.name, "reason": reason}).inc()
        logger.info("retry denied for pool %s: %s", self.name, reason)
        return False

    def before_retry(self, attempt: int,
                     deadline_s: Optional[float] = None) -> bool:
        """Gate retry number ``attempt`` (1-based; attempt 0 is the first
        try and is never gated). Checks the attempt cap, the pool's retry
        budget, and the request's remaining SLO deadline (the ambient
        admission context when ``deadline_s`` is None — a request past its
        deadline is shed, not retried); on approval sleeps the jittered
        backoff and returns True."""
        if attempt >= self.max_attempts:
            return self._deny("attempts")
        delay = self.backoff_s(attempt)
        if deadline_s is None:
            from generativeaiexamples_tpu.observability import slo as slo_mod
            deadline_s = slo_mod.remaining_s()
        if deadline_s is not None and deadline_s <= delay:
            # the backoff alone would eat the remaining budget: nothing
            # this retry produces can arrive before the deadline
            return self._deny("deadline")
        if self.budget is not None and not self.budget.try_spend():
            return self._deny("budget")
        REGISTRY.counter("retry_attempts_total",
                         labels={"pool": self.name}).inc()
        if delay > 0:
            self._sleep(delay)
        return True


def hedge_delay(base_s: float, queue_depth: int, batch: int,
                service_s: Optional[float] = None) -> float:
    """Cost-modeled hedge trigger (the QoS plane's router half —
    engine/qos.py re-exports this; it lives HERE because the routing
    process must never import the jax-pulling engine package for 15
    lines of arithmetic): how long to give the primary replica before a
    duplicate leg launches.  A loaded worker opens late for a LEGITIMATE
    reason (its queue), so the static ``APP_ROUTER_HEDGE_S`` scales with
    the advertised queue depth normalized by slot capacity, floored at
    the expected service time when an estimate exists — hedging fires on
    anomaly, not on known load.  The runaway cap is 8x the base OR the
    service floor, whichever is larger: capping BELOW the floor would
    re-enable hedging on every legitimately-slow open, exactly the
    duplicate-dispatch storm the floor exists to prevent."""
    base_s = max(0.0, float(base_s))
    if base_s <= 0.0:
        return 0.0
    depth_scale = 1.0 + max(0, int(queue_depth)) / float(max(1, int(batch)))
    delay = base_s * depth_scale
    floor = float(service_s) if service_s is not None and service_s > 0 \
        else 0.0
    delay = max(delay, floor)
    return min(delay, max(base_s * 8.0, floor))


def hedged_call(fns: Sequence[Callable[[], Any]], hedge_after_s: float,
                cancel: Optional[Callable[[Any], None]] = None,
                on_error: Optional[Callable[[int, Exception], None]] = None,
                name: str = "hedge",
                clock: Callable[[], float] = time.monotonic
                ) -> Tuple[Any, int]:
    """Run ``fns[0]``; if it hasn't returned within ``hedge_after_s``,
    launch ``fns[1]`` (then ``fns[2]``…, one hedge step per delay window).
    Returns ``(result, index)`` of the first success; late results are
    passed to ``cancel`` (close the stream, release the connection). All
    failing → the last error re-raises.

    ``on_error(index, exc)`` fires for EVERY failing leg — including a
    loser whose error would otherwise be masked by the winner. Without
    it, a hedge winning against a hard-down primary would swallow the
    primary's failure and the caller could never circuit-break it.

    Threads are daemons: an abandoned straggler can only ever hold its own
    socket, and ``cancel`` reclaims it the moment it lands."""
    if not fns:
        raise ValueError("hedged_call needs at least one callable")
    results: "queue_mod.Queue" = queue_mod.Queue()

    def run(ix: int) -> None:
        try:
            results.put(("ok", ix, fns[ix]()))
        except Exception as exc:   # tpulint: disable=except-swallow -- the error is DELIVERED: it rides the result queue to the caller, which re-raises the last one
            results.put(("err", ix, exc))

    launched = 1
    # tpulint: disable=daemon-shutdown -- hedge legs are call-scoped: the
    # result queue delivers every leg's outcome to THIS frame before it
    # returns (or the drainer below reaps stragglers); no join point
    # exists at process shutdown
    threading.Thread(target=run, args=(0,), daemon=True,
                     name=f"{name}-0").start()
    finished = 0
    last_err: Optional[Exception] = None
    winner: Optional[Tuple[Any, int]] = None
    while finished < launched:
        timeout = hedge_after_s if (launched < len(fns)
                                    and winner is None) else None
        try:
            kind, ix, value = results.get(timeout=timeout)
        except queue_mod.Empty:
            # hedge window expired with no result: launch the next leg
            REGISTRY.counter("hedges_total", labels={"pool": name}).inc()
            # tpulint: disable=daemon-shutdown -- call-scoped hedge leg (see above)
            threading.Thread(target=run, args=(launched,), daemon=True,
                             name=f"{name}-{launched}").start()
            launched += 1
            continue
        finished += 1
        if kind == "ok":
            # first success wins — the loop exits here, so anything still
            # in flight lands on the drainer thread below, never back in
            # this loop
            winner = (value, ix)
            if finished < launched:
                # stragglers still in flight: reap them on a drainer
                # thread so the winner streams immediately
                remaining = launched - finished

                def drain(n: int) -> None:
                    for _ in range(n):
                        k, i, v = results.get()
                        try:
                            if k == "ok" and cancel is not None:
                                cancel(v)
                            elif k == "err" and on_error is not None:
                                on_error(i, v)
                        except Exception as exc:
                            logger.debug("hedge drain callback "
                                         "failed: %s", exc)

                # tpulint: disable=daemon-shutdown -- reaps in-flight legs
                # so the winner streams now; exits after `remaining` gets
                threading.Thread(target=drain, args=(remaining,),
                                 daemon=True,
                                 name=f"{name}-drain").start()
            break
        else:
            last_err = value
            if on_error is not None:
                try:
                    on_error(ix, value)
                except Exception as exc:
                    logger.debug("hedge on_error callback failed: %s", exc)
            if launched < len(fns) and winner is None:
                # a leg failing FAST is better information than the hedge
                # timer: move to the next leg immediately
                # tpulint: disable=daemon-shutdown -- call-scoped hedge leg (see above)
                threading.Thread(target=run, args=(launched,), daemon=True,
                                 name=f"{name}-{launched}").start()
                launched += 1
    if winner is not None:
        if winner[1] > 0:
            REGISTRY.counter("hedge_wins_total",
                             labels={"pool": name}).inc()
        return winner
    assert last_err is not None
    raise last_err
