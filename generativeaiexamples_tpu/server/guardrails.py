"""Guardrails — colang-style input rails + fact-check output rail.

Behavioral parity with the reference's guardrails integrations
(ref: RAG/notebooks/langchain/Using_NVIDIA_NIMs_with_NeMo_Guardrails/config/
flows.co — `define user <intent>` with example utterances, `define bot
<name>` with a canned reply, `define flow` linking them; NeMo matches user
turns to intents by embedding similarity over the examples.
ref: community/oran-chatbot-multimodal/guardrails/fact_check.py — an LLM
fact-check of the response against the retrieved context, verdict-prefixed
TRUE/FALSE). The NeMo-Guardrails runtime + hosted models are replaced by
the in-proc TPU embedder and LLM.

Composition:
  * `parse_colang` reads the reference's flow format (the subset those
    configs actually use) into intent → response rules;
  * `IntentRail` embeds every example once and matches incoming queries by
    cosine similarity — above threshold, the flow's canned bot reply is
    returned instead of running the chain;
  * `RegexRail` blocks/scrubs pattern matches (PII-style) on input or
    output;
  * `FactCheckRail` judges the generated answer against the retrieval
    context and prefixes the reference's TRUE/FALSE verdict marker;
  * `Guardrails` runs input rails before the chain and output rails after.

Everything is opt-in: a server without a rails config behaves exactly as
before (`from_config` returns None).
"""

from __future__ import annotations

import contextvars
import dataclasses
import logging
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# Retrieval context handoff: chains record the exact context they prompted
# with so the fact-check rail judges the answer against what the model
# actually saw, instead of re-running retrieval (which doubles embedder/
# store work and can fetch different chunks). Context-local, so concurrent
# requests on different threads never see each other's context.
_retrieved_context: contextvars.ContextVar[Optional[str]] = (
    contextvars.ContextVar("rails_retrieved_context", default=None))


def record_context(text: str) -> None:
    """Called by chains right after building their retrieval context."""
    _retrieved_context.set(text)


def take_context() -> Optional[str]:
    """Return and clear the recorded context (None if no chain recorded)."""
    text = _retrieved_context.get()
    _retrieved_context.set(None)
    return text


@dataclasses.dataclass
class Flow:
    intent: str
    examples: List[str]
    response: str


def parse_colang(text: str) -> List[Flow]:
    """Parse the `define user / define bot / define flow` subset the
    reference configs use (ref flows.co). Quoted lines under a `define
    user` are example utterances; under `define bot`, the canned reply;
    a `define flow` pairs `user X` with the following `bot Y` line."""
    users: Dict[str, List[str]] = {}
    bots: Dict[str, str] = {}
    pairs: List[Tuple[str, str]] = []
    mode: Optional[Tuple[str, str]] = None
    flow_user: Optional[str] = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("define user "):
            mode = ("user", line[len("define user "):].strip())
            users.setdefault(mode[1], [])
            flow_user = None
            continue
        if line.startswith("define bot "):
            mode = ("bot", line[len("define bot "):].strip())
            flow_user = None
            continue
        if line.startswith("define flow"):
            mode = ("flow", "")
            flow_user = None
            continue
        quoted = re.fullmatch(r'"(.*)"', line)
        if mode and mode[0] == "user" and quoted:
            users[mode[1]].append(quoted.group(1))
        elif mode and mode[0] == "bot" and quoted:
            bots[mode[1]] = (bots.get(mode[1], "") + " " +
                             quoted.group(1)).strip()
        elif mode and mode[0] == "flow":
            if line.startswith("user "):
                flow_user = line[len("user "):].strip()
            elif line.startswith("bot ") and flow_user:
                pairs.append((flow_user, line[len("bot "):].strip()))
                flow_user = None
    flows = []
    for user_intent, bot_name in pairs:
        if user_intent in users and bot_name in bots:
            flows.append(Flow(intent=user_intent,
                              examples=users[user_intent],
                              response=bots[bot_name]))
    return flows


class IntentRail:
    """Embedding-matched intent rail: the NeMo mechanism — every example
    utterance is embedded once; an incoming query whose nearest example
    clears ``threshold`` triggers the flow's canned response."""

    def __init__(self, flows: Sequence[Flow], embedder,
                 threshold: float = 0.75) -> None:
        self.flows = [f for f in flows if f.examples]
        self.embedder = embedder
        self.threshold = threshold
        examples = [e for f in self.flows for e in f.examples]
        self._owner = [i for i, f in enumerate(self.flows)
                       for _ in f.examples]
        if examples:
            m = np.asarray(embedder.embed_queries(examples))
            self._matrix = m / np.clip(
                np.linalg.norm(m, axis=1, keepdims=True), 1e-9, None)
        else:
            self._matrix = np.zeros((0, 1))

    def check(self, query: str) -> Optional[str]:
        if not len(self._matrix):
            return None
        q = np.asarray(self.embedder.embed_queries([query]))[0]
        q = q / max(float(np.linalg.norm(q)), 1e-9)
        sims = self._matrix @ q
        best = int(np.argmax(sims))
        if float(sims[best]) >= self.threshold:
            flow = self.flows[self._owner[best]]
            logger.info("input rail %r triggered (sim %.2f)",
                        flow.intent, float(sims[best]))
            return flow.response
        return None


class RegexRail:
    """Pattern rail: ``block`` returns the refusal on match (input rails);
    ``scrub`` replaces matches with the mask (output rails)."""

    def __init__(self, patterns: Sequence[str], refusal: str = "",
                 mask: str = "[redacted]") -> None:
        self._res = [re.compile(p, re.IGNORECASE) for p in patterns]
        self.refusal = refusal
        self.mask = mask

    def check(self, text: str) -> Optional[str]:
        for rx in self._res:
            if rx.search(text):
                return self.refusal or "I can't help with that request."
        return None

    def scrub(self, text: str) -> str:
        for rx in self._res:
            text = rx.sub(self.mask, text)
        return text


FACT_CHECK_SYS = """\
Your task is to fact-check a response from a language model. You are given
the context documents as [[CONTEXT]], the user's question as [[QUESTION]],
and the model's response as [[RESPONSE]]. Verify each claim in the response
strictly against the context — no external knowledge. Reply starting with
TRUE if the response is entirely supported by the context, or FALSE if any
part is not, followed by a one-sentence justification."""


class FactCheckRail:
    """Output rail: LLM fact-check of the answer against the retrieval
    context (ref fact_check.py); a FALSE verdict prepends a visible
    warning rather than silently passing the answer through."""

    WARNING = ("[guardrails] fact-check could not verify this answer "
               "against the retrieved documents:\n")

    def __init__(self, llm) -> None:
        self.llm = llm

    def check(self, answer: str, context: str, query: str) -> str:
        if not context.strip():
            return answer
        verdict = "".join(self.llm.chat(
            [{"role": "system", "content": FACT_CHECK_SYS},
             {"role": "user",
              "content": f"[[CONTEXT]]\n{context}\n\n[[QUESTION]]\n{query}"
                         f"\n\n[[RESPONSE]]\n{answer}"}],
            max_tokens=128, temperature=0.0)).strip()
        if verdict.upper().startswith("FALSE"):
            logger.warning("fact-check failed: %s", verdict[:120])
            return self.WARNING + answer
        return answer


class Guardrails:
    """Runs input rails before the chain and output rails after it."""

    def __init__(self, input_rails: Sequence = (),
                 output_scrub: Optional[RegexRail] = None,
                 fact_check: Optional[FactCheckRail] = None) -> None:
        self.input_rails = list(input_rails)
        self.output_scrub = output_scrub
        self.fact_check = fact_check

    @property
    def has_output_rails(self) -> bool:
        return self.fact_check is not None or self.output_scrub is not None

    def check_input(self, query: str) -> Optional[str]:
        """A canned refusal/response, or None to proceed to the chain."""
        for rail in self.input_rails:
            hit = rail.check(query)
            if hit is not None:
                return hit
        return None

    def check_output(self, answer: str, context: str = "",
                     query: str = "") -> str:
        if self.fact_check is not None:
            answer = self.fact_check.check(answer, context, query)
        if self.output_scrub is not None:
            answer = self.output_scrub.scrub(answer)
        return answer


def from_config(path: str, embedder, llm,
                threshold: float = 0.75,
                enable_fact_check: bool = False,
                scrub_patterns: Sequence[str] = ()) -> Optional[Guardrails]:
    """Build Guardrails from a flows.co file; None when no path is set
    (rails are strictly opt-in). ``enable_fact_check`` /
    ``scrub_patterns`` activate the output rails (the server reads them
    from APP_GUARDRAILS_FACT_CHECK / APP_GUARDRAILS_SCRUB)."""
    if not path:
        return None
    with open(path, "r", encoding="utf-8") as fh:
        flows = parse_colang(fh.read())
    if not flows:
        logger.warning("guardrails config %s defines no usable flows", path)
    rails = Guardrails(
        input_rails=[IntentRail(flows, embedder, threshold=threshold)],
        output_scrub=(RegexRail(list(scrub_patterns)) if scrub_patterns
                      else None),
        fact_check=FactCheckRail(llm) if enable_fact_check else None)
    logger.info("guardrails active: %d flows from %s (fact_check=%s, "
                "scrub=%d patterns)", len(flows), path, enable_fact_check,
                len(scrub_patterns))
    return rails
