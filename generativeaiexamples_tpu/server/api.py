"""Chain server REST + SSE API.

Endpoint-for-endpoint parity with the reference chain server
(ref: RAG/src/chain_server/server.py — /health:249, /generate:313,
/search:418 (as "/search" POST:407), /documents GET:441 POST:270 DELETE:467),
including:

  * request sanitization with bleach on user-controlled strings
    (ref server.py:68-80, 120-137);
  * the SSE chunk contract: ``data: {ChainResponse}\n\n`` frames with
    id/choices/message/finish_reason, closed by a finish chunk and [DONE]
    (ref ChainResponse server.py:148-170, response_generator:350-376);
  * generation error → canned SSE message instead of a broken stream
    (ref Milvus error path server.py:380-392);
  * max_tokens capped at 1024, message length capped
    (ref server.py:61-66, 104-110).

Built on aiohttp; generation runs on an executor thread because chains yield
from the blocking scheduler queue.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
import uuid
from typing import Any, Dict, Iterator, Optional

import bleach
from aiohttp import web

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.core.tracing import instrumentation_wrapper
from generativeaiexamples_tpu.observability import otel
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability import usage as usage_mod
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server import guardrails as guardrails_mod
from generativeaiexamples_tpu.server.common import (
    MAX_TOKENS_CAP, StreamDrain, add_debug_routes, health_handler,
    metrics_handler, parse_stop,
)

logger = logging.getLogger(__name__)

MAX_CONTENT_CHARS = 131072   # ref server.py:61-66
UPLOAD_DIR = os.environ.get("UPLOAD_DIR", "/tmp/gaie-tpu-uploads")


def _sanitize(text: str) -> str:
    return bleach.clean(text[:MAX_CONTENT_CHARS], strip=True)


def _chain_chunk(rid: str, content: str, finish_reason: Optional[str] = None) -> str:
    """ChainResponse-shaped SSE chunk (ref server.py:148-170)."""
    return json.dumps({
        "id": rid,
        "choices": [{"index": 0,
                     "message": {"role": "assistant", "content": content},
                     "finish_reason": finish_reason}],
    })


class ChainServer:
    def __init__(self, example: BaseExample, guardrails=None) -> None:
        self.example = example
        # opt-in colang-style rails (server/guardrails.py): built from
        # APP_GUARDRAILS_CONFIG when the caller didn't inject their own
        self.guardrails = guardrails
        if self.guardrails is None:
            import os

            rails_path = os.environ.get("APP_GUARDRAILS_CONFIG", "")
            if rails_path:
                from generativeaiexamples_tpu.server.guardrails import (
                    from_config)

                ctx = getattr(example, "ctx", None)
                if ctx is not None:
                    scrub = os.environ.get("APP_GUARDRAILS_SCRUB", "")
                    self.guardrails = from_config(
                        rails_path, ctx.embedder, ctx.llm,
                        enable_fact_check=os.environ.get(
                            "APP_GUARDRAILS_FACT_CHECK", "").lower()
                            in ("1", "true", "yes"),
                        scrub_patterns=[p for p in scrub.split("||") if p])
                else:
                    logger.warning(
                        "APP_GUARDRAILS_CONFIG set but the example has no "
                        "ctx (embedder/llm); rails disabled")
        self.app = web.Application(client_max_size=128 * 1024 * 1024)
        self.app.add_routes([
            web.get("/health", health_handler),
            web.get("/metrics", metrics_handler),
            web.post("/generate", self.generate),
            web.post("/search", self.search),
            web.get("/documents", self.get_documents),
            web.post("/documents", self.upload_document),
            web.delete("/documents", self.delete_document),
        ])
        # flight recorder + request timelines: the chain server usually
        # hosts the in-process engine scheduler, so its /debug surface
        # carries live engine data too
        add_debug_routes(self.app)

    # ------------------------------------------------------------ generate

    @instrumentation_wrapper
    async def generate(self, request: web.Request) -> web.StreamResponse:
        t_start = time.perf_counter()
        body = await request.json()
        messages = body.get("messages", [])
        if not isinstance(messages, list) or not messages:
            raise web.HTTPUnprocessableEntity(text=json.dumps(
                {"error": "messages must be a non-empty list"}))
        history = [{"role": str(m.get("role", "user")),
                    "content": _sanitize(str(m.get("content", "")))}
                   for m in messages]
        # last user message is the query (ref server.py:327-338)
        query = history.pop()["content"]
        use_kb = bool(body.get("use_knowledge_base", True))
        def setting(name, default, cast):
            value = body.get(name)
            return default if value is None else cast(value)

        settings: Dict[str, Any] = {
            "temperature": setting("temperature", 0.2, float),
            "top_p": setting("top_p", 0.7, float),
            "max_tokens": min(setting("max_tokens", 256, int), MAX_TOKENS_CAP),
        }
        # `stop` is part of the published chain-server contract (ref
        # docs/api_reference/openapi_schema.json:517-526): forwarded to the
        # chain (engines abort generation early) AND enforced again on the
        # outgoing stream, so chains that drop unknown settings still honor
        # the contract (held-back partial matches never reach the client)
        stop = parse_stop(body.get("stop"))
        if stop:
            settings["stop"] = stop
        REGISTRY.counter("generate_requests").inc()
        # X-Request-Id propagates the way the engine's does: honor the
        # caller's id (gateway retries / cross-log joins) or mint one; the
        # SSE chunk ids, the response header, stage-span attributes, and
        # any downstream SLO breach records all join on this one key
        rid = request.headers.get("X-Request-Id", "").strip() or uuid.uuid4().hex
        # SLO admission (observability/slo.py): class from header or body,
        # deadline stamped NOW — all downstream LLM calls propagate the
        # remaining budget. Unknown class names fail loudly (422, like
        # every other malformed field on this endpoint).
        try:
            slo_class, deadline_s = slo_mod.parse_inbound(
                request.headers,
                fallback_class=str(body.get("slo_class") or ""))
        except ValueError as exc:
            raise web.HTTPUnprocessableEntity(
                text=json.dumps({"error": str(exc)}))
        deadline_ms: Optional[float] = (
            None if deadline_s is None else deadline_s * 1000.0)
        # usage plane (observability/usage.py): the tenant identity from
        # X-Tenant-Id / API-key headers rides the admission context, so
        # every downstream engine dispatch (the failover router's
        # prefill/handoff/retry legs included) bills the same tenant
        tenant = usage_mod.tenant_from_headers(request.headers)

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "X-Request-Id": rid,
        })
        await resp.prepare(request)

        def guarded():
            # runs on the StreamDrain reader thread: rails' device work
            # (intent embedding) must not block the event loop, and a rails
            # failure must yield the canned error inside a valid SSE stream.
            # The admission context + request id are (re-)established HERE
            # because this generator body executes on that reader thread —
            # contextvars set in the handler coroutine don't cross threads.
            token = otel.set_request_id(rid)
            try:
                with slo_mod.admission(slo_class, deadline_ms=deadline_ms), \
                        usage_mod.tenant_scope(tenant):
                    yield from self._guarded_chain(query, history, use_kb,
                                                   settings)
            finally:
                otel.reset_request_id(token)

        from generativeaiexamples_tpu.engine.scheduler import _stop_scan
        first_at: Optional[float] = None
        last_at = 0.0
        chunks = 0
        held = ""
        hit = False

        async def emit(content: str) -> None:
            nonlocal first_at, last_at, chunks
            now = time.perf_counter()
            if first_at is None:
                first_at = now
                REGISTRY.histogram("e2e_ttft_s").observe(now - t_start)
            last_at = now
            chunks += 1
            await resp.write(f"data: {_chain_chunk(rid, content)}\n\n".encode())

        async for item in StreamDrain(guarded()):
            if stop:
                item, held, hit = _stop_scan(stop, held + item)
                if item:
                    await emit(item)
                if hit:
                    break
                continue
            await emit(item)
        if held and not hit:
            # trailing holdback that never completed a stop match
            await emit(held)
        await resp.write(f"data: {_chain_chunk(rid, '', 'stop')}\n\n".encode())
        # metrics observe BEFORE the stream closes: a client that reads
        # [DONE] and immediately scrapes /metrics must find this request's
        # latency/TPOT already counted (the same happens-before discipline
        # the scheduler applies to _STOP — write_eof is the edge clients
        # synchronize on)
        REGISTRY.histogram("e2e_latency_s").observe(time.perf_counter() - t_start)
        if chunks > 1 and first_at is not None:
            # chain-level time-per-output-chunk: the streaming-cadence
            # sibling of the engine's token-exact TPOT (SSE deltas can
            # carry several tokens, so this is an upper-ish proxy —
            # docs/observability.md's metric catalog spells out the pair)
            REGISTRY.histogram("e2e_tpot_s").observe(
                (last_at - first_at) / (chunks - 1))
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    def _guarded_chain(self, query, history, use_kb, settings):
        """The rails-wrapped chain body ``generate`` streams (sync
        generator; runs on the StreamDrain reader thread)."""
        try:
            if self.guardrails is not None:
                canned = self.guardrails.check_input(query)
                if canned is not None:
                    REGISTRY.counter("guardrails_input_blocks").inc()
                    yield canned
                    return
            chain = (self.example.rag_chain if use_kb else self.example.llm_chain)
            if (self.guardrails is not None
                    and self.guardrails.has_output_rails):
                # output rails (fact-check / scrub) need the complete
                # answer: buffer, check, emit once — rails trade
                # streaming latency for verification by design
                guardrails_mod.take_context()  # clear any stale record
                answer = "".join(chain(query, history, **settings))
                # fact-check against the context the chain actually
                # prompted with; re-retrieve only for chains that don't
                # record one
                context = guardrails_mod.take_context() if use_kb else ""
                if context is None:
                    context = self._rails_context(query)
                yield self.guardrails.check_output(answer, context, query)
                return
            yield from chain(query, history, **settings)
        except Exception:  # canned error message (ref :380-392)
            logger.exception("generation failed")
            REGISTRY.counter("generate_errors").inc()
            yield ("Error from chain server. Please check chain-server "
                   "logs for more details.")

    def _rails_context(self, query: str) -> str:
        """Retrieved evidence for the fact-check rail (the oran app passes
        its own retrieval results as [[CONTEXT]]); examples without
        document_search fact-check against nothing (rail skips)."""
        search = getattr(self.example, "document_search", None)
        if search is None or self.guardrails.fact_check is None:
            return ""
        try:
            hits = search(query)
            return "\n\n".join(str(h.get("content", "")) for h in hits)
        except Exception:
            logger.exception("rails context retrieval failed")
            return ""

    # -------------------------------------------------------------- search

    @instrumentation_wrapper
    async def search(self, request: web.Request) -> web.Response:
        body = await request.json()
        query = _sanitize(str(body.get("query", "")))
        top_k = int(body.get("top_k", 4))
        if not query:
            raise web.HTTPUnprocessableEntity(text=json.dumps(
                {"error": "query required"}))
        loop = asyncio.get_running_loop()
        try:
            chunks = await loop.run_in_executor(
                None, lambda: self.example.document_search(query, top_k))
        except NotImplementedError:
            raise web.HTTPNotImplemented(text=json.dumps(
                {"error": "example does not support search"}))
        return web.json_response({"chunks": [
            {"content": c.get("content", ""), "filename": c.get("source", ""),
             "score": c.get("score", 0.0)} for c in chunks]})

    # ----------------------------------------------------------- documents

    @instrumentation_wrapper
    async def get_documents(self, request: web.Request) -> web.Response:
        try:
            docs = self.example.get_documents()
        except NotImplementedError:
            docs = []
        return web.json_response({"documents": docs})

    @instrumentation_wrapper
    async def upload_document(self, request: web.Request) -> web.Response:
        reader = await request.multipart()
        field = await reader.next()
        while field is not None and field.name != "file":
            field = await reader.next()
        if field is None:
            raise web.HTTPUnprocessableEntity(text=json.dumps(
                {"error": "multipart field 'file' required"}))
        filename = os.path.basename(field.filename or f"upload-{uuid.uuid4().hex}")
        os.makedirs(UPLOAD_DIR, exist_ok=True)
        path = os.path.join(UPLOAD_DIR, filename)
        with open(path, "wb") as fh:
            while True:
                chunk = await field.read_chunk()
                if not chunk:
                    break
                fh.write(chunk)
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, lambda: self.example.ingest_docs(path, filename))
        except Exception as exc:
            logger.exception("ingestion failed for %s", filename)
            raise web.HTTPInternalServerError(text=json.dumps(
                {"error": f"ingestion failed: {exc}"}))
        REGISTRY.counter("documents_uploaded").inc()
        return web.json_response({"message": "File uploaded successfully"})

    @instrumentation_wrapper
    async def delete_document(self, request: web.Request) -> web.Response:
        filename = request.query.get("filename", "")
        if not filename:
            raise web.HTTPUnprocessableEntity(text=json.dumps(
                {"error": "filename query param required"}))
        try:
            ok = self.example.delete_documents([filename])
        except NotImplementedError:
            ok = False
        return web.json_response({"deleted": bool(ok)})


def run_server(example: BaseExample, host: str = "0.0.0.0",
               port: int = 8081) -> None:
    from generativeaiexamples_tpu.observability.bootstrap import (
        init_observability)
    init_observability("chain")
    server = ChainServer(example)
    web.run_app(server.app, host=host, port=port, print=None)
