"""Shared HTTP plumbing for the model, encoder, and chain servers.

One copy of the generation cap, the health/metrics/debug handlers (compose
healthcheck parity, ref docker-compose-nim-ms.yaml:23-28 / server.py:249),
and the SSE framing + per-request drain thread, so the servers cannot
drift apart.

``/metrics`` content-negotiates: the default stays the JSON snapshot
(existing dashboards/tests), while ``Accept: text/plain`` (what a stock
Prometheus scraper sends) or ``?format=prometheus`` serves text exposition
format 0.0.4 — the stack is scrapeable without a sidecar exporter.
``/debug/flight`` and ``/debug/requests[/<id>]`` expose the engine flight
recorder and recent per-request timelines (observability/flight.py).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import AsyncIterator, Optional

from aiohttp import web

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability.flight import FLIGHT, REQUEST_LOG

MAX_TOKENS_CAP = 1024  # ref: RAG/src/chain_server/server.py:104-110

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                            "charset=utf-8")

# Debug-surface caps: a poll during an incident must never serialize an
# unbounded ring into one response. Explicit query params may widen up to
# the hard max; absent params get the sane default.
FLIGHT_WINDOW_DEFAULT_S = 600.0
FLIGHT_LIMIT_DEFAULT = 1024
FLIGHT_LIMIT_MAX = 8192
REQUESTS_LIMIT_DEFAULT = 50
REQUESTS_LIMIT_MAX = 500
TRACE_WINDOW_DEFAULT_S = 600.0
TRACE_LIMIT_DEFAULT = 2048
TRACE_LIMIT_MAX = 8192


def parse_stop(value) -> list:
    """Normalize an OpenAI-contract `stop` field (string | list | null)
    to at most 4 non-empty strings — one rule for both servers (ref
    docs/api_reference/openapi_schema.json:517-526)."""
    if isinstance(value, str):
        value = [value]
    return [str(s) for s in (value or []) if s][:4]


class _DrainSwitch:
    """Process-level graceful-drain flag for servers WITHOUT an engine
    watchdog (chain, encoder): while draining, /health answers 503 so
    upstream pools route new work away while in-flight requests finish —
    the same zero-drop rotation primitive the engine's watchdog provides
    (engine/watchdog.py), minus the evacuation machinery (these servers
    hold no device state to migrate)."""

    def __init__(self) -> None:
        self.draining = False

    def drain(self) -> None:
        if not self.draining:
            REGISTRY.gauge("server_draining").set(1)
        self.draining = True

    def undrain(self) -> None:
        if self.draining:
            REGISTRY.gauge("server_draining").set(0)
        self.draining = False


DRAIN = _DrainSwitch()


async def health_handler(request: web.Request) -> web.Response:
    # slo_pressure rides the liveness probe so a pool client learns about
    # error-budget burn for free with every health check it already makes
    # (server/failover.py records it per worker)
    body = {"message": "Service is up.",
            "slo_pressure": slo_mod.SLO.pressure()}
    if DRAIN.draining:
        body["message"] = "Service is draining."
        return web.json_response(body, status=503)
    return web.json_response(body)


async def drain_handler(request: web.Request) -> web.Response:
    """``POST /debug/drain[?off=1]`` for non-engine servers: flip the
    process drain switch (health 503 ↔ 200). The engine server overrides
    this route with its watchdog-arbitrated version, which also accepts
    ``?evacuate=1`` for live KV migration (engine/server.py)."""
    if request.query.get("off", "").strip() in ("1", "true", "on"):
        DRAIN.undrain()
    elif request.query.get("evacuate", "").strip() in ("1", "true", "on"):
        raise web.HTTPConflict(text=json.dumps(
            {"error": "this server holds no engine KV state to evacuate; "
                      "?evacuate=1 applies to engine workers only"}))
    else:
        DRAIN.drain()
    return web.json_response({"draining": DRAIN.draining})


def _wants_openmetrics(request: web.Request) -> bool:
    # Explicit opt-in ONLY: stock Prometheus advertises
    # application/openmetrics-text in its default Accept, and this registry
    # renders exemplars without the # TYPE metadata a conforming OpenMetrics
    # parser requires before accepting them — switching on Accept would flip
    # every existing scraper onto a body it may reject. 0.0.4 output stays
    # byte-stable for all Accept-negotiated traffic; the exemplar-carrying
    # form is a diagnostic surface behind ?format=openmetrics.
    return request.query.get("format", "").lower() == "openmetrics"


def _wants_prometheus(request: web.Request) -> bool:
    if request.query.get("format", "").lower() in ("prometheus", "text"):
        return True
    accept = request.headers.get("Accept", "")
    # A Prometheus scraper asks for openmetrics/text-plain and never for
    # JSON; generic HTTP clients (axios et al.) default to an Accept that
    # LISTS text/plain as a fallback after application/json — those must
    # keep getting the documented-default JSON snapshot, so text/plain only
    # wins when JSON wasn't requested at all.
    return ("openmetrics" in accept
            or ("text/plain" in accept and "application/json" not in accept))


async def metrics_handler(request: web.Request) -> web.Response:
    if _wants_openmetrics(request):
        # OpenMetrics 1.0: same series, plus exemplars (trace ids on the
        # SLO latency histograms) and the # EOF terminator
        body = REGISTRY.render_prometheus(openmetrics=True)
        return web.Response(body=body.encode("utf-8"),
                            headers={"Content-Type":
                                     OPENMETRICS_CONTENT_TYPE})
    if _wants_prometheus(request):
        return web.Response(body=REGISTRY.render_prometheus().encode("utf-8"),
                            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE})
    return web.json_response(REGISTRY.snapshot())


def _query_number(request: web.Request, name: str, default, cast,
                  maximum=None):
    raw = request.query.get(name, "")
    if not raw:
        return default
    try:
        value = cast(raw)
    except ValueError:
        raise web.HTTPBadRequest(text=json.dumps(
            {"error": f"{name} must be a number, got {raw!r}"}))
    if maximum is not None:
        value = min(value, maximum)
    return value


async def flight_handler(request: web.Request) -> web.Response:
    """Windowed flight-recorder time series. ``?window=<seconds>`` bounds
    the lookback (default 600 s) and ``?limit=<n>`` the sample count
    (default 1024, newest kept; hard cap 8192) — the full ~17 min ring is
    ~4096 samples and serializing it all into one incident-time poll is
    exactly the wrong moment for a megabyte response."""
    seconds = _query_number(request, "window", FLIGHT_WINDOW_DEFAULT_S, float)
    limit = _query_number(request, "limit", FLIGHT_LIMIT_DEFAULT, int,
                          maximum=FLIGHT_LIMIT_MAX)
    samples = FLIGHT.window(seconds, limit=max(0, limit))
    return web.json_response({**FLIGHT.describe(),
                              "window_s": seconds,
                              "limit": limit,
                              "samples": samples,
                              # discrete incidents (recompiles, resets) ride
                              # their own ring so sample shapes stay uniform
                              "events": FLIGHT.events(seconds)})


async def requests_recent_handler(request: web.Request) -> web.Response:
    n = _query_number(request, "n", REQUESTS_LIMIT_DEFAULT, int,
                      maximum=REQUESTS_LIMIT_MAX)
    return web.json_response({"requests": REQUEST_LOG.recent(n),
                              "limit": n})


async def devtime_handler(request: web.Request) -> web.Response:
    """Per-program device-time ledger (observability/devtime.py): where the
    chip's time went, by (program, bucket) key, with useful-vs-padded rows,
    queue/device/issue split, and the live MFU inputs. Counts populate in
    every mode; device seconds need APP_DEVTIME=sample|on."""
    from generativeaiexamples_tpu.observability.devtime import DEVTIME
    return web.json_response(DEVTIME.snapshot())


async def compiles_handler(request: web.Request) -> web.Response:
    """Compile-watch log (observability/devtime.py): every program key
    whose first dispatch was NOT pre-compiled by warmup, with its trigger
    key; entries with during_serving=true are the mid-serving recompiles
    behind engine_recompiles_total (latency cliffs)."""
    from generativeaiexamples_tpu.observability.devtime import DEVTIME
    return web.json_response(DEVTIME.compiles())


async def chaos_handler(request: web.Request) -> web.Response:
    """Fault-injection plane state (observability/chaos.py): mode, seed,
    active spec, per-fault decision/injection counts — a chaos run's
    injected schedule is inspectable, not inferred from symptoms."""
    from generativeaiexamples_tpu.observability.chaos import CHAOS
    return web.json_response(CHAOS.snapshot())


async def deadletter_handler(request: web.Request) -> web.Response:
    """Event-agent dead letters (chains/event_agent.py): events that
    exhausted their retry budget, newest first — paired with the
    ``event_agent_dead_letter_total`` counter."""
    from generativeaiexamples_tpu.chains.event_agent import (
        dead_letter_payload)
    return web.json_response(dead_letter_payload())


async def usage_handler(request: web.Request) -> web.Response:
    """Per-tenant usage ledger of THIS process (observability/usage.py):
    the full resource vectors — queue/prefill/decode seconds, tokens,
    KV page-seconds, retries/hedges — with the cardinality-cap state and
    the billing basis (devtime proration vs token fallback)."""
    from generativeaiexamples_tpu.observability.usage import USAGE
    return web.json_response(USAGE.snapshot())


async def fleet_handler(request: web.Request) -> web.Response:
    """Fleet view from the process's routing frontend (server/failover.py):
    per-worker role/load/cache/chip cards from the probe cycle plus the
    fleet-summed per-tenant rollups. Processes without a router (a lone
    engine worker) answer with their own single-worker equivalent: local
    usage + perf, no probes."""
    from generativeaiexamples_tpu.observability import usage as usage_mod
    from generativeaiexamples_tpu.server import failover as failover_mod
    router = failover_mod.current_router()
    if router is None:
        return web.json_response({
            "workers": {},
            "note": "no routing frontend in this process; local view only",
            "tenants": usage_mod.USAGE.rollup(),
            "local_perf": usage_mod.worker_perf_card(),
        })
    # fleet() may re-probe stale workers over HTTP — keep it off the
    # event loop
    loop = asyncio.get_running_loop()
    body = await loop.run_in_executor(None, router.fleet)
    return web.json_response(body)


async def qos_handler(request: web.Request) -> web.Response:
    """QoS admission plane snapshot (engine/qos.py, APP_QOS): per-tenant
    weights, virtual clocks, quota buckets/throttle state, the service-
    time estimate basis (devtime | analytic | none), and outstanding
    admission reservations. ``{"enabled": false}`` in off mode — the
    surface exists everywhere so an operator probing a FIFO worker gets
    a definitive answer, not a 404 to interpret.

    The engine package import pulls jax; on processes that never loaded
    it (a pure router/encoder), a policy CANNOT be registered — answer
    off-mode without triggering a multi-second jax import inside the
    event loop. Processes that serve an engine already hold the module."""
    import os
    import sys
    qos_mod = sys.modules.get("generativeaiexamples_tpu.engine.qos")
    if qos_mod is None:
        return web.json_response({
            "enabled": False,
            "mode": (os.environ.get("APP_QOS", "").strip().lower()
                     or "off"),
            "hint": "set APP_QOS=fair (engine worker env) to enable the "
                    "admission plane; docs/scheduling.md"})
    return web.json_response(qos_mod.debug_payload())


async def trace_handler(request: web.Request) -> web.Response:
    """Canonical fleet event trace (observability/trace.py, APP_TRACE=on):
    the newest window of admission/dispatch/preempt/spill/promote/route/
    finish records this process emitted, plus the stream's own health
    (recorded/dropped/rotation path). ``?window=<seconds>`` bounds the
    lookback (default 600 s), ``?limit=<n>`` the record count (newest
    kept, hard cap 8192), ``?kind=a,b`` filters by record kind,
    ``?rid=<id>`` narrows to one request's slice (rid-stamped events
    plus the global dispatch emits whose ``rids`` roster mention it —
    the forensics join). Off mode answers ``{"enabled": false}`` with
    the env hint — a definitive answer on every process, never a 404 to
    interpret."""
    from generativeaiexamples_tpu.observability.trace import TRACE
    seconds = _query_number(request, "window", TRACE_WINDOW_DEFAULT_S, float)
    limit = _query_number(request, "limit", TRACE_LIMIT_DEFAULT, int,
                          maximum=TRACE_LIMIT_MAX)
    kinds_raw = request.query.get("kind", "").strip()
    kinds = ([k.strip() for k in kinds_raw.split(",") if k.strip()]
             or None)
    rid = request.query.get("rid", "").strip()
    if not TRACE.enabled:
        return web.json_response({
            **TRACE.describe(),
            "hint": "set APP_TRACE=on (worker env) to record the fleet "
                    "event trace; docs/simulation.md"})
    if rid:
        from generativeaiexamples_tpu.observability import forensics
        records = forensics.trace_slice(rid)
        if kinds is not None:
            want = frozenset(kinds)
            records = [r for r in records if r.get("kind") in want]
        if limit and len(records) > limit:
            records = records[-limit:]
        return web.json_response({**TRACE.describe(), "rid": rid,
                                  "limit": limit, "records": records})
    return web.json_response({**TRACE.describe(),
                              "window_s": seconds,
                              "limit": limit,
                              "records": TRACE.window(seconds, limit=limit,
                                                      kinds=kinds)})


async def locks_handler(request: web.Request) -> web.Response:
    """Runtime lock-order sanitizer (observability/lockwatch.py,
    APP_LOCKWATCH=on): the witness order graph over every tracked lock,
    plus every inversion (cycle-closing acquisition, BOTH stacks) and
    long hold (> APP_LOCKWATCH_HOLD_MS) observed since arming. Off mode
    answers ``{"enabled": false}`` with the env hint — the armed state
    is a construction-time property of each lock, so flipping the env on
    a live process tracks only locks built after the flip."""
    from generativeaiexamples_tpu.observability import lockwatch
    if not lockwatch._env_on():
        return web.json_response({
            "enabled": False,
            "hint": "set APP_LOCKWATCH=on (worker env, before process "
                    "start) to arm the lock-order sanitizer; "
                    "docs/static_analysis.md"})
    return web.json_response(lockwatch.WATCH.payload())


async def forensics_handler(request: web.Request) -> web.Response:
    """Tail-exemplar ring listing (observability/forensics.py,
    APP_FORENSICS=on): the requests that breached their SLO or landed
    above the trailing p99, auto-captured with their full trace slice.
    Off mode answers ``{"enabled": false}`` with the env hint."""
    from generativeaiexamples_tpu.observability.forensics import FORENSICS
    if not FORENSICS.enabled:
        return web.json_response({
            **FORENSICS.describe(),
            "hint": "set APP_FORENSICS=on (worker env) to capture tail "
                    "exemplars; docs/observability.md"})
    return web.json_response({**FORENSICS.describe(),
                              "exemplars": FORENSICS.exemplars()})


def _forensics_join_legs(rid: str) -> list:
    """Router-side cross-worker join (the usage-plane /health piggyback
    pattern): ask every live worker for its leg of the request. Runs in
    an executor — never on the event loop."""
    from generativeaiexamples_tpu.server import failover as failover_mod
    router = failover_mod.current_router()
    if router is None:
        return []
    legs = []
    try:
        import httpx
        for w in list(getattr(router, "_workers", []) or []):
            url = getattr(w, "url", "")
            if not url:
                continue
            try:
                r = httpx.get(f"{url}/debug/forensics/{rid}", timeout=2.0)
                if r.status_code != 200:
                    continue
                body = r.json()
                bd = body.get("breakdown") or {}
                if bd.get("found"):
                    legs.append({"worker": url, "breakdown": bd})
            except Exception:   # tpulint: disable=except-swallow -- a worker without the endpoint (or down) simply contributes no leg
                continue
    except Exception:   # tpulint: disable=except-swallow -- missing httpx in a stripped process degrades to the local view
        return legs
    return legs


async def forensics_rid_handler(request: web.Request) -> web.Response:
    """Critical-path breakdown for ONE request: the captured exemplar if
    the ring holds it, else a live reconstruction from whatever the
    trace/request-log planes still hold. On a routing frontend the local
    (router-axis) breakdown is joined with each worker's leg, fetched
    over HTTP by rid — mono clocks never compare across hosts, so legs
    stay on their own axes."""
    from generativeaiexamples_tpu.observability.forensics import FORENSICS
    rid = request.match_info.get("rid", "")
    body = FORENSICS.payload(rid)
    loop = asyncio.get_running_loop()
    legs = await loop.run_in_executor(None, _forensics_join_legs, rid)
    if legs:
        body["worker_legs"] = legs
    bd = body.get("breakdown") or {}
    if not body.get("captured") and not bd.get("found") and not legs:
        raise web.HTTPNotFound(text=json.dumps(
            {"error": f"no forensics for request {rid!r} (trace ring and "
                      "request log have both aged it out)",
             "enabled": body.get("enabled", False)}))
    return web.json_response(body)


async def alerts_handler(request: web.Request) -> web.Response:
    """SLO burn-rate alert state (observability/alerts.py): active
    alerts per objective/scope, the raise-edge log, and the rule
    definitions in force. Served on every server; alerts only
    accumulate where verdicts are fed (APP_FORENSICS=on on a scheduler
    process)."""
    from generativeaiexamples_tpu.observability.alerts import ALERTS
    from generativeaiexamples_tpu.observability.forensics import FORENSICS
    body = ALERTS.payload()
    body["enabled"] = FORENSICS.enabled
    if not FORENSICS.enabled:
        body["hint"] = ("set APP_FORENSICS=on (worker env) to feed the "
                        "burn-rate windows; docs/observability.md")
    return web.json_response(body)


async def doctor_handler(request: web.Request) -> web.Response:
    """Symptom→cause diagnosis engine (observability/forensics.py): maps
    the signals the process already records — recompiles, padding waste,
    spill thrash, qos sheds, affinity overrides, retry-budget
    exhaustion, watchdog trips, lock inversions — to named causes ranked
    by estimated device-seconds lost, each naming the configuration knob
    to turn (docs/configuration.md)."""
    from generativeaiexamples_tpu.observability.forensics import (
        doctor_payload)
    return web.json_response(doctor_payload())


async def slo_handler(request: web.Request) -> web.Response:
    """Per-class SLO attainment, burn rates, pressure, recent breaches
    (observability/slo.py) — the operator view of 'are we keeping our
    objectives and should the fleet be shedding'."""
    return web.json_response(slo_mod.SLO.debug_payload())


async def request_timeline_handler(request: web.Request) -> web.Response:
    rid = request.match_info.get("rid", "")
    rec = REQUEST_LOG.get(rid)
    if rec is None:
        raise web.HTTPNotFound(text=json.dumps(
            {"error": f"no recent request {rid!r} (log keeps the last "
                      f"{REQUEST_LOG.capacity})"}))
    return web.json_response(rec)


def add_debug_routes(app: web.Application, drain: bool = True) -> None:
    """Register the observability debug surface (engine, encoder, and chain
    servers all carry it — the flight recorder and request log are process-
    global, so whichever process hosts the scheduler answers with data).
    ``drain=False`` skips the default POST /debug/drain (the engine server
    registers its own watchdog-arbitrated handler at that path)."""
    if drain:
        app.add_routes([web.post("/debug/drain", drain_handler)])
    app.add_routes([
        web.get("/debug/flight", flight_handler),
        web.get("/debug/requests", requests_recent_handler),
        web.get("/debug/requests/{rid}", request_timeline_handler),
        web.get("/debug/slo", slo_handler),
        # devtime ledger + compile-watch: process-global like FLIGHT, so
        # the encoder server answers with its embed/rerank micro-batch
        # entries and the engine with its dispatch families
        web.get("/debug/devtime", devtime_handler),
        web.get("/debug/compiles", compiles_handler),
        # robustness plane: the chaos injector's live schedule and the
        # event agents' dead-letter ring (docs/robustness.md)
        web.get("/debug/chaos", chaos_handler),
        web.get("/debug/deadletter", deadletter_handler),
        # fleet usage plane: this process's per-tenant ledger, and the
        # router's cross-worker aggregation (docs/observability.md
        # "Who spent the chip")
        web.get("/debug/usage", usage_handler),
        web.get("/debug/fleet", fleet_handler),
        # QoS admission plane: tenant fair-queuing state + quota buckets
        # (docs/scheduling.md)
        web.get("/debug/qos", qos_handler),
        # canonical fleet event trace: the replayable admission/dispatch/
        # route record stream (docs/simulation.md)
        web.get("/debug/trace", trace_handler),
        # runtime lock-order sanitizer: witness graph + inversions
        # (docs/static_analysis.md)
        web.get("/debug/locks", locks_handler),
        # latency forensics plane: tail-exemplar ring, per-request
        # critical-path breakdowns, burn-rate alerts, and the diagnosis
        # engine (docs/observability.md "Why was this request slow")
        web.get("/debug/forensics", forensics_handler),
        web.get("/debug/forensics/{rid}", forensics_rid_handler),
        web.get("/debug/alerts", alerts_handler),
        web.get("/debug/doctor", doctor_handler),
    ])


async def sse_write(resp: web.StreamResponse, payload: str) -> None:
    await resp.write(f"data: {payload}\n\n".encode())


async def sse_done(resp: web.StreamResponse) -> None:
    await resp.write(b"data: [DONE]\n\n")
    await resp.write_eof()


class StreamDrain:
    """Bridge a blocking delta iterator onto the event loop.

    One dedicated reader thread per request pushes deltas into an
    asyncio.Queue via call_soon_threadsafe — no executor-pool round trip per
    token, and slow consumers can't starve other requests' streams.
    """

    _DONE = object()

    def __init__(self, iterator) -> None:
        self._iterator = iterator
        self._loop = asyncio.get_running_loop()
        self._queue: "asyncio.Queue" = asyncio.Queue()
        # tpulint: disable=daemon-shutdown -- request-scoped: the pump
        # exits when the delta iterator ends (or the loop closes); there
        # is no process-shutdown hook to join hundreds of live streams
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        try:
            for delta in self._iterator:
                self._loop.call_soon_threadsafe(self._queue.put_nowait, delta)
        finally:
            self._loop.call_soon_threadsafe(self._queue.put_nowait, self._DONE)

    async def __aiter__(self) -> AsyncIterator[str]:
        while True:
            item = await self._queue.get()
            if item is self._DONE:
                return
            yield item

    async def join_text(self) -> str:
        parts = []
        async for delta in self:
            parts.append(delta)
        return "".join(parts)
