"""Shared HTTP plumbing for the model server and the chain server.

One copy of the generation cap, the health/metrics handlers (compose
healthcheck parity, ref docker-compose-nim-ms.yaml:23-28 / server.py:249),
and the SSE framing + per-request drain thread, so the two servers cannot
drift apart.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import AsyncIterator, Optional

from aiohttp import web

from generativeaiexamples_tpu.core.metrics import REGISTRY

MAX_TOKENS_CAP = 1024  # ref: RAG/src/chain_server/server.py:104-110


def parse_stop(value) -> list:
    """Normalize an OpenAI-contract `stop` field (string | list | null)
    to at most 4 non-empty strings — one rule for both servers (ref
    docs/api_reference/openapi_schema.json:517-526)."""
    if isinstance(value, str):
        value = [value]
    return [str(s) for s in (value or []) if s][:4]


async def health_handler(request: web.Request) -> web.Response:
    return web.json_response({"message": "Service is up."})


async def metrics_handler(request: web.Request) -> web.Response:
    return web.json_response(REGISTRY.snapshot())


async def sse_write(resp: web.StreamResponse, payload: str) -> None:
    await resp.write(f"data: {payload}\n\n".encode())


async def sse_done(resp: web.StreamResponse) -> None:
    await resp.write(b"data: [DONE]\n\n")
    await resp.write_eof()


class StreamDrain:
    """Bridge a blocking delta iterator onto the event loop.

    One dedicated reader thread per request pushes deltas into an
    asyncio.Queue via call_soon_threadsafe — no executor-pool round trip per
    token, and slow consumers can't starve other requests' streams.
    """

    _DONE = object()

    def __init__(self, iterator) -> None:
        self._iterator = iterator
        self._loop = asyncio.get_running_loop()
        self._queue: "asyncio.Queue" = asyncio.Queue()
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        try:
            for delta in self._iterator:
                self._loop.call_soon_threadsafe(self._queue.put_nowait, delta)
        finally:
            self._loop.call_soon_threadsafe(self._queue.put_nowait, self._DONE)

    async def __aiter__(self) -> AsyncIterator[str]:
        while True:
            item = await self._queue.get()
            if item is self._DONE:
                return
            yield item

    async def join_text(self) -> str:
        parts = []
        async for delta in self:
            parts.append(delta)
        return "".join(parts)
