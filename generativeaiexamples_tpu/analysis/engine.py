"""The tpulint driver: discover files, parse once, run every rule,
apply suppressions and the baseline.

Pure stdlib + pure AST: linting never imports the analyzed code, so it
runs identically with or without JAX installed and costs well under a
second for the whole package (the tier-1 self-check budget is 10 s).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from generativeaiexamples_tpu.analysis import baseline as baseline_mod
from generativeaiexamples_tpu.analysis import rules as _rules  # noqa: F401 — registers the module rules
from generativeaiexamples_tpu.analysis.astutil import ModuleContext
from generativeaiexamples_tpu.analysis.callgraph import Program  # noqa: F401 — registers the program rules
from generativeaiexamples_tpu.analysis.findings import BaselineKey, Finding
from generativeaiexamples_tpu.analysis.registry import RULES, Rule
from generativeaiexamples_tpu.analysis.suppressions import Suppressions

_SKIP_DIRS = frozenset({"__pycache__", ".git", ".mypy_cache", ".pytest_cache",
                        "node_modules"})


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    files: List[str] = field(default_factory=list)   # repo-relative, scanned
    suppressed: int = 0
    baselined: int = 0
    unknown_suppressions: List[str] = field(default_factory=list)

    @property
    def files_scanned(self) -> int:
        return len(self.files)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.unknown_suppressions

    def summary(self) -> Dict[str, object]:
        by_rule: Dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {"files_scanned": self.files_scanned,
                "findings": len(self.findings),
                "suppressed": self.suppressed,
                "baselined": self.baselined,
                "by_rule": dict(sorted(by_rule.items())),
                "unknown_suppressions": list(self.unknown_suppressions)}


# the source tree root (the directory holding the generativeaiexamples_tpu
# package): baseline keys and rendered paths anchor here, NOT to cwd, so a
# baseline written from the repo root still matches a run started anywhere
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _rel(path: str) -> str:
    """Stable repo-root-relative posix path (the baseline key and the
    rendered location); files outside the repo keep their absolute path —
    still cwd-independent, just not portable across machines."""
    apath = os.path.abspath(path)
    try:
        rel = os.path.relpath(apath, _ROOT)
    except ValueError:          # different drive (windows)
        rel = apath
    if rel.startswith(".."):
        rel = apath
    return rel.replace(os.sep, "/")


def discover(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted, de-duplicated .py list.
    A path that does not exist is an error, not an empty result — a
    typo'd lint target must never read as a clean tree."""
    out: List[str] = []
    seen = set()
    for path in paths:
        if not os.path.exists(path):
            raise ValueError(f"no such file or directory: {path}")
        if os.path.isfile(path):
            candidates: List[str] = [path]
        else:
            candidates = []
            for root, dirs, names in os.walk(path):
                dirs[:] = [d for d in dirs
                           if d not in _SKIP_DIRS and not d.startswith(".")]
                candidates.extend(os.path.join(root, name)
                                  for name in names if name.endswith(".py"))
        for cand in candidates:
            key = os.path.abspath(cand)
            if key not in seen and cand.endswith(".py"):
                seen.add(key)
                out.append(cand)
    return sorted(out, key=_rel)


def _select(only: Optional[Sequence[str]], skip: Optional[Sequence[str]]
            ) -> List[Rule]:
    names = list(RULES)
    unknown = [n for n in list(only or []) + list(skip or [])
               if n not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}; "
                         f"available: {', '.join(sorted(RULES))}")
    if only:
        names = [n for n in names if n in set(only)]
    if skip:
        names = [n for n in names if n not in set(skip)]
    return [RULES[n] for n in names]


def analyze_source(path: str, source: str,
                   rules: Optional[Sequence[Rule]] = None,
                   ) -> List[Finding]:
    """All raw findings for one module (suppressions NOT applied — the
    caller owns policy). A syntax error is itself a finding: tier-1 must
    not report 'clean' on a tree it could not parse.  Program-scoped
    rules run over a one-module program here, so single-file fixtures
    exercise the same interprocedural code the full run does."""
    rel = _rel(path)
    try:
        ctx = ModuleContext(rel, source)
    except SyntaxError as exc:
        return [Finding(rel, exc.lineno or 1, "parse-error", "error",
                        f"file does not parse: {exc.msg}")]
    findings: List[Finding] = []
    program: Optional[Program] = None
    for r in rules if rules is not None else list(RULES.values()):
        if r.scope == "program":
            if program is None:
                program = Program([ctx])
            findings.extend(r.check(program))
        else:
            findings.extend(r.check(ctx))
    return sorted(findings)


def analyze_file(path: str, rules: Optional[Sequence[Rule]] = None
                 ) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return analyze_source(path, fh.read(), rules)


def run_paths(paths: Sequence[str],
              only: Optional[Sequence[str]] = None,
              skip: Optional[Sequence[str]] = None,
              baseline_path: Optional[str] = baseline_mod.DEFAULT_BASELINE_PATH,
              ) -> Report:
    """Lint ``paths`` end to end: discover → parse → rules → inline
    suppressions → baseline.  ``baseline_path=None`` disables the
    baseline (``--no-baseline``).  Suppression comments naming unknown
    rules are reported, not ignored — a typo in ``disable=`` must not
    silently re-enable nothing."""
    rules = _select(only, skip)
    module_rules = [r for r in rules if r.scope == "module"]
    program_rules = [r for r in rules if r.scope == "program"]
    grandfathered: Dict[BaselineKey, int] = (
        baseline_mod.load(baseline_path) if baseline_path else {})
    report = Report()
    all_remaining: List[Finding] = []
    contexts: List[ModuleContext] = []
    supps: Dict[str, Suppressions] = {}
    for path in discover(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        rel = _rel(path)
        report.files.append(rel)
        supp = Suppressions(source)
        supps[rel] = supp
        try:
            ctx: Optional[ModuleContext] = ModuleContext(rel, source)
        except SyntaxError as exc:
            ctx = None
            findings = [Finding(rel, exc.lineno or 1, "parse-error", "error",
                                f"file does not parse: {exc.msg}")]
        else:
            contexts.append(ctx)
            findings = []
            for r in module_rules:
                findings.extend(r.check(ctx))
            findings.sort()
        kept, n_supp = supp.split(findings)
        report.suppressed += n_supp
        all_remaining.extend(kept)
        for name in sorted(supp.mentioned):
            if name not in RULES:
                report.unknown_suppressions.append(
                    f"{rel}: suppression references unknown rule "
                    f"{name!r}")
    # whole-program phase: one Program over every parsed module, each
    # interprocedural rule run ONCE; findings anchor to real call sites,
    # so the per-file inline suppressions apply to them unchanged
    if program_rules and contexts:
        program = Program(contexts)
        pfindings: List[Finding] = []
        for r in program_rules:
            pfindings.extend(r.check(program))
        for f in sorted(pfindings):
            supp_f = supps.get(f.file)
            if supp_f is not None and supp_f.is_suppressed(f.rule, f.line):
                report.suppressed += 1
            else:
                all_remaining.append(f)
    remaining, absorbed = baseline_mod.apply(all_remaining, grandfathered)
    report.baselined = absorbed
    report.findings = sorted(remaining)
    return report


def build_program(paths: Sequence[str]) -> Program:
    """Parse ``paths`` into a whole-program :class:`Program` (the CLI's
    ``--lock-graph`` rendering path; unparseable files are skipped — the
    lint run itself owns reporting them)."""
    contexts: List[ModuleContext] = []
    for path in discover(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            contexts.append(ModuleContext(_rel(path), source))
        except SyntaxError:
            continue
    return Program(contexts)
