"""Inline suppression comments.

    do_thing()            # tpulint: disable=net-timeout  -- probe, see X
    # tpulint: disable=except-swallow -- error rides the return value
    except Exception:

A trailing comment suppresses the named rule(s) on its own line AND on
the first line of the logical statement it terminates — findings anchor
to a statement's first physical line, so a suppression on the closing
line of a wrapped multi-line call still lands.  A standalone comment
line suppresses the next line of code (so a suppression can carry a
reason without blowing the line length).  Two spellings of scope:

  * ``# tpulint: disable=rule1,rule2`` — line-scoped (``disable=all``
    matches every rule);
  * ``# tpulint: disable-file=rule1,rule2`` — whole-file, anywhere in the
    file (fixture files full of deliberate violations).

Comments are extracted with :mod:`tokenize` (never by scanning raw
lines), so a suppression example quoted inside a docstring — like the
ones above — is not a suppression.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, List, Set, Tuple

# the rule list ends at the first token that is not a comma-joined name,
# so a trailing free-text reason ("-- probe endpoint, see docs") rides
# the same comment without being parsed as rule names
_RULES_PART = r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
_LINE_RE = re.compile(r"#\s*tpulint:\s*disable=" + _RULES_PART)
_FILE_RE = re.compile(r"#\s*tpulint:\s*disable-file=" + _RULES_PART)

ALL = "all"

# tokens that neither end nor belong to a logical statement line
_NON_CODE_TOKENS = frozenset({
    tokenize.COMMENT, tokenize.NL, tokenize.INDENT, tokenize.DEDENT,
    tokenize.ENCODING, tokenize.ENDMARKER, tokenize.NEWLINE,
})


def _parse_rules(spec: str) -> Set[str]:
    return {part.strip() for part in spec.split(",") if part.strip()}


class Suppressions:
    """Per-file suppression lookup built in ONE tokenize pass.

    ``mentioned`` collects every rule name any suppression references so
    the engine can validate them against the registry without a second
    pass over the source.
    """

    def __init__(self, source: str) -> None:
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        self.mentioned: Set[str] = set()
        lines = source.splitlines()
        comment_lines: Set[int] = set()
        pending: List[Tuple[int, Set[str]]] = []
        stmt_start = None   # first physical line of the open logical line
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.NEWLINE:
                    stmt_start = None
                    continue
                if tok.type not in _NON_CODE_TOKENS:
                    if stmt_start is None:
                        stmt_start = tok.start[0]
                    continue
                if tok.type != tokenize.COMMENT:
                    continue
                lineno = tok.start[0]
                standalone = not tok.line[:tok.start[1]].strip()
                if standalone:
                    comment_lines.add(lineno)
                m = _FILE_RE.search(tok.string)
                if m:
                    rules = _parse_rules(m.group(1))
                    self.file_wide |= rules
                    self.mentioned |= rules
                    continue
                m = _LINE_RE.search(tok.string)
                if not m:
                    continue
                rules = _parse_rules(m.group(1))
                self.mentioned |= rules
                if standalone:
                    # next-code-line semantics; inside an open wrapped
                    # statement ALSO cover the statement's lines so far —
                    # findings may anchor to the statement's first line
                    # while the user comments next to the nested call
                    pending.append((lineno, rules))
                if not standalone or stmt_start is not None:
                    # trailing comments (and standalone ones inside a
                    # wrapped statement) cover every physical line of the
                    # logical statement up to the comment
                    first = lineno if stmt_start is None else stmt_start
                    for ln in range(first, lineno + 1):
                        self.by_line.setdefault(ln, set()).update(rules)
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparseable source: the AST pass reports it; no suppressions
            return
        # a standalone suppression comment applies to the next line that is
        # neither a comment nor blank (multi-line reasons stack, and a blank
        # line between comment and code must not void the suppression)
        for lineno, rules in pending:
            target = lineno + 1
            while target <= len(lines) and (
                    target in comment_lines or not lines[target - 1].strip()):
                target += 1
            self.by_line.setdefault(target, set()).update(rules)
        self.mentioned.discard(ALL)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if ALL in self.file_wide or rule in self.file_wide:
            return True
        rules = self.by_line.get(line, ())
        return ALL in rules or rule in rules

    def split(self, findings: List) -> Tuple[List, int]:
        """(kept, suppressed_count)."""
        kept = [f for f in findings
                if not self.is_suppressed(f.rule, f.line)]
        return kept, len(findings) - len(kept)
