"""Checked-in baseline of grandfathered findings.

A new rule lands with the violations it finds either fixed or recorded
here — the tree stays at zero *new* findings from day one, and the
baseline burns down over time instead of blocking the rule.  Keys are
``(rule, file, message)`` with a count, deliberately line-free: an
unrelated edit that shifts a grandfathered finding by ten lines must not
churn this file (messages carry the symbol names, so they move with the
code).

The shipped baseline (``tpulint_baseline.json``) is EMPTY — every
violation the initial rules surfaced was fixed or inline-annotated in
the PR that introduced them.  The machinery stays because the next rule
will not be so lucky.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Optional, Tuple

from generativeaiexamples_tpu.analysis.findings import BaselineKey, Finding

VERSION = 1

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                                     "tpulint_baseline.json")


def load(path: str) -> Dict[BaselineKey, int]:
    """key → grandfathered count. A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != VERSION:
        raise ValueError(f"unsupported baseline format in {path}")
    out: Dict[BaselineKey, int] = {}
    for entry in data.get("findings", []):
        # hand-edits and merge-conflict resolutions happen to this file —
        # a malformed entry must surface as a usage error, not a traceback
        if not isinstance(entry, dict) or not all(
                isinstance(entry.get(k), str)
                for k in ("rule", "file", "message")):
            raise ValueError(
                f"malformed baseline entry in {path}: {entry!r} "
                "(need string rule/file/message)")
        key = (entry["rule"], entry["file"], entry["message"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def save(path: str, findings: List[Finding],
         keep: Optional[Dict[BaselineKey, int]] = None) -> None:
    """Write ``findings`` (plus ``keep`` — pre-existing entries the caller
    wants preserved, e.g. those for files outside a partial-path run) as
    the new baseline."""
    counts = Counter(f.baseline_key() for f in findings)
    for key, count in (keep or {}).items():
        counts[key] += count
    entries = [{"rule": rule, "file": file, "message": message,
                "count": count}
               for (rule, file, message), count in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": VERSION, "findings": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")


def apply(findings: List[Finding], baseline: Dict[BaselineKey, int]
          ) -> Tuple[List[Finding], int]:
    """Subtract grandfathered findings: up to ``count`` findings per key
    are absorbed (oldest-in-file first); the rest stay live.  Returns
    (remaining, absorbed_count)."""
    budget = dict(baseline)
    remaining: List[Finding] = []
    absorbed = 0
    for f in sorted(findings):
        key = f.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            absorbed += 1
        else:
            remaining.append(f)
    return remaining, absorbed
