"""The tpulint rule catalog — TPU-serving hazards this codebase has
actually shipped (and fixed) by hand.

Every rule is a pure-AST heuristic: no imports of the analyzed code, no
JAX, no dataflow.  That buys determinism and speed (the whole package
lints in well under a second) at the cost of some reach — e.g.
clock-discipline flags ``time.time()`` *directly* in arithmetic, not a
wall-clock value stored and subtracted three lines later.  The rules are
tuned so that a true positive is near-certain; anything deliberate gets
an inline ``# tpulint: disable=<rule>`` with a reason.

Rationale per rule lives in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Sequence

from generativeaiexamples_tpu.analysis.astutil import (
    ModuleContext, call_name, dotted_name)
from generativeaiexamples_tpu.analysis.findings import Finding
from generativeaiexamples_tpu.analysis.registry import rule

# --------------------------------------------------------------------------
# shared vocab
# --------------------------------------------------------------------------

_JIT_NAMES = frozenset({
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
})
_PARTIAL_NAMES = frozenset({"functools.partial", "partial"})

# host↔device sync triggers on traced values (method form)
_SYNC_ATTRS = frozenset({"item", "tolist", "block_until_ready"})
# ... and call form
_SYNC_CALLS = frozenset({
    "jax.device_get", "device_get", "np.asarray", "np.array",
    "numpy.asarray", "numpy.array",
})

_HTTP_CALLS = frozenset(
    f"{mod}.{verb}"
    for mod in ("requests", "httpx")
    for verb in ("get", "post", "put", "patch", "delete", "head", "options",
                 "request", "stream")
)
_URLOPEN_CALLS = frozenset({"urllib.request.urlopen", "request.urlopen",
                            "urlopen"})

# matched against underscore-separated segments of the held name's last
# component, EXACTLY — substring matching would drag in clocks
# ("self.clock") and blocking-IO helpers ("self.blocker")
_LOCKISH_SEGMENTS = frozenset({"lock", "rlock", "wlock", "mutex", "cv",
                               "cond", "condition"})

# blocking while holding a lock: serializes every other thread on it
_BLOCKING_UNDER_LOCK_CALLS = frozenset(
    {"time.sleep"} | _HTTP_CALLS | _URLOPEN_CALLS
    | {"jax.device_get", "device_get"}
    # disk syscalls: the KV tier's write-behind demotion (engine/
    # kv_tier.py) stages multi-MB files near the driver thread — a
    # flush/rename under the tier lock stalls every admission probe
    | {"os.replace", "os.fsync", "os.remove", "os.unlink"})
_BLOCKING_UNDER_LOCK_ATTRS = frozenset({"result", "block_until_ready"})
# pathlib whole-file I/O: one call hides an open+read/write+close
_DISK_UNDER_LOCK_ATTRS = frozenset({"write_bytes", "read_bytes"})
# Condition.wait RELEASES the lock; notify wakes without blocking
_LOCK_SAFE_ATTRS = frozenset({"wait", "wait_for", "notify", "notify_all",
                              "acquire", "release"})

_LOG_METHODS = frozenset({"debug", "info", "warning", "warn", "error",
                          "exception", "critical", "log"})
_METRIC_ATTRS = frozenset({"inc", "dec", "observe", "set"})


def _walk_excluding_defs(body: Sequence[ast.AST]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function bodies —
    a closure defined under a lock does not *run* under it."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``pjit`` as a name, a configured call
    (``jax.jit(f, ...)``), or ``partial(jax.jit, ...)``."""
    if dotted_name(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in _JIT_NAMES:
            return True
        if name in _PARTIAL_NAMES and node.args:
            return dotted_name(node.args[0]) in _JIT_NAMES
    return False


def _jit_decorated(fn: ast.AST) -> bool:
    return any(_is_jit_expr(d) for d in getattr(fn, "decorator_list", []))


def _walk_trace_scope(body: Sequence[ast.AST]) -> Iterator[ast.AST]:
    """Walk a traced function's body INCLUDING nested plain defs/lambdas —
    a helper defined and called inside a jitted function runs under the
    same trace, so its host syncs are just as fatal. Only a nested def
    carrying its own jit decorator is skipped (it is its own check root)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _jit_decorated(node):
            continue
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
# trace-hazard
# --------------------------------------------------------------------------

@rule("trace-hazard", "error",
      "Host sync / host round-trip on traced values inside jit-compiled or "
      "hot-path code (.item(), .tolist(), np.asarray, jax.device_get, "
      "float()/int() on traced values)")
def check_trace_hazard(ctx: ModuleContext) -> Iterable[Finding]:
    """Inside a jitted function these either fail at trace time or, worse,
    silently force a device fetch per call on the decode path.  Functions
    marked ``# tpulint: hot-path`` (scheduler-tick code) get the same
    treatment minus the float()/int() check (host floats are fine there —
    it is the per-token device fetch that kills throughput)."""
    for fn in ctx.walk():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        jitted = _jit_decorated(fn)
        hot = ctx.has_marker(fn, "hot-path")
        if not (jitted or hot):
            continue
        where = "jit-compiled" if jitted else "hot-path"
        for node in _walk_trace_scope(fn.body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS):
                yield Finding(
                    ctx.path, node.lineno, "trace-hazard", "error",
                    f"`.{node.func.attr}()` in {where} `{fn.name}` forces a "
                    "host sync per call; batch the fetch outside the "
                    "compiled/hot region")
            elif name in _SYNC_CALLS:
                yield Finding(
                    ctx.path, node.lineno, "trace-hazard", "error",
                    f"`{name}` in {where} `{fn.name}` pulls the value to "
                    "host; keep device arrays on device or fetch them "
                    "batched outside")
            elif (jitted and name in ("float", "int", "bool")
                  and len(node.args) == 1
                  and not isinstance(node.args[0], ast.Constant)):
                yield Finding(
                    ctx.path, node.lineno, "trace-hazard", "error",
                    f"`{name}()` on a non-constant inside jit-compiled "
                    f"`{fn.name}` concretizes a traced value (trace-time "
                    "error or silent sync); use jnp ops instead")


# --------------------------------------------------------------------------
# recompile-hazard
# --------------------------------------------------------------------------

@rule("recompile-hazard", "error",
      "jax.jit/pjit constructed inside a loop — every construction is a "
      "fresh compile cache, so the XLA compile cost repeats per iteration")
def check_recompile_hazard(ctx: ModuleContext) -> Iterable[Finding]:
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        is_jit = name in _JIT_NAMES or (
            name in _PARTIAL_NAMES and node.args
            and dotted_name(node.args[0]) in _JIT_NAMES)
        if is_jit and ctx.in_loop(node):
            yield Finding(
                ctx.path, node.lineno, "recompile-hazard", "error",
                f"`{name}` constructed inside a loop — the compiled "
                "function (and its cache) is rebuilt every iteration; "
                "hoist the jit out of the loop and reuse it")


# --------------------------------------------------------------------------
# lock-discipline
# --------------------------------------------------------------------------

def _lockish(expr: ast.AST) -> Optional[str]:
    name = dotted_name(expr)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1].lower()
    segments = [s for s in last.split("_") if s]
    if any(s in _LOCKISH_SEGMENTS for s in segments):
        return name
    return None


@rule("lock-discipline", "error",
      "Blocking call (sleep, HTTP, future .result(), TPU fetch, disk "
      "I/O) while holding a threading.Lock/Condition — serializes every "
      "thread contending on that lock behind the slow operation")
def check_lock_discipline(ctx: ModuleContext) -> Iterable[Finding]:
    """``Condition.wait`` is exempt (it releases the lock); closures
    defined under the lock are skipped (they run later, elsewhere)."""
    for node in ctx.walk():
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        held = None
        for item in node.items:
            held = _lockish(item.context_expr)
            if held:
                break
        if not held:
            continue
        for inner in _walk_excluding_defs(node.body):
            if not isinstance(inner, ast.Call):
                continue
            name = call_name(inner)
            attr = (inner.func.attr
                    if isinstance(inner.func, ast.Attribute) else None)
            if attr in _LOCK_SAFE_ATTRS:
                continue
            if name in _BLOCKING_UNDER_LOCK_CALLS:
                yield Finding(
                    ctx.path, inner.lineno, "lock-discipline", "error",
                    f"`{name}` while holding `{held}` — every thread "
                    "contending on the lock blocks behind it; move the "
                    "slow call outside the critical section")
            elif attr in _BLOCKING_UNDER_LOCK_ATTRS:
                yield Finding(
                    ctx.path, inner.lineno, "lock-discipline", "error",
                    f"`.{attr}()` while holding `{held}` — a blocking "
                    "wait inside the critical section; collect the future "
                    "/ device value after releasing the lock")
            elif attr in _DISK_UNDER_LOCK_ATTRS:
                yield Finding(
                    ctx.path, inner.lineno, "lock-discipline", "error",
                    f"`.{attr}()` while holding `{held}` — whole-file "
                    "disk I/O inside the critical section; stage the "
                    "bytes under the lock, touch the filesystem after "
                    "releasing it (see engine/kv_tier.py write-behind)")


# --------------------------------------------------------------------------
# clock-discipline
# --------------------------------------------------------------------------

@rule("clock-discipline", "error",
      "time.time() used in interval/rate arithmetic — wall clock steps on "
      "NTP adjustment, producing negative or wildly wrong durations; use "
      "time.monotonic() (wall clock only for reported timestamps)")
def check_clock_discipline(ctx: ModuleContext) -> Iterable[Finding]:
    """Flags ``time.time()`` appearing as an operand of +/- arithmetic or
    a comparison.  A bare ``time.time()`` stored as a *timestamp*
    (``"created": int(time.time())``) is legitimate and passes; a stored
    value subtracted later is out of reach for a single-expression pass —
    reviewers still own that case."""
    for node in ctx.walk():
        if call_name(node) != "time.time":
            continue
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.stmt):
                break
            if (isinstance(anc, ast.BinOp)
                    and isinstance(anc.op, (ast.Add, ast.Sub))) \
                    or isinstance(anc, ast.Compare):
                yield Finding(
                    ctx.path, node.lineno, "clock-discipline", "error",
                    "`time.time()` in duration/interval arithmetic — wall "
                    "clock is not monotonic; use `time.monotonic()` and "
                    "keep wall clock for reported timestamps only")
                break


# --------------------------------------------------------------------------
# clock-injection
# --------------------------------------------------------------------------

# policy modules the replay simulator (ops/simulate.py) drives on a
# virtual clock — a direct stdlib clock read here silently desynchronizes
# record and replay instead of failing loudly
_CLOCK_POLICY_SUFFIXES = (
    "engine/scheduler.py", "engine/qos.py", "engine/kv_tier.py",
    "observability/forensics.py", "observability/alerts.py",
)
_STDLIB_CLOCK_CALLS = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.monotonic_ns", "time.perf_counter_ns", "time.time_ns",
})


@rule("clock-injection", "error",
      "Direct stdlib clock read (time.time/monotonic/perf_counter) in "
      "scheduler/QoS/KV-tier policy code — these modules run under the "
      "replay simulator's virtual clock and must read core/clock.py "
      "(mono()/perf()/wall()) or an injected clock instead")
def check_clock_injection(ctx: ModuleContext) -> Iterable[Finding]:
    """The time-travel debugger's contract (docs/simulation.md): every
    time-dependent decision in the three policy modules flows through the
    injectable process clock, so a recorded trace replays bit-identically
    on virtual time.  ``time.sleep`` stays legal (it is a *wait*, not a
    clock read — the simulator never calls the paths that block).
    Genuine telemetry-only sites use the inline
    ``# tpulint: disable=clock-injection`` allowlist with a reason."""
    norm = ctx.path.replace("\\", "/")
    if not norm.endswith(_CLOCK_POLICY_SUFFIXES):
        return
    for node in ctx.walk():
        name = call_name(node)
        if name in _STDLIB_CLOCK_CALLS:
            yield Finding(
                ctx.path, node.lineno, "clock-injection", "error",
                f"`{name}()` in simulator-driven policy code — read the "
                "injected process clock (core/clock.py mono()/perf()/"
                "wall()) so replay on a virtual clock stays faithful")


# --------------------------------------------------------------------------
# net-timeout
# --------------------------------------------------------------------------

@rule("net-timeout", "error",
      "Outbound HTTP call without timeout= — one hung peer wedges the "
      "calling thread (and whatever lock or slot it holds) forever")
def check_net_timeout(ctx: ModuleContext) -> Iterable[Finding]:
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in _HTTP_CALLS:
            timed = any(kw.arg == "timeout" for kw in node.keywords)
        elif name in _URLOPEN_CALLS:
            # urlopen(url, data, timeout) — positional third arg counts
            timed = (any(kw.arg == "timeout" for kw in node.keywords)
                     or len(node.args) >= 3)
        else:
            continue
        if not timed:
            yield Finding(
                ctx.path, node.lineno, "net-timeout", "error",
                f"`{name}` without `timeout=` — a silent peer blocks this "
                "thread indefinitely; pass an explicit timeout "
                "(core.config.http_timeout() for the shared default)")


# --------------------------------------------------------------------------
# devtime-fence
# --------------------------------------------------------------------------

@rule("devtime-fence", "error",
      "Bare block_until_ready outside the devtime ledger's sampled fence "
      "helper — an ad-hoc device fence serializes the dispatch pipeline "
      "and bypasses the APP_DEVTIME sampling gate")
def check_devtime_fence(ctx: ModuleContext) -> Iterable[Finding]:
    """Every device fence in serving code must route through
    observability/devtime.py's :func:`_fence` (gated by ``APP_DEVTIME``) —
    the ledger exists so timing fences are SAMPLED and accounted, and one
    stray ``jax.block_until_ready`` on the hot path quietly re-serializes
    the pipelining PR 2–5 built. Fires on both the module-call and the
    method form, anywhere (a fence in 'cold' code has a way of migrating
    into a loop). ``jax.device_get`` is the same fence wearing a transfer's
    clothes — it blocks until the value is computed AND copied — so it is
    held to the same standard: every result fetch must route through the
    scheduler's counted ``_fetch`` seam (which feeds
    ``engine_host_fetches_total`` / ``engine_steps_per_fetch``, the
    decode-dispatch-tail telemetry). The deliberate exceptions — warmup's
    compile barrier, the ledger's own helper, bench phase boundaries, the
    ``_fetch`` seam itself, cold-path KV exports — carry annotated
    suppressions with their reasons."""
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        attr = (node.func.attr
                if isinstance(node.func, ast.Attribute) else None)
        if name == "jax.block_until_ready" or attr == "block_until_ready":
            yield Finding(
                ctx.path, node.lineno, "devtime-fence", "error",
                "bare `block_until_ready` — route device fences through "
                "observability/devtime.py's sampled ledger helper "
                "(APP_DEVTIME gate), or annotate the deliberate fence "
                "with a reason")
        elif name == "jax.device_get":
            yield Finding(
                ctx.path, node.lineno, "devtime-fence", "error",
                "bare `jax.device_get` — a device→host fetch is a fence "
                "plus a transfer; route it through the scheduler's "
                "counted `_fetch` seam (engine_host_fetches_total / "
                "engine_steps_per_fetch stay honest), or annotate the "
                "deliberate fetch with a reason")


# --------------------------------------------------------------------------
# retry-discipline
# --------------------------------------------------------------------------

# a call whose presence marks a loop as backoff-disciplined: the shared
# jittered helpers (server/resilience.py), a policy gate, or a plain sleep
_BACKOFF_CALLS = frozenset({"time.sleep", "asyncio.sleep", "sleep"})
_BACKOFF_ATTRS = frozenset({"sleep", "before_retry", "backoff_s"})

# each-iteration-consumes-new-input markers: a loop that pulls fresh work
# every pass (queue consumer, stream reader) is a PUMP, not a retry loop —
# continuing after an exception there skips a bad item, it does not re-run
# the same operation
_CONSUME_ATTRS = frozenset({"get", "get_nowait", "pop", "popleft",
                            "read", "read_chunk", "readline", "recv",
                            "accept", "next"})


def _loop_has_call(loop: ast.AST, names: frozenset,
                   attrs: frozenset) -> bool:
    for node in _walk_excluding_defs(loop.body):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) in names:
            return True
        if isinstance(node.func, ast.Attribute) and node.func.attr in attrs:
            return True
    return False


def _retrying_handlers(loop: ast.AST):
    """ExceptHandlers inside ``loop`` (own body only, not nested defs)
    that neither raise, return, nor break — i.e. the loop runs again
    after the failure: a retry."""
    for node in _walk_excluding_defs(loop.body):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            exits = any(isinstance(inner, (ast.Raise, ast.Return, ast.Break))
                        for inner in _walk_excluding_defs(handler.body))
            if not exits:
                yield handler


_DELIVER_ATTRS = frozenset({"set", "put", "put_nowait", "append",
                            "appendleft"})


def _delivers_error(handler: ast.ExceptHandler) -> bool:
    """A handler that hands the failure to a consumer (event.set(),
    queue.put(), dead_letter.append()) and loops is a PUMP skipping a bad
    item — the item's owner sees the error; the loop is not blindly
    re-running the same operation."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _DELIVER_ATTRS:
            return True
    return False


def _is_true_const(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Constant) and expr.value in (True, 1)


@rule("retry-discipline", "error",
      "Retry loop without backoff discipline: an unbounded `while True` "
      "retry, or a bounded network retry with no backoff between attempts "
      "— a synchronized retry storm amplifies the outage it responds to")
def check_retry_discipline(ctx: ModuleContext) -> Iterable[Finding]:
    """Two shapes, both tuned for near-certain true positives:

    * ``while True`` containing an except handler that swallows-and-loops,
      with no backoff/sleep call AND no per-iteration input consumption
      (queue ``get``/``pop``/``read`` — pump loops skip bad items, they
      don't retry them): an unbounded, undelayed retry spins the CPU and
      hammers whatever it is retrying against.
    * ``for _ in range(...)`` retrying an HTTP call (the transport-retry
      shape) with no backoff call in the loop: bounded, but a correlated
      failure burst retries in lockstep — route it through
      server/resilience.py's jittered policy.
    """
    for node in ctx.walk():
        if isinstance(node, ast.While) and _is_true_const(node.test):
            handlers = [h for h in _retrying_handlers(node)
                        if not _delivers_error(h)]
            if not handlers:
                continue
            if _loop_has_call(node, _BACKOFF_CALLS, _BACKOFF_ATTRS):
                continue
            if _loop_has_call(node, frozenset(), _CONSUME_ATTRS):
                continue
            yield Finding(
                ctx.path, handlers[0].lineno, "retry-discipline", "error",
                "unbounded `while True` retry with no backoff — cap the "
                "attempts and sleep a jittered backoff between them "
                "(server/resilience.py full_jitter_backoff)")
        elif isinstance(node, ast.For) \
                and isinstance(node.iter, ast.Call) \
                and call_name(node.iter) in ("range",):
            handlers = list(_retrying_handlers(node))
            if not handlers:
                continue
            has_http = any(
                isinstance(inner, ast.Call)
                and (call_name(inner) in _HTTP_CALLS
                     or call_name(inner) in _URLOPEN_CALLS)
                for inner in _walk_excluding_defs(node.body))
            if not has_http:
                continue   # LLM re-prompt loops etc. — backoff is wrong there
            if _loop_has_call(node, _BACKOFF_CALLS, _BACKOFF_ATTRS):
                continue
            yield Finding(
                ctx.path, handlers[0].lineno, "retry-discipline", "error",
                "network retry loop with no backoff between attempts — "
                "a correlated failure burst retries in lockstep; gate "
                "each retry through the shared jittered policy "
                "(server/resilience.ResiliencePolicy.before_retry)")


# --------------------------------------------------------------------------
# metric-cardinality
# --------------------------------------------------------------------------

# registry factory methods that mint a labeled time series per label SET
_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})

# identifiers whose value space is per-request / caller-controlled: using
# one as a label value mints a fresh Prometheus series per request until
# the process OOMs the scrape. Matched EXACTLY against the last dotted
# component (request_id, rid, ...) — "worker"/"url"/"tenant" stay clean
# (pool-bounded, or capped by the usage plane's overflow bucket).
_UNBOUNDED_LABEL_NAMES = frozenset({
    "request_id", "rid", "trace_id", "span_id", "session_id",
    "prompt", "prompt_ids", "query", "text", "message", "content",
})
# call results that are unbounded by construction
_UNBOUNDED_CALLS = frozenset({"uuid.uuid4", "uuid.uuid1", "uuid4", "uuid1",
                              "time.time", "time.monotonic",
                              "time.perf_counter"})


def _registryish(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    return name is not None and "registry" in name.lower()


def _unbounded_label_value(value: ast.AST) -> Optional[str]:
    """Why this label-value expression mints unbounded series, or None.
    Walks the whole expression — f-strings, str()/format() wrappers, and
    attribute chains all count; the hazard is the identifier inside."""
    for node in ast.walk(value):
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = dotted_name(node)
            if name is not None \
                    and name.rsplit(".", 1)[-1] in _UNBOUNDED_LABEL_NAMES:
                return f"`{name}` is a per-request value"
        if isinstance(node, ast.Call) \
                and call_name(node) in _UNBOUNDED_CALLS:
            return f"`{call_name(node)}()` mints a fresh value per call"
    return None


@rule("metric-cardinality", "error",
      "Metric label value derived from a request id, prompt text, or "
      "another unbounded per-request string — every distinct value mints "
      "a new time series, growing the registry (and every scrape) without "
      "bound")
def check_metric_cardinality(ctx: ModuleContext) -> Iterable[Finding]:
    """Fires on ``REGISTRY.counter/gauge/histogram(..., labels={...})``
    (any receiver whose dotted name contains "registry") where a label
    VALUE references a per-request identifier (request_id, trace_id,
    prompt, ...) or an unbounded call (uuid4, time.*). Bounded label
    sources — worker URLs (pool-sized), finish causes (enum), tenant ids
    (capped by the usage plane's ``"other"`` overflow bucket) — pass.
    The failure mode is exactly what observability/usage.py's
    cardinality cap exists to prevent; this rule keeps the next labeled
    metric from reintroducing it."""
    for node in ctx.walk():
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in _METRIC_FACTORIES \
                or not _registryish(node.func.value):
            continue
        labels = next((kw.value for kw in node.keywords
                       if kw.arg == "labels"), None)
        if not isinstance(labels, ast.Dict):
            continue
        for key_node, value in zip(labels.keys, labels.values):
            why = _unbounded_label_value(value)
            if why is None:
                continue
            key = (repr(key_node.value)
                   if isinstance(key_node, ast.Constant) else "<label>")
            yield Finding(
                ctx.path, value.lineno, "metric-cardinality", "error",
                f"label {key} on `{node.func.attr}` uses an unbounded "
                f"value ({why}) — every distinct value is a new time "
                "series; use a bounded enum, a capped id space "
                "(observability/usage.py's tenant cap), or attach the id "
                "as an exemplar/log field instead")


# --------------------------------------------------------------------------
# daemon-shutdown
# --------------------------------------------------------------------------

_THREAD_CTORS = frozenset({"threading.Thread", "Thread"})


def _joined_names(ctx: ModuleContext) -> frozenset:
    """Last components of every ``X.join(...)`` receiver in the module —
    `self._disk_thread.join(2)` contributes ``_disk_thread``.  One level
    of local aliasing is followed: the idiomatic bounded-join shutdown
    hook detaches under the lock first (``t = self._writer`` or
    ``t, self._writer = self._writer, None``), then joins ``t`` — that
    must credit ``_writer``, not the throwaway local."""
    aliases: dict = {}
    for node in ctx.walk():
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            if isinstance(tgt, ast.Name) and isinstance(val, ast.Attribute):
                aliases.setdefault(tgt.id, set()).add(val.attr)
            elif isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                    and len(tgt.elts) == len(val.elts):
                for t_el, v_el in zip(tgt.elts, val.elts):
                    if isinstance(t_el, ast.Name) \
                            and isinstance(v_el, ast.Attribute):
                        aliases.setdefault(t_el.id, set()).add(v_el.attr)
    out = set()
    for node in ctx.walk():
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            base = dotted_name(node.func.value)
            if base:
                name = base.rsplit(".", 1)[-1]
                out.add(name)
                out.update(aliases.get(name, ()))
    return frozenset(out)


@rule("daemon-shutdown", "error",
      "threading.Thread(daemon=True) with no join() anywhere in the "
      "module — the interpreter kills daemons mid-write at exit, so an "
      "unjoined writer loses its last buffer; add a bounded-join "
      "shutdown hook (sentinel + join(timeout)) on drain/atexit")
def check_daemon_shutdown(ctx: ModuleContext) -> Iterable[Finding]:
    """Fires on the creation site.  Clean when the module joins the
    stored thread somewhere (the sentinel that unblocks the loop is the
    author's business — the join is what makes shutdown *bounded* and
    observable).  Deliberate fire-and-forget threads (request-scoped
    pumps, the exit-grace timer) carry an inline suppression with the
    reason their lifecycle needs no join."""
    joined = _joined_names(ctx)
    for node in ctx.walk():
        if not isinstance(node, ast.Call) \
                or call_name(node) not in _THREAD_CTORS:
            continue
        daemon = next((kw.value for kw in node.keywords
                       if kw.arg == "daemon"), None)
        if not (isinstance(daemon, ast.Constant) and daemon.value is True):
            continue
        stored = None
        parent = ctx.parent(node)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            stored = dotted_name(parent.targets[0])
        elif isinstance(parent, (ast.AnnAssign, ast.NamedExpr)):
            stored = dotted_name(parent.target)
        if stored is not None and stored.rsplit(".", 1)[-1] in joined:
            continue
        what = (f"`{stored}`" if stored
                else "an unbound `threading.Thread(daemon=True)`")
        yield Finding(
            ctx.path, node.lineno, "daemon-shutdown", "error",
            f"daemon thread {what} is never join()ed — at interpreter "
            "exit daemons die mid-operation (a write-behind loses its "
            "last buffer); add a sentinel-stop + bounded join on "
            "drain/atexit, or annotate the deliberate fire-and-forget "
            "with a reason")


# --------------------------------------------------------------------------
# except-swallow
# --------------------------------------------------------------------------

def _broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted_name(e) for e in t.elts]
    else:
        names = [dotted_name(t)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles_visibly(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.AugAssign)):
            return True        # re-raise, or an error counter increment
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in ("print", "warnings.warn", "traceback.print_exc"):
            return True
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            base = dotted_name(node.func.value) or ""
            if attr in _LOG_METHODS and ("log" in base.lower()
                                         or isinstance(node.func.value,
                                                       ast.Call)):
                return True    # logger.x / logging.x / getLogger(...).x
            if attr in _METRIC_ATTRS:
                return True    # REGISTRY.counter(...).inc() and kin
        if name and "REGISTRY" in name:
            return True
    return False


@rule("except-swallow", "warning",
      "Broad `except Exception` that neither logs, counts, nor re-raises — "
      "failures vanish; a dead component with /health green is the worst "
      "failure mode")
def check_except_swallow(ctx: ModuleContext) -> Iterable[Finding]:
    for node in ctx.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _broad_handler(node) and not _handles_visibly(node):
            caught = (dotted_name(node.type)
                      if node.type is not None else "everything")
            yield Finding(
                ctx.path, node.lineno, "except-swallow", "warning",
                f"broad `except {caught}` swallows the failure silently — "
                "log it, count it (errors_total), narrow the type, or "
                "annotate the deliberate swallow with a reason")
