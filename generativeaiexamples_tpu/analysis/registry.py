"""Rule registry: name → (severity, description, check function).

A rule is a plain function ``check(ctx) -> Iterable[Finding]`` over a
parsed module (:class:`~generativeaiexamples_tpu.analysis.astutil.ModuleContext`),
registered with the :func:`rule` decorator.  The registry is the single
source of truth for the CLI's ``--list-rules``, the doc catalog, and the
engine's rule selection (``--only`` / ``--skip``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Iterable, TYPE_CHECKING

from generativeaiexamples_tpu.analysis.findings import SEVERITIES, Finding

if TYPE_CHECKING:   # pragma: no cover
    from generativeaiexamples_tpu.analysis.astutil import ModuleContext

CheckFn = Callable[["ModuleContext"], Iterable[Finding]]

# "module": check(ModuleContext), run per file.  "program": check(Program)
# (analysis/callgraph.py), run ONCE over the whole scanned tree by the
# engine — interprocedural rules see every module at once.
SCOPES = ("module", "program")


@dataclass(frozen=True)
class Rule:
    name: str
    severity: str
    description: str
    check: CheckFn
    scope: str = "module"


RULES: Dict[str, Rule] = {}


def rule(name: str, severity: str, description: str,
         scope: str = "module") -> Callable[[CheckFn], CheckFn]:
    """Register ``fn`` as the checker for ``name``. Import-time validation
    keeps rule metadata honest (the doc catalog renders from it)."""
    if severity not in SEVERITIES:
        raise ValueError(f"rule {name!r}: severity must be one of {SEVERITIES}")
    if scope not in SCOPES:
        raise ValueError(f"rule {name!r}: scope must be one of {SCOPES}")

    def deco(fn: CheckFn) -> CheckFn:
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name, severity, description, fn, scope)
        return fn

    return deco
