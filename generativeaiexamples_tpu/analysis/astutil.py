"""Shared AST plumbing for tpulint rules.

One parse per file: :class:`ModuleContext` wraps the tree with parent
links (stdlib ``ast`` has none), the raw source lines (for comment-based
markers like ``# tpulint: hot-path``), and the dotted-name/ancestry
helpers every rule needs.  Rules stay small because the traversal
mechanics live here.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

_PARENT = "_tpulint_parent"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (calls, subscripts
    and other computed bases break the chain on purpose — a rule matching
    ``jax.jit`` should not match ``get_jax().jit``)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.AST) -> Optional[str]:
    """Dotted name of a Call's callee, else None."""
    if isinstance(node, ast.Call):
        return dotted_name(node.func)
    return None


class ModuleContext:
    """A parsed module plus the lookups rules share."""

    def __init__(self, path: str, source: str, tree: Optional[ast.Module] = None
                 ) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree if tree is not None else ast.parse(source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                setattr(child, _PARENT, parent)

    # ------------------------------------------------------------- traversal

    def walk(self) -> Iterator[ast.AST]:
        return ast.walk(self.tree)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, _PARENT, None)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        """Innermost first, up to (and including) the Module."""
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def in_loop(self, node: ast.AST) -> bool:
        """True when ``node`` executes repeatedly: under a for/while or
        inside a comprehension in the same function scope (a nested def
        re-binds per call, not per iteration — crossing one stops the
        search)."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While,
                                ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
        return False

    # -------------------------------------------------------------- comments

    def line_text(self, lineno: int) -> str:
        """1-based; empty string past EOF."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def has_marker(self, node: ast.AST, marker: str) -> bool:
        """True when ``# tpulint: <marker>`` rides the ``def`` line itself
        or the line above the whole declaration (above the first decorator,
        when there are any — a marker must keep working when a decorator is
        later added to the function)."""
        def_line = getattr(node, "lineno", 0)
        first = def_line
        for deco in getattr(node, "decorator_list", []) or []:
            first = min(first, getattr(deco, "lineno", first))
        needle = f"tpulint: {marker}"
        return (needle in self.line_text(def_line)
                or needle in self.line_text(first - 1))
