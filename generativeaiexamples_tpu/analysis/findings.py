"""Finding model: one hazard at one ``file:line``.

Findings are value objects so the engine can dedupe, sort, diff, and
baseline them.  The baseline key deliberately omits the line number —
grandfathered findings must survive unrelated edits that shift lines,
otherwise every PR churns the baseline file.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

SEVERITIES = ("error", "warning")

BaselineKey = Tuple[str, str, str]   # (rule, file, message)


@dataclass(frozen=True, order=True)
class Finding:
    file: str          # repo-relative posix path
    line: int          # 1-based
    rule: str
    severity: str      # "error" | "warning"
    message: str

    def baseline_key(self) -> BaselineKey:
        return (self.rule, self.file, self.message)

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.severity}] {self.rule}: {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return {"file": self.file, "line": self.line, "rule": self.rule,
                "severity": self.severity, "message": self.message}
