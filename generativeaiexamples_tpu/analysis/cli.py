"""tpulint CLI.

    python -m generativeaiexamples_tpu.analysis [paths...] [options]
    make lint

Exit codes: 0 clean, 1 findings (or unknown suppressions), 2 usage
errors.  ``--json`` emits a machine-readable report (stable keys) so
future tooling can diff findings across commits; ``--write-baseline``
grandfathers the current findings instead of failing on them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from generativeaiexamples_tpu.analysis import baseline as baseline_mod
from generativeaiexamples_tpu.analysis import rules as _rules  # noqa: F401
from generativeaiexamples_tpu.analysis.engine import build_program, run_paths
from generativeaiexamples_tpu.analysis.registry import RULES

# the installed package directory itself — cwd-independent, like every
# other path the analyzer touches (engine._rel anchors to the repo root)
DEFAULT_TARGET = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m generativeaiexamples_tpu.analysis",
        description="tpulint: static analysis for TPU-serving hazards "
                    "(docs/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=[DEFAULT_TARGET],
                   help="files or directories (default: the "
                   "generativeaiexamples_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (findings + summary)")
    p.add_argument("--only", action="append", metavar="RULE",
                   help="run only this rule (repeatable)")
    p.add_argument("--skip", action="append", metavar="RULE",
                   help="skip this rule (repeatable)")
    p.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE_PATH,
                   metavar="PATH", help="baseline file (default: the "
                   "checked-in tpulint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report grandfathered "
                   "findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file and "
                   "exit 0 (the grandfathering workflow)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--json-out", metavar="PATH",
                   help="ALSO write the machine-readable report to PATH "
                   "(the CI artifact), independent of --json")
    p.add_argument("--budget-s", type=float, metavar="SECONDS",
                   help="fail (exit 1) if the run takes longer than this "
                   "— the lint wall-time budget, enforced in CI")
    p.add_argument("--lock-graph", action="store_true",
                   help="print the interprocedural lock-order graph "
                   "(one witnessed edge per line) and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            r = RULES[name]
            print(f"{name} [{r.severity}]\n    {r.description}")
        return 0

    if args.lock_graph:
        try:
            program = build_program(args.paths)
        except (ValueError, OSError) as exc:
            print(f"tpulint: {exc}", file=sys.stderr)
            return 2
        graph = program.render_lock_graph()
        print(graph if graph else "(no lock-order edges)")
        return 0

    if args.write_baseline and (args.only or args.skip):
        # a filtered run sees a subset of findings; writing it would drop
        # every other rule's grandfathered entries from the baseline
        print("tpulint: --write-baseline cannot be combined with "
              "--only/--skip (it would overwrite the other rules' "
              "baseline entries)", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    try:
        report = run_paths(
            args.paths, only=args.only, skip=args.skip,
            baseline_path=None if (args.no_baseline or args.write_baseline)
            else args.baseline)
    except (ValueError, OSError) as exc:
        print(f"tpulint: {exc}", file=sys.stderr)
        return 2
    elapsed_s = time.monotonic() - t0

    if report.files_scanned == 0:
        print("tpulint: no .py files under the given paths — refusing to "
              "report an unscanned tree as clean", file=sys.stderr)
        return 2

    if args.write_baseline:
        if report.unknown_suppressions:
            # grandfathering now would permanently hide the finding the
            # typo'd suppression meant to cover — fix the typo first
            for msg in report.unknown_suppressions:
                print(msg, file=sys.stderr)
            print("tpulint: refusing --write-baseline while suppressions "
                  "reference unknown rules", file=sys.stderr)
            return 1
        broken = [f for f in report.findings if f.rule == "parse-error"]
        if broken:
            # a grandfathered parse-error would make every later run call
            # an unparseable tree "clean" — the one invariant the analyzer
            # must never trade away
            for f in broken:
                print(f.render(), file=sys.stderr)
            print("tpulint: refusing --write-baseline while files do not "
                  "parse", file=sys.stderr)
            return 1
        # a partial-path run sees a subset of files: preserve the baseline
        # entries of every file OUTSIDE the scanned set, else grandfathered
        # findings elsewhere silently resurface on the next full run
        try:
            existing = baseline_mod.load(args.baseline)
        except (ValueError, OSError) as exc:
            print(f"tpulint: {exc}", file=sys.stderr)
            return 2
        scanned = set(report.files)
        keep = {key: count for key, count in existing.items()
                if key[1] not in scanned}
        baseline_mod.save(args.baseline, report.findings, keep=keep)
        print(f"tpulint: wrote {len(report.findings)} finding(s) to "
              f"{args.baseline}"
              + (f" (kept {sum(keep.values())} existing for files "
                 "outside the scanned paths)" if keep else ""))
        return 0

    doc = {"version": 1,
           "findings": [f.to_json() for f in report.findings],
           "summary": {**report.summary(),
                       "elapsed_s": round(elapsed_s, 3)}}
    rendered = json.dumps(doc, indent=2, sort_keys=True)

    over_budget = (args.budget_s is not None and elapsed_s > args.budget_s)

    if args.json_out:
        try:
            with open(args.json_out, "w", encoding="utf-8") as f:
                f.write(rendered + "\n")
        except OSError as exc:
            print(f"tpulint: cannot write {args.json_out}: {exc}",
                  file=sys.stderr)
            return 2

    if args.as_json:
        print(rendered)
    else:
        for f in report.findings:
            print(f.render())
        for msg in report.unknown_suppressions:
            print(f"{msg}", file=sys.stderr)
        s = report.summary()
        status = "clean" if report.clean else f"{s['findings']} finding(s)"
        print(f"tpulint: {status} — {s['files_scanned']} file(s) scanned, "
              f"{s['suppressed']} suppressed, {s['baselined']} baselined "
              f"[{elapsed_s:.2f}s]")
    if over_budget:
        print(f"tpulint: wall-time budget exceeded — {elapsed_s:.2f}s > "
              f"{args.budget_s:.0f}s (a lint nobody waits for is a lint "
              f"nobody runs)", file=sys.stderr)
        return 1
    return 0 if report.clean else 1


if __name__ == "__main__":   # pragma: no cover
    raise SystemExit(main())
