"""``python -m generativeaiexamples_tpu.analysis`` → the tpulint CLI."""

from generativeaiexamples_tpu.analysis.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
