"""Config-knob extraction — the ``APP_*`` side of the docs sync contract.

docs/configuration.md carries a marker-delimited catalog of every
environment knob the package reads (between ``<!-- config-catalog:begin
-->`` and ``<!-- config-catalog:end -->``), and
``tests/test_config_catalog.py`` holds it equal to the code in BOTH
directions: a knob the code reads but the table omits fails (the
undocumented-flag failure — an operator cannot set what they cannot
find), and a table row no code reads fails just as loudly (doc rot — an
operator tuning a dead knob and watching nothing change).

Knobs reach the process three ways, and the catalog sees each:

  * **Literal reads** — ``os.environ.get("APP_X")``, ``os.getenv``,
    ``os.environ["APP_X"]`` (Load context only; writes are not reads),
    and the typed helpers ``env_float``/``env_int`` (core/config.py)
    plus the router's module-local ``_env_int``/``_env_float``. Pure
    AST, same bargain as tpulint/metrics_catalog: no imports of the
    analyzed code. A name passed as a module-level string constant
    (``MODE_ENV = "APP_QOS"``) resolves through the module's constant
    table; an f-string resolves constant interpolations
    (``f"{ENV_PREFIX}_CONFIG_FILE"`` → ``APP_CONFIG_FILE``) and turns
    anything else into a ``*`` — a *dynamic pattern* row.
  * **Schema overlay** — every field of the AppConfig dataclass tree is
    an ``APP_<PATH>_<FIELD>`` override (core/config.py ``_from_dict``).
    Those names are computed, not written, so :func:`collect_schema_env`
    enumerates them by reflecting the schema itself (an import of
    core/config only — the one catalog source where reflection IS the
    ground truth, since the dataclass is the single place the names are
    defined).
  * **Pass-through names** — variables read and handed to a subprocess
    or library verbatim (``JAX_PLATFORMS``, ``PALLAS_AXON_POOL_IPS``)
    are not ``APP_`` knobs and stay out of the catalog by the prefix
    filter.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Set, Tuple

# marker pair the docs section lives between
CATALOG_BEGIN = "<!-- config-catalog:begin -->"
CATALOG_END = "<!-- config-catalog:end -->"

# `name` in a table row's first backticked cell
_ROW_NAME = re.compile(r"^\|\s*`([^`]+)`")

# callables whose first argument is an env-var name (the typed readers
# in core/config.py, the router's module-local variants, and the debug
# plane's bool `_flag`)
_ENV_HELPERS = frozenset({"env_float", "env_int", "_env_float",
                          "_env_int", "_flag"})

_PREFIX = "APP_"


def _iter_py(pkg_dir: str) -> Iterator[str]:
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = [d for d in dirs if not d.startswith((".", "__pycache__"))]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments — the indirection
    the qos/config modules use for their env names."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _env_callee(node: ast.Call) -> bool:
    """True when the call reads the environment by name: ``os.environ.get``
    / ``os.environ.setdefault`` / ``os.getenv`` / an ``env_*`` helper."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("get", "setdefault") \
                and isinstance(fn.value, ast.Attribute) \
                and fn.value.attr == "environ":
            return True
        if fn.attr == "getenv" and isinstance(fn.value, ast.Name) \
                and fn.value.id == "os":
            return True
        if fn.attr in _ENV_HELPERS:
            return True
    elif isinstance(fn, ast.Name) and fn.id in _ENV_HELPERS:
        return True
    return False


def _resolve_name(arg: ast.expr, consts: Dict[str, str]) -> str:
    """The env-var name an expression denotes: '' when invisible,
    a ``*``-bearing pattern when partially resolvable."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.Name):
        return consts.get(arg.id, "")
    if isinstance(arg, ast.JoinedStr):
        parts: List[str] = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id in consts:
                parts.append(consts[v.value.id])
            else:
                parts.append("*")
        return "".join(parts)
    return ""


def collect_env_reads(pkg_dir: str) -> Tuple[Set[str], Set[str]]:
    """Scan the package: returns ``(static, patterns)`` — APP_-prefixed
    names read by literal/constant, and ``*``-bearing dynamic patterns."""
    static: Set[str] = set()
    patterns: Set[str] = set()
    for path in _iter_py(pkg_dir):
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            # tpulint's parse-error rule owns unparseable files
            continue
        consts = _module_constants(tree)
        for node in ast.walk(tree):
            name = ""
            if isinstance(node, ast.Call) and node.args \
                    and _env_callee(node):
                name = _resolve_name(node.args[0], consts)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Attribute) \
                    and node.value.attr == "environ":
                name = _resolve_name(node.slice, consts)
            if not name or not name.startswith(_PREFIX):
                continue
            (patterns if "*" in name else static).add(name)
    return static, patterns


def collect_schema_env() -> Set[str]:
    """Every ``APP_*`` override the AppConfig schema overlay accepts —
    enumerated by reflecting the dataclass tree (the names are computed
    per field in ``_from_dict``; the schema is their only definition)."""
    from generativeaiexamples_tpu.core import config as config_mod
    names = {env_name for env_name, _ftype, _default, _help
             in config_mod._iter_env_vars(config_mod.AppConfig,
                                          config_mod.ENV_PREFIX)}
    return {n for n in names if n.startswith(_PREFIX)}


def parse_catalog(md_text: str) -> Tuple[Set[str], Set[str]]:
    """Names from the marker-delimited docs section: returns
    ``(documented_static, documented_patterns)`` — a name containing
    ``*`` is a dynamic pattern row."""
    try:
        start = md_text.index(CATALOG_BEGIN)
        end = md_text.index(CATALOG_END)
    except ValueError:
        raise ValueError(
            "docs catalog markers not found (config-catalog:begin/end)")
    block = md_text[start:end]
    names: Set[str] = set()
    patterns: Set[str] = set()
    for line in block.splitlines():
        m = _ROW_NAME.match(line.strip())
        if not m:
            continue
        name = m.group(1)
        (patterns if "*" in name else names).add(name)
    return names, patterns
