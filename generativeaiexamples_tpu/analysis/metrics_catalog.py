"""Metric-catalog extraction — the code side of the docs sync contract.

docs/observability.md carries a marker-delimited catalog of every metric
family the package registers (the section between
``<!-- metric-catalog:begin -->`` and ``<!-- metric-catalog:end -->``).
This module AST-walks the shipped package and collects what the code
*actually* registers, so ``tests/test_metric_catalog.py`` can hold the
two sides equal in both directions: an undocumented registration fails,
and a documented-but-dead name (the classic doc-rot failure — a
dashboard built on a metric that no longer exists) fails just as loudly.

Collection is pure AST (same bargain as tpulint — no imports of the
analyzed code): a call whose callee chain ends in
``REGISTRY.counter/gauge/histogram`` with a literal first argument is a
static registration; an f-string first argument becomes a *dynamic
pattern* with ``*`` standing for the interpolated parts
(``stage_*_s``). Dynamic patterns are documented as patterns — the
catalog cannot enumerate per-encoder or per-stage instantiations.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Set, Tuple

_METRIC_CTORS = frozenset({"counter", "gauge", "histogram"})

# marker pair the docs section lives between
CATALOG_BEGIN = "<!-- metric-catalog:begin -->"
CATALOG_END = "<!-- metric-catalog:end -->"

# `name` in a table row's first backticked cell
_ROW_NAME = re.compile(r"^\|\s*`([^`]+)`")


def _iter_py(pkg_dir: str) -> Iterator[str]:
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = [d for d in dirs if not d.startswith((".", "__pycache__"))]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _registry_ctor(node: ast.Call) -> str:
    """'counter'/'gauge'/'histogram' when the callee is a
    ``REGISTRY.<ctor>`` chain (any base spelling whose last-but-one
    segment is REGISTRY — ``metrics.REGISTRY.counter`` counts), else ''."""
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _METRIC_CTORS):
        return ""
    base = fn.value
    if isinstance(base, ast.Name) and base.id == "REGISTRY":
        return fn.attr
    if isinstance(base, ast.Attribute) and base.attr == "REGISTRY":
        return fn.attr
    return ""


def _dynamic_pattern(js: ast.JoinedStr) -> str:
    parts: List[str] = []
    for v in js.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    return "".join(parts) or "*"


def collect_registered(pkg_dir: str
                       ) -> Tuple[Dict[str, Set[str]], Set[str]]:
    """Scan the package: returns ``(static, dynamic)`` where ``static``
    maps ``name -> {ctor kinds seen}`` and ``dynamic`` is the set of
    f-string patterns (``*`` per interpolation)."""
    static: Dict[str, Set[str]] = {}
    dynamic: Set[str] = set()
    for path in _iter_py(pkg_dir):
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            # tpulint's parse-error rule owns unparseable files; the
            # catalog just skips them
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            ctor = _registry_ctor(node)
            if not ctor or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                static.setdefault(arg.value, set()).add(ctor)
            elif isinstance(arg, ast.JoinedStr):
                dynamic.add(_dynamic_pattern(arg))
            # a plain variable first arg (rare: wrapper helpers) is
            # invisible here by design — wrappers register literals at
            # their own call sites
    return static, dynamic


def parse_catalog(md_text: str) -> Tuple[Set[str], Set[str]]:
    """Names from the marker-delimited docs section: returns
    ``(documented_static, documented_patterns)`` — a name containing
    ``*`` is a dynamic pattern row."""
    try:
        start = md_text.index(CATALOG_BEGIN)
        end = md_text.index(CATALOG_END)
    except ValueError:
        raise ValueError(
            "docs catalog markers not found (metric-catalog:begin/end)")
    block = md_text[start:end]
    names: Set[str] = set()
    patterns: Set[str] = set()
    for line in block.splitlines():
        m = _ROW_NAME.match(line.strip())
        if not m:
            continue
        name = m.group(1)
        (patterns if "*" in name else names).add(name)
    return names, patterns


def pattern_matches(pattern: str, name: str) -> bool:
    """``stage_*_s`` vs ``stage_retrieve_s`` — ``*`` spans any non-empty
    run (the interpolated part is never empty in practice)."""
    rx = "^" + ".+".join(re.escape(p) for p in pattern.split("*")) + "$"
    return re.match(rx, name) is not None
