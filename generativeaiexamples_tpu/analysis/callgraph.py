"""Whole-program interprocedural analysis: the module-level call graph
and the transitive facts tpulint's deep rules fire on.

PR 3's per-module rules see a blocking call only when it sits *directly*
inside a ``with lock:`` block or a jitted function — a helper that
fetches or sleeps under its caller's lock escapes entirely, and nothing
checks lock *ordering* across modules.  This module closes both gaps
with the same bargain as the rest of tpulint (pure AST, no imports of
the analyzed code, deterministic, fast):

* :class:`Program` indexes every function in a set of parsed modules,
  resolves the calls a pure-AST pass *can* resolve — bare names to
  module functions, ``self.m()`` / ``cls.m()`` within the enclosing
  class, ``mod.f()`` / ``from mod import f`` across modules in the
  scanned set, nested defs within their enclosing function — and
  records, per call site, the stack of lockish ``with`` contexts the
  site executes under.
* Three transitive facts are then fixpointed over the graph:
  **may-block** (sleep, HTTP, future ``.result()``, ``device_get``,
  disk syscalls — the lock-discipline vocabulary), **may-sync** (the
  trace-hazard vocabulary: ``.item()``, ``np.asarray`` …), and
  **locks-acquired** (every lock a function may take, directly or via
  any callee).
* The deep rules report on those facts: ``deep-lock`` (a call chain
  that blocks while a lock is held), ``deep-hot-path`` (a jit/hot-path
  root whose call chain syncs or blocks), and ``lock-order`` (a cycle
  in the static lock-acquisition graph — the textbook AB/BA deadlock,
  caught before a thread ever runs).

Resolution is deliberately conservative: an attribute call on an object
of unknown type (``self._qos.order()``) is skipped, not guessed — a
tpulint true positive must stay near-certain.  The runtime counterpart
(observability/lockwatch.py) covers exactly the edges static resolution
cannot see: callbacks wired at construction time cross here as plain
attributes, but at runtime they acquire real locks in a real order.

Lock identity: ``self._lock`` inside class ``C`` of ``engine/qos.py``
becomes node ``engine.qos.C._lock`` — per-class, so the spill pool's
lock and the tier's lock stay distinct nodes; a module-global lock
becomes ``engine.qos._lock``; a function-local lock is scoped under the
function.  The rendered graph (``--lock-graph``) is checked into
docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from generativeaiexamples_tpu.analysis.astutil import (
    ModuleContext, call_name, dotted_name)
from generativeaiexamples_tpu.analysis.findings import Finding
from generativeaiexamples_tpu.analysis.registry import rule
from generativeaiexamples_tpu.analysis.rules import (
    _BLOCKING_UNDER_LOCK_ATTRS, _BLOCKING_UNDER_LOCK_CALLS,
    _DISK_UNDER_LOCK_ATTRS, _LOCK_SAFE_ATTRS, _SYNC_ATTRS, _SYNC_CALLS,
    _jit_decorated, _lockish)

_MAX_CHAIN = 6      # rendered hops before "…" (messages must stay greppable)


# --------------------------------------------------------------------------
# per-function index
# --------------------------------------------------------------------------

@dataclass
class CallSite:
    lineno: int
    target: str                      # qname of the resolved callee
    under: Tuple[str, ...]           # lock ids held here, outermost first


@dataclass
class LockAcquire:
    lineno: int
    lock: str                        # lock id acquired
    under: Tuple[str, ...]           # lock ids already held


@dataclass
class FunctionInfo:
    qname: str                       # "<relpath>::<Class.meth|func>"
    path: str                        # repo-relative module path
    name: str                        # display name (Class.meth / func)
    hot: bool = False                # jit-decorated or `# tpulint: hot-path`
    calls: List[CallSite] = field(default_factory=list)
    acquires: List[LockAcquire] = field(default_factory=list)
    # direct facts: (lineno, op description)
    blocks: List[Tuple[int, str]] = field(default_factory=list)
    syncs: List[Tuple[int, str]] = field(default_factory=list)


def _module_stem(path: str) -> str:
    """'generativeaiexamples_tpu/engine/qos.py' -> 'engine.qos' (the
    package prefix is noise in every rendered name)."""
    stem = path.replace("\\", "/")
    if stem.endswith(".py"):
        stem = stem[:-3]
    parts = [p for p in stem.split("/") if p]
    if len(parts) > 1 and parts[0] == "generativeaiexamples_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1] or parts
    return ".".join(parts)


def _module_dotted(path: str) -> str:
    """Full dotted module name for import resolution."""
    stem = path.replace("\\", "/")
    if stem.endswith(".py"):
        stem = stem[:-3]
    parts = [p for p in stem.split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _ModuleIndex:
    """One module's functions, imports, and class layout."""

    def __init__(self, ctx: ModuleContext) -> None:
        self.ctx = ctx
        self.path = ctx.path
        self.stem = _module_stem(ctx.path)
        self.functions: Dict[str, FunctionInfo] = {}   # local name -> info
        # import bindings: local name -> ("module", dotted) or
        # ("symbol", dotted_module, symbol)
        self.imports: Dict[str, Tuple[str, ...]] = {}
        # names bound at module level: a bare lock name in this set is one
        # shared module-global node, not a per-function local
        self.globals: Set[str] = set()
        for node in self.ctx.tree.body:
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self.globals.add(t.id)
                elif isinstance(t, ast.Tuple):
                    self.globals.update(e.id for e in t.elts
                                        if isinstance(e, ast.Name))
        self._collect_imports()
        self._collect_functions()

    def _collect_imports(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    # `import a.b.c` binds `a`; only the asname form gives
                    # a direct module handle worth resolving through
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.imports[local] = ("module", target)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = ("from", node.module, alias.name)

    def _collect_functions(self) -> None:
        # two phases: register every def first (calls resolve forward —
        # `tick` may call a helper defined below it), then index bodies
        defs: List[Tuple[ast.AST, str]] = []

        def visit(body: Sequence[ast.stmt], prefix: str) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local = prefix + node.name
                    defs.append((node, local))
                    # nested defs resolve only from the enclosing function
                    visit(node.body, local + ".<locals>.")
                elif isinstance(node, ast.ClassDef):
                    visit(node.body, prefix + node.name + ".")
        visit(self.ctx.tree.body, "")
        for node, local in defs:
            self.functions[local] = FunctionInfo(
                qname=f"{self.path}::{local}", path=self.path,
                name=local.replace(".<locals>.", "."))
        for node, local in defs:
            self._index_function(node, local)

    # -- lock identity ----------------------------------------------------

    def _lock_id(self, name: str, local: str) -> str:
        """Resolve a lockish dotted name to a stable node id."""
        if name.startswith("self.") or name.startswith("cls."):
            cls = local.rsplit(".", 1)[0] if "." in local else ""
            tail = name.split(".", 1)[1]
            if cls and "<locals>" not in cls:
                return f"{self.stem}.{cls}.{tail}"
            return f"{self.stem}.{tail}"
        if "." in name:
            return f"{self.stem}.{name}"
        # bare name: module global or function local
        if name in self.globals or name in self.functions \
                or name in self.imports:
            return f"{self.stem}.{name}"
        return f"{self.stem}.{local}.{name}" if local else \
            f"{self.stem}.{name}"

    # -- one function's body ----------------------------------------------

    def _index_function(self, fn: ast.AST, local: str) -> FunctionInfo:
        info = self.functions[local]
        info.hot = (_jit_decorated(fn)
                    or self.ctx.has_marker(fn, "hot-path"))
        cls_prefix = local.rsplit(".", 1)[0] + "." if "." in local else ""

        def classify_call(node: ast.Call, under: Tuple[str, ...]) -> None:
            name = call_name(node)
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            if attr in _LOCK_SAFE_ATTRS:
                return
            if name in _BLOCKING_UNDER_LOCK_CALLS:
                info.blocks.append((node.lineno, f"`{name}`"))
            elif attr in _BLOCKING_UNDER_LOCK_ATTRS:
                info.blocks.append((node.lineno, f"`.{attr}()`"))
            elif attr in _DISK_UNDER_LOCK_ATTRS:
                info.blocks.append((node.lineno, f"`.{attr}()`"))
            if attr in _SYNC_ATTRS:
                info.syncs.append((node.lineno, f"`.{attr}()`"))
            elif name in _SYNC_CALLS:
                info.syncs.append((node.lineno, f"`{name}`"))
            target = self._resolve_local(name, local, cls_prefix)
            if target is not None:
                info.calls.append(CallSite(node.lineno, target, under))

        def scan(node: ast.AST, under: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return          # a closure under a lock does not RUN under it
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held = list(under)
                for item in node.items:
                    lname = _lockish(item.context_expr)
                    if lname:
                        lock = self._lock_id(lname, local)
                        info.acquires.append(
                            LockAcquire(item.context_expr.lineno, lock,
                                        tuple(held)))
                        held.append(lock)
                    else:
                        scan(item.context_expr, tuple(under))
                for stmt in node.body:
                    scan(stmt, tuple(held))
                return
            if isinstance(node, ast.Call):
                classify_call(node, under)
            for child in ast.iter_child_nodes(node):
                scan(child, under)

        for stmt in fn.body:
            scan(stmt, ())
        return info

    # -- call resolution ---------------------------------------------------

    def _resolve_local(self, name: Optional[str], local: str,
                       cls_prefix: str) -> Optional[str]:
        """Resolve a dotted callee name to a local qname or a deferred
        cross-module key ``('xmod', dotted_module, symbol)`` encoded as a
        string the Program finishes resolving (it knows every module)."""
        if not name:
            return None
        if name.startswith("self.") or name.startswith("cls."):
            meth = name.split(".", 1)[1]
            if "." in meth:
                return None             # self._qos.order(): unknown type
            cand = cls_prefix + meth
            if cand in self.functions:
                return f"{self.path}::{cand}"
            return None
        if "." not in name:
            # nested def in the enclosing function wins, then module scope,
            # then a `from mod import f` binding
            nested = f"{local}.<locals>.{name}"
            if nested in self.functions:
                return f"{self.path}::{nested}"
            if name in self.functions:
                return f"{self.path}::{name}"
            bind = self.imports.get(name)
            if bind and bind[0] == "from":
                return f"@{bind[1]}::{bind[2]}"
            return None
        head, rest = name.split(".", 1)
        bind = self.imports.get(head)
        if bind is None:
            return None
        if bind[0] == "module" and "." not in rest:
            return f"@{bind[1]}::{rest}"
        if bind[0] == "from" and "." not in rest:
            # `from pkg import mod` then `mod.f()`
            return f"@{bind[1]}.{bind[2]}::{rest}"
        return None


# --------------------------------------------------------------------------
# the program
# --------------------------------------------------------------------------

class Program:
    """A set of parsed modules plus the resolved call graph and the
    transitive facts the deep rules consume."""

    def __init__(self, contexts: Iterable[ModuleContext]) -> None:
        self.modules: List[_ModuleIndex] = [
            _ModuleIndex(ctx) for ctx in contexts]
        self.functions: Dict[str, FunctionInfo] = {}
        by_dotted: Dict[str, _ModuleIndex] = {}
        for mod in self.modules:
            by_dotted[_module_dotted(mod.path)] = mod
            for local, info in mod.functions.items():
                self.functions[info.qname] = info
        # finish cross-module resolution: '@dotted.module::symbol' keys
        # become real qnames when the module is in the scanned set (tails
        # match too, so running over a subtree still resolves package
        # imports), else the call is dropped
        tails: Dict[str, _ModuleIndex] = {}
        for dotted, mod in by_dotted.items():
            tails.setdefault(dotted.split(".")[-1], mod)
        for info in self.functions.values():
            resolved: List[CallSite] = []
            for site in info.calls:
                if not site.target.startswith("@"):
                    resolved.append(site)
                    continue
                dotted, symbol = site.target[1:].split("::", 1)
                mod = by_dotted.get(dotted) or tails.get(
                    dotted.split(".")[-1])
                if mod is not None and symbol in mod.functions:
                    resolved.append(CallSite(
                        site.lineno, mod.functions[symbol].qname,
                        site.under))
            info.calls = resolved
        self._fixpoint()

    # -- transitive facts --------------------------------------------------

    def _fixpoint(self) -> None:
        # witness per function: ("direct", lineno, op) or ("via", callee,
        # call lineno) — enough to reconstruct one chain per finding
        self.block_why: Dict[str, Tuple] = {}
        self.sync_why: Dict[str, Tuple] = {}
        self.locks_acquired: Dict[str, Dict[str, int]] = {}
        for q, info in self.functions.items():
            if info.blocks:
                lineno, op = min(info.blocks)
                self.block_why[q] = ("direct", lineno, op)
            if info.syncs:
                lineno, op = min(info.syncs)
                self.sync_why[q] = ("direct", lineno, op)
            self.locks_acquired[q] = {a.lock: a.lineno
                                      for a in sorted(info.acquires,
                                                      key=lambda a: a.lineno,
                                                      reverse=True)}
        changed = True
        while changed:
            changed = False
            for q, info in self.functions.items():
                for site in info.calls:
                    if site.target == q:
                        continue
                    if site.target in self.block_why \
                            and q not in self.block_why:
                        self.block_why[q] = ("via", site.target, site.lineno)
                        changed = True
                    if site.target in self.sync_why \
                            and q not in self.sync_why:
                        self.sync_why[q] = ("via", site.target, site.lineno)
                        changed = True
                    callee_locks = self.locks_acquired.get(site.target, {})
                    mine = self.locks_acquired[q]
                    for lock in callee_locks:
                        if lock not in mine:
                            mine[lock] = site.lineno
                            changed = True

    def chain_through_hot(self, start: str, why: Dict[str, Tuple]) -> bool:
        """True when the witness chain from ``start`` passes through a
        hot-marked/jitted function — that function is its own audited
        check root (trace-hazard and deep-hot-path analyze it directly),
        so callers upstream of it do not re-report its deliberate ops."""
        cur: Optional[str] = start
        seen: Set[str] = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            if self.functions[cur].hot:
                return True
            wit = why.get(cur)
            if wit is None or wit[0] == "direct":
                return False
            cur = wit[1]
        return False

    # -- chain rendering ---------------------------------------------------

    def chain(self, start: str, why: Dict[str, Tuple]) -> str:
        """'helper -> fetch -> `requests.get`' — the witness path from a
        function to the operation that gives it the fact."""
        parts: List[str] = []
        cur: Optional[str] = start
        seen: Set[str] = set()
        while cur is not None and cur not in seen and len(parts) < _MAX_CHAIN:
            seen.add(cur)
            info = self.functions[cur]
            parts.append(info.name)
            wit = why.get(cur)
            if wit is None:
                break
            if wit[0] == "direct":
                parts.append(wit[2])
                return " -> ".join(parts)
            cur = wit[1]
        parts.append("…")
        return " -> ".join(parts)

    # -- the lock graph ----------------------------------------------------

    def lock_edges(self) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
        """(held, acquired) -> (file, line, how) — first witness per edge,
        from both shapes: a nested ``with`` in one function, and a call
        made under a lock to a function that (transitively) acquires
        another."""
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def add(a: str, b: str, path: str, line: int, how: str) -> None:
            if a == b:
                return                   # RLock re-entry is not an ordering
            key = (a, b)
            wit = (path, line, how)
            if key not in edges or wit < edges[key]:
                edges[key] = wit
        for info in self.functions.values():
            for acq in info.acquires:
                for held in acq.under:
                    add(held, acq.lock, info.path, acq.lineno,
                        f"nested `with` in `{info.name}`")
            for site in info.calls:
                if not site.under:
                    continue
                for lock, _ in sorted(
                        self.locks_acquired.get(site.target, {}).items()):
                    for held in site.under:
                        add(held, lock, info.path, site.lineno,
                            f"`{info.name}` calls "
                            f"`{self.functions[site.target].name}`")
        return edges

    def lock_cycles(self) -> List[List[Tuple[str, str]]]:
        """Elementary cycles in the lock graph, as edge lists, one per
        strongly-connected component (deterministic: the cycle walk
        starts from the smallest node and follows smallest successors)."""
        edges = self.lock_edges()
        succ: Dict[str, List[str]] = {}
        for (a, b) in edges:
            succ.setdefault(a, []).append(b)
        for outs in succ.values():
            outs.sort()
        # Tarjan SCC, iterative
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(root: str) -> None:
            work = [(root, iter(succ.get(root, [])))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on.add(nxt)
                        work.append((nxt, iter(succ.get(nxt, []))))
                        advanced = True
                        break
                    if nxt in on:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for node in sorted(set(a for a, _ in edges)
                           | set(b for _, b in edges)):
            if node not in index:
                strongconnect(node)

        cycles: List[List[Tuple[str, str]]] = []
        for comp in sccs:
            comp_set = set(comp)
            # walk one representative cycle: smallest node, smallest
            # in-component successor each hop, until we return
            start = comp[0]
            path = [start]
            seen = {start}
            cur = start
            while True:
                nxt = next((n for n in succ.get(cur, [])
                            if n in comp_set and (n == start or n not in seen)),
                           None)
                if nxt is None or nxt == start:
                    break
                path.append(nxt)
                seen.add(nxt)
                cur = nxt
            cycle = [(path[i], path[(i + 1) % len(path)])
                     for i in range(len(path))]
            cycles.append(cycle)
        return sorted(cycles)

    def render_lock_graph(self) -> str:
        """The checked-in graph (docs/static_analysis.md): one sorted
        line per edge with its first witness."""
        edges = self.lock_edges()
        lines = []
        for (a, b), (path, lineno, how) in sorted(edges.items()):
            lines.append(f"{a} -> {b}    [{path}:{lineno} {how}]")
        return "\n".join(lines)


# --------------------------------------------------------------------------
# the deep rules
# --------------------------------------------------------------------------
# Program-scoped: the engine runs each ONCE over the whole scanned tree
# (analyze_source wraps a single module into a one-module Program, so the
# per-rule fixtures in tests/test_tpulint.py exercise the same code).

@rule("deep-lock", "error",
      "Blocking call (sleep, HTTP, future .result(), TPU fetch, disk "
      "I/O) reached through a call chain while a lock is held — the "
      "interprocedural reach of lock-discipline",
      scope="program")
def check_deep_lock(program: Program) -> Iterable[Finding]:
    """Fires on the CALL SITE under the lock whose resolved callee
    may-block (direct blocking under a lock stays lock-discipline's);
    the message carries the witness chain down to the operation."""
    for info in program.functions.values():
        for site in info.calls:
            if not site.under or site.target not in program.block_why:
                continue
            lock = site.under[-1]
            chain = program.chain(site.target, program.block_why)
            yield Finding(
                info.path, site.lineno, "deep-lock", "error",
                f"`{info.name}` holds `{lock}` while the call chain "
                f"`{chain}` blocks — every thread contending on the lock "
                "stalls behind it; move the call outside the critical "
                "section or make the callee non-blocking")


@rule("deep-hot-path", "error",
      "Host sync or blocking call reached through a call chain from a "
      "jit-compiled or `# tpulint: hot-path` function — the "
      "interprocedural reach of trace-hazard",
      scope="program")
def check_deep_hot_path(program: Program) -> Iterable[Finding]:
    """Reports at the hot root's call site; a callee that is itself
    hot-marked is its own check root (trace-hazard and this rule both
    analyze it directly) and is skipped here to keep one finding per
    hazard."""
    for info in program.functions.values():
        if not info.hot:
            continue
        for site in info.calls:
            callee = program.functions[site.target]
            if callee.hot:
                continue
            why = None
            kind = ""
            if site.target in program.sync_why:
                why, kind = program.sync_why, "forces a host sync"
            elif site.target in program.block_why:
                why, kind = program.block_why, "blocks"
            if why is None or program.chain_through_hot(site.target, why):
                continue
            chain = program.chain(site.target, why)
            yield Finding(
                info.path, site.lineno, "deep-hot-path", "error",
                f"hot-path `{info.name}` reaches `{chain}` which {kind} — "
                "per-tick host work serializes the dispatch pipeline; "
                "batch it outside the hot region")


@rule("lock-order", "error",
      "Cycle in the static lock-acquisition graph (lock B taken while "
      "holding A on one path, A while holding B on another) — two "
      "threads interleaving those paths deadlock",
      scope="program")
def check_lock_order(program: Program) -> Iterable[Finding]:
    edges = program.lock_edges()
    for cycle in program.lock_cycles():
        names = [a for a, _ in cycle] + [cycle[0][0]]
        witnesses = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
            f" ({edges[(a, b)][2]})"
            for a, b in cycle if (a, b) in edges)
        path, lineno, _ = edges[cycle[0]]
        yield Finding(
            path, lineno, "lock-order", "error",
            f"lock-order cycle `{' -> '.join(names)}` — acquisition "
            f"orders conflict ({witnesses}); pick one global order or "
            "drop a lock from one path")
