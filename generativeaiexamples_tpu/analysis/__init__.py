"""tpulint — in-tree static analysis for TPU-serving hazards.

The stack is a multi-threaded, multi-server TPU dataplane (scheduler
ticks, encoder micro-batching, flight recorder), and the hazard classes
that kill such systems — silent host↔device syncs on the decode path,
jit recompile churn, blocking calls under locks, wall-clock interval
arithmetic, untimed network I/O, silently-swallowed exceptions — are
exactly the ones reviewers keep catching by hand.  tpulint encodes those
review rules as an AST pass over the package and gates every PR: a
tier-1 test (tests/test_tpulint.py) runs the analyzer over the whole
tree and fails on any unsuppressed, non-baselined finding.

Entry points:

  * ``python -m generativeaiexamples_tpu.analysis <paths>`` — the CLI
    (human or ``--json`` output, non-zero exit on findings; ``make lint``).
  * :func:`run_paths` — the library API the self-check test uses.

See ``docs/static_analysis.md`` for the rule catalog, suppression
(``# tpulint: disable=<rule>``) and baseline workflow, and how to add a
rule.
"""

from generativeaiexamples_tpu.analysis.findings import Finding
from generativeaiexamples_tpu.analysis.registry import RULES, Rule, rule
from generativeaiexamples_tpu.analysis.engine import Report, analyze_file, run_paths

# importing rules populates the registry
from generativeaiexamples_tpu.analysis import rules as _rules  # noqa: F401

__all__ = ["Finding", "RULES", "Rule", "rule", "Report", "analyze_file",
           "run_paths"]
