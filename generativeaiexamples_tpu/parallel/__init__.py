"""Parallelism foundation: device mesh, sharding rules, collectives, ring attention.

The reference has **no in-tree parallelism** — DP/TP/PP are config knobs into
external NeMo/Megatron containers over NCCL (ref: finetuning/Gemma/lora.ipynb
cell 26; SURVEY §2.4/§5.8). This package is the TPU-native replacement: a
`jax.sharding.Mesh` over ICI/DCN, NamedSharding rules for params and
activations, and XLA collectives instead of NCCL process groups.
"""

from generativeaiexamples_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    local_mesh,
)
from generativeaiexamples_tpu.parallel.ring_attention import (  # noqa: F401
    sequence_parallel_attention,
)
from generativeaiexamples_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    logical_to_spec,
    shard_params,
)
