"""Device-mesh construction over ICI (intra-slice) and DCN (cross-slice).

Replaces the reference's NCCL process-group world (inside NIM/TRT-LLM via
``INFERENCE_GPU_COUNT``, ref docker-compose-nim-ms.yaml:18-20, and NeMo
trainer TP/PP, ref Gemma/lora.ipynb cell 26) with an explicit
`jax.sharding.Mesh`. Axis conventions:

  * ``data``    — batch/data parallel (gradient all-reduce rides ICI)
  * ``fsdp``    — fully-sharded params (reduce-scatter/all-gather)
  * ``tensor``  — megatron-style tensor parallel (activation collectives)
  * ``seq``     — sequence/context parallel (ring attention, §5.7)
  * ``expert``  — MoE expert parallel (all_to_all dispatch)

Meshes are created with `jax.make_mesh`, which orders axes so the innermost
(fastest-varying) axis maps to physically adjacent devices — put ``tensor``
last so its collectives ride the shortest ICI hops.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

_distributed_initialized = False

INFER_AXES: Tuple[str, ...] = ("data", "tensor")
TRAIN_AXES: Tuple[str, ...] = ("data", "fsdp", "tensor")
LONGCTX_AXES: Tuple[str, ...] = ("data", "seq", "tensor")
MOE_AXES: Tuple[str, ...] = ("data", "expert", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh spec, e.g. MeshConfig(axes=("data","tensor"), shape=(1, 8))."""

    axes: Tuple[str, ...] = INFER_AXES
    shape: Tuple[int, ...] = ()  # empty = auto: all devices on the last axis

    def resolve_shape(self, n_devices: int) -> Tuple[int, ...]:
        if self.shape:
            if math.prod(self.shape) != n_devices:
                raise ValueError(
                    f"mesh shape {self.shape} needs {math.prod(self.shape)} devices, "
                    f"have {n_devices}")
            return self.shape
        return (1,) * (len(self.axes) - 1) + (n_devices,)


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh over all (or the given) devices.

    On real TPU slices `jax.make_mesh` picks an ICI-friendly device order;
    on CPU simulation (xla_force_host_platform_device_count) order is trivial.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = config.resolve_shape(len(devices))
    # Auto axis types: the partitioner infers intermediate shardings (jax 0.9
    # `make_mesh` defaults to Explicit, which rejects ambiguous gathers like
    # token-embedding lookups instead of inferring).
    auto = (jax.sharding.AxisType.Auto,) * len(config.axes)
    if devices == list(jax.devices()):
        return jax.make_mesh(shape, config.axes, axis_types=auto)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, config.axes, axis_types=auto)


def local_mesh(axes: Tuple[str, ...] = INFER_AXES) -> Mesh:
    """All local devices on the last axis — the single-host v5e-8 default
    (1×8 ICI ring, tensor-parallel serving)."""
    return create_mesh(MeshConfig(axes=axes))


def parse_mesh_shape(spec: str, axes: Tuple[str, ...] = INFER_AXES) -> MeshConfig:
    """Parse 'AxB[xC...]' (e.g. '1x8') into a MeshConfig."""
    if not spec:
        return MeshConfig(axes=axes)
    dims = tuple(int(p) for p in spec.lower().split("x"))
    if len(dims) != len(axes):
        raise ValueError(f"mesh spec {spec!r} has {len(dims)} dims for axes {axes}")
    return MeshConfig(axes=axes, shape=dims)


# ---------------------------------------------------------------------------
# Multi-host / multi-slice (DCN) support
# ---------------------------------------------------------------------------

def initialize_distributed(coordinator_address: str = "",
                           num_processes: int = 0,
                           process_id: int = -1) -> bool:
    """Bring up the multi-host JAX runtime (the NCCL-world replacement for
    cross-host serving/training — SURVEY §5.8: XLA collectives over ICI
    within a slice and DCN across slices replace NCCL entirely).

    On TPU pods `jax.distributed.initialize()` self-discovers everything;
    elsewhere the coordinator triple comes from the arguments or the
    standard env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID). A single-process run (nothing configured) is a no-op
    returning False, so the same entrypoints serve laptop and pod.
    Idempotent: a second call (two entrypoints bootstrapping the same
    process) returns True instead of tripping jax's only-once guard.
    """
    import os

    global _distributed_initialized
    if _distributed_initialized:
        return True
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS", ""))
    on_tpu_pod = (os.environ.get("TPU_WORKER_HOSTNAMES")
                  or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if not coordinator_address and not on_tpu_pod:
        return False
    kwargs = {}
    if coordinator_address:
        num_processes = (num_processes or
                         int(os.environ.get("JAX_NUM_PROCESSES", "0")))
        process_id = (process_id if process_id >= 0 else
                      int(os.environ.get("JAX_PROCESS_ID", "-1")))
        if num_processes < 1 or process_id < 0:
            # defaulting to a world of size 1 would turn a half-configured
            # N-host launch into N silent independent replicas
            raise ValueError(
                "JAX_COORDINATOR_ADDRESS is set but JAX_NUM_PROCESSES / "
                "JAX_PROCESS_ID are not — a multi-host launch must state "
                "its world size explicitly")
        kwargs = {"coordinator_address": coordinator_address,
                  "num_processes": num_processes, "process_id": process_id}
    jax.distributed.initialize(**kwargs)
    _distributed_initialized = True
    return True


def _default_slice_id(device) -> int:
    """Which DCN island a device belongs to: TPU slices expose
    ``slice_index``; everything else degrades to the owning process.
    CPU backends report a constant slice_index even across processes (the
    multi-process CPU test rig), so only TPUs trust it."""
    sid = getattr(device, "slice_index", None)
    if sid is None or device.platform != "tpu":
        return device.process_index
    return sid


def create_hybrid_mesh(axes: Tuple[str, ...],
                       ici_shape: Tuple[int, ...],
                       dcn_shape: Tuple[int, ...],
                       devices: Optional[Sequence[jax.Device]] = None,
                       slice_id_fn=None) -> Mesh:
    """Mesh spanning multiple ICI slices joined by DCN.

    Per mesh axis ``i`` the global extent is ``dcn_shape[i] *
    ici_shape[i]`` with DCN-major ordering, so a collective along an axis
    whose ``dcn_shape`` entry is 1 NEVER crosses the data-center network —
    the scaling-book recipe: put ``data`` (one gradient all-reduce per
    step) across DCN, keep ``tensor``/``seq``/``fsdp`` (per-layer
    activation collectives) inside a slice. Rule tables (sharding.py) work
    unchanged: axis names don't change, only the device placement does.

    ``slice_id_fn`` exists for CPU-simulated tests (virtual devices carry
    no slice_index); production uses the devices' own topology metadata.
    """
    if len(axes) != len(ici_shape) or len(axes) != len(dcn_shape):
        raise ValueError(f"axes {axes} vs ici {ici_shape} / dcn {dcn_shape} "
                         "rank mismatch")
    devices = list(devices if devices is not None else jax.devices())
    auto = (jax.sharding.AxisType.Auto,) * len(axes)
    if slice_id_fn is None:
        # real hardware: prefer mesh_utils' topology-aware construction
        # (intra-slice ICI adjacency), same DCN-major axis semantics; fall
        # through to the explicit grouping only when devices lack topology
        # metadata (CPU simulation) or the shapes don't match its model
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
            return Mesh(arr, axes, axis_types=auto)
        except Exception as exc:
            # the fallback grouping is correct but topology-unaware
            # (intra-slice order = enumeration order); on a real pod that
            # costs ICI hops, so the degradation must be visible
            import logging
            logging.getLogger(__name__).warning(
                "mesh_utils hybrid construction unavailable (%s); using "
                "slice-grouped fallback placement", exc)
    slice_id_fn = slice_id_fn or _default_slice_id
    slices: dict = {}
    for d in devices:
        slices.setdefault(slice_id_fn(d), []).append(d)
    n_slices = math.prod(dcn_shape)
    per_slice = math.prod(ici_shape)
    if len(slices) != n_slices:
        raise ValueError(f"dcn shape {dcn_shape} needs {n_slices} slices, "
                         f"devices form {len(slices)}")
    sizes = {len(v) for v in slices.values()}
    if sizes != {per_slice}:
        raise ValueError(f"ici shape {ici_shape} needs {per_slice} devices "
                         f"per slice, slices have {sorted(sizes)}")
    # (*dcn_shape, *ici_shape) with slices DCN-major, then interleave the
    # per-axis (dcn_i, ici_i) dim pairs and fuse them
    ordered = [d for sid in sorted(slices) for d in slices[sid]]
    arr = np.asarray(ordered, dtype=object).reshape(*dcn_shape, *ici_shape)
    n = len(axes)
    arr = arr.transpose(*(p for i in range(n) for p in (i, n + i)))
    arr = arr.reshape(tuple(dcn_shape[i] * ici_shape[i] for i in range(n)))
    return Mesh(arr, axes, axis_types=auto)
