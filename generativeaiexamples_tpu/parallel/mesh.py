"""Device-mesh construction over ICI (intra-slice) and DCN (cross-slice).

Replaces the reference's NCCL process-group world (inside NIM/TRT-LLM via
``INFERENCE_GPU_COUNT``, ref docker-compose-nim-ms.yaml:18-20, and NeMo
trainer TP/PP, ref Gemma/lora.ipynb cell 26) with an explicit
`jax.sharding.Mesh`. Axis conventions:

  * ``data``    — batch/data parallel (gradient all-reduce rides ICI)
  * ``fsdp``    — fully-sharded params (reduce-scatter/all-gather)
  * ``tensor``  — megatron-style tensor parallel (activation collectives)
  * ``seq``     — sequence/context parallel (ring attention, §5.7)
  * ``expert``  — MoE expert parallel (all_to_all dispatch)

Meshes are created with `jax.make_mesh`, which orders axes so the innermost
(fastest-varying) axis maps to physically adjacent devices — put ``tensor``
last so its collectives ride the shortest ICI hops.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

INFER_AXES: Tuple[str, ...] = ("data", "tensor")
TRAIN_AXES: Tuple[str, ...] = ("data", "fsdp", "tensor")
LONGCTX_AXES: Tuple[str, ...] = ("data", "seq", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh spec, e.g. MeshConfig(axes=("data","tensor"), shape=(1, 8))."""

    axes: Tuple[str, ...] = INFER_AXES
    shape: Tuple[int, ...] = ()  # empty = auto: all devices on the last axis

    def resolve_shape(self, n_devices: int) -> Tuple[int, ...]:
        if self.shape:
            if math.prod(self.shape) != n_devices:
                raise ValueError(
                    f"mesh shape {self.shape} needs {math.prod(self.shape)} devices, "
                    f"have {n_devices}")
            return self.shape
        return (1,) * (len(self.axes) - 1) + (n_devices,)


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh over all (or the given) devices.

    On real TPU slices `jax.make_mesh` picks an ICI-friendly device order;
    on CPU simulation (xla_force_host_platform_device_count) order is trivial.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = config.resolve_shape(len(devices))
    # Auto axis types: the partitioner infers intermediate shardings (jax 0.9
    # `make_mesh` defaults to Explicit, which rejects ambiguous gathers like
    # token-embedding lookups instead of inferring).
    auto = (jax.sharding.AxisType.Auto,) * len(config.axes)
    if devices == list(jax.devices()):
        return jax.make_mesh(shape, config.axes, axis_types=auto)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, config.axes, axis_types=auto)


def local_mesh(axes: Tuple[str, ...] = INFER_AXES) -> Mesh:
    """All local devices on the last axis — the single-host v5e-8 default
    (1×8 ICI ring, tensor-parallel serving)."""
    return create_mesh(MeshConfig(axes=axes))


def parse_mesh_shape(spec: str, axes: Tuple[str, ...] = INFER_AXES) -> MeshConfig:
    """Parse 'AxB[xC...]' (e.g. '1x8') into a MeshConfig."""
    if not spec:
        return MeshConfig(axes=axes)
    dims = tuple(int(p) for p in spec.lower().split("x"))
    if len(dims) != len(axes):
        raise ValueError(f"mesh spec {spec!r} has {len(dims)} dims for axes {axes}")
    return MeshConfig(axes=axes, shape=dims)
