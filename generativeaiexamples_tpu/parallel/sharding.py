"""Logical-axis sharding rules → NamedSharding for parameter pytrees.

The scaling-book recipe: annotate each parameter dimension with a *logical*
axis name ("embed", "mlp", "heads", "vocab", ...), then map logical names to
mesh axes via a rule table. Changing the parallelism strategy (pure TP for
serving vs FSDP+TP for training) is a rule-table swap — the model code never
mentions mesh axes.

This is the in-tree replacement for the reference's Megatron
``tensor_model_parallel_size``/``pipeline_model_parallel_size`` knobs
(ref: finetuning/Gemma/lora.ipynb cell 26): here the same intent is expressed
as (logical axis → mesh axis) rules and XLA inserts the collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical dimension names used by models in generativeaiexamples_tpu.models.
Logical = Tuple[Optional[str], ...]


@dataclass(frozen=True)
class ShardingRules:
    """Mapping logical axis name → mesh axis name (or None = replicate)."""

    rules: Mapping[str, Optional[str]]

    def mesh_axes(self, logical: Logical) -> P:
        return P(*(self.rules.get(name) if name else None for name in logical))


# Serving on one host: megatron-style TP over the "tensor" axis.
INFERENCE_RULES = ShardingRules(rules={
    "vocab": "tensor",
    "vocab_table": None,
    "embed_table": "tensor",        # embed/unembed split over vocab
    "embed": None,            # replicate the model dim
    "heads": "tensor",        # attention heads split (Q)
    "kv_heads": "tensor",     # KV heads split (GQA: requires kv_heads % tp == 0)
    "mlp": "tensor",          # FFN hidden split
    "batch": "data",
    "seq": None,
    "expert": "expert",
})

# Training: FSDP over params + optional TP.
TRAIN_RULES = ShardingRules(rules={
    "vocab": "tensor",
    "vocab_table": None,
    "embed_table": "fsdp",
    "embed": "fsdp",          # shard the big dim of every matrix over fsdp
    "heads": "tensor",
    "kv_heads": "tensor",
    "mlp": "tensor",
    "batch": "data",
    "seq": None,
    "expert": "expert",
})

# Long-context serving: sequence axis sharded for ring attention (§5.7).
LONG_CONTEXT_RULES = ShardingRules(rules={
    "vocab": "tensor",
    "vocab_table": None,
    "embed_table": "tensor",
    "embed": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "mlp": "tensor",
    "batch": "data",
    "seq": "seq",
    "expert": "expert",
})


def logical_to_spec(logical: Logical, rules: ShardingRules, mesh: Mesh) -> P:
    """Resolve a logical annotation to a PartitionSpec valid on ``mesh``
    (axes absent from the mesh degrade to replication)."""
    axes = []
    for name in logical:
        mesh_axis = rules.rules.get(name) if name else None
        axes.append(mesh_axis if mesh_axis in mesh.axis_names else None)
    return P(*axes)


def shard_params(params: Any, logical_tree: Any, rules: ShardingRules,
                 mesh: Mesh) -> Any:
    """Device-put a parameter pytree according to its logical annotations.

    ``logical_tree`` mirrors ``params`` with a Logical tuple per leaf (models
    expose it via ``Model.logical_axes()``).
    """
    def place(leaf, logical):
        spec = logical_to_spec(logical, rules, mesh)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, logical_tree,
                        is_leaf=lambda x: isinstance(x, tuple) and all(
                            isinstance(e, (str, type(None))) for e in x))


def sharding_tree(logical_tree: Any, rules: ShardingRules, mesh: Mesh) -> Any:
    """Build a pytree of NamedShardings (for jit in_shardings/out_shardings)."""
    def to_sharding(logical):
        return NamedSharding(mesh, logical_to_spec(logical, rules, mesh))

    return jax.tree.map(to_sharding, logical_tree,
                        is_leaf=lambda x: isinstance(x, tuple) and all(
                            isinstance(e, (str, type(None))) for e in x))
