"""Sequence/context parallelism: ring attention + Ulysses head-scatter.

The reference has no long-context story at all — its context budget is
retrieval-side trimming to 1,500 tokens (ref RAG/src/chain_server/utils.py:103
``DEFAULT_MAX_CONTEXT`` and ``LimitRetrievedNodesLength``, utils.py:106-134)
and the Megatron ``sequence_parallel`` knobs its notebooks never set (ref
finetuning/Gemma/lora.ipynb cell 26 sets only TP/PP). Here long context is
first-class: activations are sharded along the sequence dimension over the
``seq`` mesh axis and attention runs as an SPMD program over the ICI ring.

Two interchangeable strategies, both exposed through :func:`sequence_parallel_attention`:

* **Ring attention** — each device keeps its local Q block resident and
  rotates K/V blocks around the ring with ``lax.ppermute`` (one ICI hop per
  step, n_seq steps total), accumulating blockwise softmax in the streaming
  (m, l, acc) form — flash attention's online softmax, distributed. Works for
  any head count; K/V traffic per step is (B, S/n, kv_heads, hd), which on a
  v5e ring overlaps with the block matmul.
* **Ulysses** — ``lax.all_to_all`` re-shards from sequence-split to
  head-split, runs ordinary full attention locally (full sequence, H/n
  heads), and re-shards back. Two all-to-alls instead of n ppermutes; needs
  n_heads and kv_heads divisible by the axis size.

Both compute causal masking from *global* positions derived from
``lax.axis_index``, so results are bitwise-independent of the mesh size up to
float reassociation. Validated against ``ops.attention.mha_prefill`` on an
8-device CPU mesh (tests/test_ring_attention.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF, mha_prefill


def _gqa_block_scores(q: jnp.ndarray, k: jnp.ndarray, scale: float) -> jnp.ndarray:
    """(B,S,KV,G,D) x (B,T,KV,D) -> (B,KV,G,S,T) f32 scores (no repeat_kv)."""
    return jnp.einsum("bskgd,btkd->bkgst", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale


def _ring_body(q, k, v, kv_lens, *, axis_name: str, causal: bool):
    """shard_map body: local Q stays, K/V rotate around ``axis_name``.

    q: (B, S_loc, H, D); k, v: (B, T_loc, KV, D) — the local shards.
    kv_lens: (B,) replicated global valid lengths (right-padded batches).
    """
    idx = lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)
    B, S_loc, H, D = q.shape
    T_loc, KV = k.shape[1], k.shape[2]
    G = H // KV
    scale = 1.0 / (D ** 0.5)

    qg = q.reshape(B, S_loc, KV, G, D)
    q_pos = idx * S_loc + jnp.arange(S_loc, dtype=jnp.int32)          # (S_loc,)
    # ppermute: device i sends to i+1, so after t steps we hold chunk (i - t).
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((B, KV, G, S_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KV, G, S_loc), jnp.float32)
    acc0 = jnp.zeros((B, KV, G, S_loc, D), jnp.float32)

    def accumulate(t, k_c, v_c, m, l, acc):
        src = (idx - t) % n
        kv_pos = src * T_loc + jnp.arange(T_loc, dtype=jnp.int32)     # (T_loc,)
        valid = kv_pos[None, :] < kv_lens[:, None]                    # (B, T_loc)
        mask = valid[:, None, None, None, :]                          # (B,1,1,1,T)
        if causal:
            mask = mask & (kv_pos[None, :] <= q_pos[:, None])[None, None, None]
        s = _gqa_block_scores(qg, k_c, scale)                         # (B,KV,G,S,T)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp(NEG_INF - NEG_INF) would be 1 for rows with no live key yet;
        # keep the correction 0 there so fully-masked blocks contribute nothing.
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.where(m > NEG_INF / 2, jnp.exp(m - m_new), 0.0)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bkgst,btkd->bkgsd", p, v_c.astype(jnp.float32))
        return m_new, l, acc

    def maybe_accumulate(t, k_c, v_c, m, l, acc):
        """Skip blocks entirely in the causal future (their mask is all-off).

        Safe under shard_map: `accumulate` contains no collectives, so a
        per-device predicate is fine. This halves attention FLOPs on average;
        per-*step* wall clock is still set by the busiest device (the known
        ring imbalance — a striped/zigzag chunk layout is the follow-up).
        """
        if not causal:
            return accumulate(t, k_c, v_c, m, l, acc)
        src = (idx - t) % n
        live = src * T_loc <= idx * S_loc + (S_loc - 1)
        return lax.cond(live,
                        lambda ops: accumulate(t, *ops),
                        lambda ops: (ops[2], ops[3], ops[4]),
                        (k_c, v_c, m, l, acc))

    def step(t, carry):
        k_c, v_c, m, l, acc = carry
        m, l, acc = maybe_accumulate(t, k_c, v_c, m, l, acc)
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        return k_c, v_c, m, l, acc

    # last block accumulates outside the loop: no wasted final K/V rotation
    k_c, v_c, m, l, acc = lax.fori_loop(0, n - 1, step, (k, v, m0, l0, acc0))
    _, l, acc = maybe_accumulate(n - 1, k_c, v_c, m, l, acc)
    out = acc / jnp.where(l > 0, l, 1.0)[..., None]        # (B, KV, G, S, D)
    out = jnp.transpose(out, (0, 3, 1, 2, 4))              # -> (B, S, KV, G, D)
    return out.reshape(B, S_loc, H, D).astype(q.dtype)


def _ulysses_body(q, k, v, kv_lens, *, axis_name: str, causal: bool):
    """shard_map body: all_to_all seq-split -> head-split, local full attention.

    Requires n_heads % n == 0 and kv_heads % n == 0 (checked by the wrapper).
    """
    a2a = partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    qh = a2a(q, split_axis=2, concat_axis=1)     # (B, S, H/n, D)
    kh = a2a(k, split_axis=2, concat_axis=1)     # (B, S, KV/n, D)
    vh = a2a(v, split_axis=2, concat_axis=1)
    B, S = qh.shape[0], qh.shape[1]
    kv_mask = jnp.arange(S, dtype=jnp.int32)[None, :] < kv_lens[:, None]
    out = mha_prefill(qh, kh, vh, kv_mask=kv_mask, causal=causal)
    return a2a(out, split_axis=1, concat_axis=2)  # back to (B, S/n, H, D)


def sequence_parallel_attention(
        q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
        mesh: Mesh, axis: str = "seq", impl: str = "ring",
        kv_lens: Optional[jnp.ndarray] = None,
        causal: bool = True) -> jnp.ndarray:
    """Causal self-attention with Q/K/V sharded on dim 1 over ``mesh[axis]``.

    q: (B, S, H, D); k, v: (B, S, KV, D) — *global* shapes; dim 1 must be
    divisible by the axis size. kv_lens: (B,) valid lengths for right-padded
    batches (defaults to S). Composable under jit/scan: the shard_map is
    closed over ``mesh`` and partitions only the sequence dimension, so head
    and batch sharding from outer rules pass through untouched.
    """
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"impl must be 'ring' or 'ulysses', got {impl!r}")
    n = mesh.shape[axis]
    B, S, H, D = q.shape
    KV = k.shape[2]
    if S % n != 0:
        raise ValueError(f"seq len {S} not divisible by {axis} axis size {n}")
    # Batch and head sharding from the outer mesh pass straight through: the
    # bodies are pointwise in batch and (for ring) in heads, so we map those
    # dims onto their usual axes instead of forcing an all-gather.
    data_ax = "data" if "data" in mesh.axis_names else None
    tp_ax = "tensor" if "tensor" in mesh.axis_names else None
    n_t = mesh.shape[tp_ax] if tp_ax else 1
    if impl == "ulysses" and ((H // n_t) % n or (KV // n_t) % n):
        raise ValueError(
            f"ulysses needs per-TP-shard heads divisible by {axis} axis size: "
            f"H={H}/{n_t} KV={KV}/{n_t} n={n}")
    if kv_lens is None:
        kv_lens = jnp.full((B,), S, jnp.int32)
    body = {"ring": _ring_body, "ulysses": _ulysses_body}[impl]
    seq_spec = P(data_ax, axis, tp_ax, None)
    fn = jax.shard_map(
        partial(body, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(data_ax)),
        out_specs=seq_spec,
        check_vma=False)
    return fn(q, k, v, kv_lens)
