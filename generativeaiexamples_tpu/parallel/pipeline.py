"""Pipeline parallelism — GPipe-style microbatch schedule over a "stage" axis.

The reference's pipeline parallelism is a NeMo/Megatron config knob
(``pipeline_model_parallel_size``, ref finetuning/Gemma/lora.ipynb cell 26)
executed by an external container over NCCL point-to-point sends. The
TPU-native counterpart: layers are stage-sharded over a mesh axis and
activations flow stage-to-stage via ``ppermute`` inside one ``shard_map``
— a single SPMD program, no host-side stage orchestration, differentiable
end to end (autodiff reverses the schedule for the backward pass, so a
pipelined train step is just ``jax.grad`` over this forward).

Schedule (classic GPipe): with S stages and M microbatches, the loop runs
``M + S - 1`` ticks. At tick t, stage 0 injects microbatch t (while t < M),
every stage runs its local layer chunk on what it received, and the last
stage banks its output for microbatch ``t - (S - 1)``. The bubble fraction
is (S-1)/(M+S-1) — callers pick M ≥ S for sane utilization.

Scope: the decoder block stack — dense (mlp glu/plain) AND MoE blocks
(experts stay stage-local; only the scalar load-balance aux crosses
stages). Everything outside the blocks (embedding, final norm, unembed)
runs outside the shard_map on replicated parameters, so only the deep
per-layer weights are stage-sharded — exactly the memory that motivates
PP.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.ops.attention import mha_prefill
from generativeaiexamples_tpu.ops.layers import rotary_embedding

Params = Dict[str, Any]

PIPELINE_AXES: Tuple[str, ...] = ("data", "stage")


def stage_params(params: Params, n_stages: int) -> Params:
    """Reshape every stacked layer leaf (L, ...) → (S, L/S, ...) so the
    leading axis can shard over "stage"."""
    L = params["layers"]["wq"].shape[0]
    if L % n_stages:
        raise ValueError(f"n_layers ({L}) must divide by n_stages "
                         f"({n_stages})")
    staged = jax.tree.map(
        lambda w: w.reshape(n_stages, L // n_stages, *w.shape[1:]),
        params["layers"])
    out = dict(params)
    out["layers"] = staged
    return out


def place_staged_params(params: Params, cfg: llama.LlamaConfig,
                        mesh: Mesh, n_stages: int) -> Params:
    """Device-put: staged layer stacks sharded over "stage" (leading axis),
    embedding/norm/unembed replicated."""
    staged = stage_params(params, n_stages)
    out = {}
    for name, leaf in staged.items():
        if name == "layers":
            out["layers"] = jax.tree.map(
                lambda w: jax.device_put(
                    w, NamedSharding(mesh, P("stage"))),
                leaf)
        else:
            out[name] = jax.device_put(leaf, NamedSharding(mesh, P()))
    return out


def _run_stage(cfg: llama.LlamaConfig, layers_local: Params,
               x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run this stage's (L/S)-layer chunk (full causal attention).
    Returns (x, aux) — aux is this chunk's summed MoE load-balance loss
    (0 for dense blocks), so MoE models pipeline like dense ones: experts
    stay stage-local (the routing einsums need no collectives) and only
    the scalar aux crosses stages, via the final psum."""
    attn = partial(mha_prefill, causal=True, window=cfg.sliding_window)

    def body(carry, layer):
        h, aux = carry
        h, layer_aux = llama._block(cfg, h, layer, cos, sin, attn, {})
        return (h, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), layers_local)
    return x, aux


def pipelined_forward(params: Params, cfg: llama.LlamaConfig,
                      tokens: jnp.ndarray, mesh: Mesh,
                      n_microbatches: int = 0,
                      return_aux: bool = False):
    """Causal-LM logits with the block stack pipelined over mesh["stage"].

    ``params`` must come from :func:`place_staged_params`. tokens (B, S);
    B must divide by (data-axis size x n_microbatches). Default
    n_microbatches = 2 x stages (bubble ≤ 1/3). MoE blocks compose:
    experts are stage-local, and ``return_aux=True`` returns
    (logits, load-balance aux) on the same scale as llama.forward's.
    """
    S_stages = int(mesh.shape["stage"])
    B, S = tokens.shape
    per_shard = B // int(mesh.shape.get("data", 1))
    if n_microbatches:
        M = n_microbatches
    else:
        # largest divisor of the per-shard batch ≤ 2x stages (bubble ≤ 1/3
        # when the batch allows it, graceful otherwise)
        M = max(m for m in range(1, min(2 * S_stages, per_shard) + 1)
                if per_shard % m == 0)

    h = llama.embed_tokens(params, cfg, tokens)              # (B, S, D)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None],
                                 (B, S))
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

    data = int(mesh.shape.get("data", 1))
    if (B // data) % M:
        raise ValueError(f"per-data-shard batch ({B // data}) must divide "
                         f"by n_microbatches ({M})")

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P("stage"), P("data"), P("data"), P("data")),
             out_specs=(P("data"), P()), check_vma=False)
    def run(layers_stage, h_local, cos_local, sin_local):
        # layers_stage leaves: (1, L/S, ...) → (L/S, ...)
        layers_local = jax.tree.map(lambda w: w[0], layers_stage)
        stage = jax.lax.axis_index("stage")
        b = h_local.shape[0] // M                     # microbatch rows
        mb = h_local.reshape(M, b, *h_local.shape[1:])
        cos_mb = cos_local.reshape(M, b, *cos_local.shape[1:])
        sin_mb = sin_local.reshape(M, b, *sin_local.shape[1:])
        state = jnp.zeros_like(mb[0])                 # in-flight activation
        out = jnp.zeros_like(mb)

        def tick(carry, t):
            state, out, aux = carry
            # receive from the previous stage (one-hop ring shift)
            received = jax.lax.ppermute(
                state, "stage",
                perm=[(i, (i + 1) % S_stages) for i in range(S_stages)])
            inject = mb[jnp.minimum(t, M - 1)]
            x = jnp.where(stage == 0, inject, received)
            # positions are microbatch-dependent: stage s processes
            # microbatch (t - s) at tick t
            m_ix = jnp.clip(t - stage, 0, M - 1)
            x, tick_aux = _run_stage(cfg, layers_local, x,
                                     cos_mb[m_ix], sin_mb[m_ix])
            # bubble ticks run on zero/garbage activations: their router
            # statistics must not leak into the load-balance loss
            valid = (t >= stage) & (t - stage <= M - 1)
            aux = aux + jnp.where(valid, tick_aux, 0.0)
            # last stage banks microbatch t-(S-1)
            o_ix = t - (S_stages - 1)
            bank = ((stage == S_stages - 1) & (o_ix >= 0))
            out = jax.lax.cond(
                bank,
                lambda o: o.at[jnp.clip(o_ix, 0, M - 1)].set(x),
                lambda o: o, out)
            return (x, out, aux), None

        (_, out, aux), _ = jax.lax.scan(tick, (state, out, jnp.float32(0.0)),
                                        jnp.arange(M + S_stages - 1))
        # only the last stage holds real outputs; share them along the ring.
        # aux: each stage owns its layers' contribution — sum over stages,
        # then normalize to llama.forward's per-layer-per-batch scale
        # (each of M microbatches crossed all n_layers once)
        out = jax.lax.psum(
            jnp.where(stage == S_stages - 1, out, jnp.zeros_like(out)),
            "stage")
        aux = jax.lax.psum(aux, "stage") / (cfg.n_layers * M)
        # every data shard computed its own aux; average over "data" so the
        # returned scalar is replicated (out_specs P() asserts that)
        aux = jax.lax.pmean(aux, "data")
        return out.reshape(h_local.shape), aux

    h, aux = run(params["layers"], h, cos, sin)
    logits = llama._unembed(cfg, params, h)
    if return_aux:
        return logits, aux
    return logits
