"""Serving-topology planning: which engine role each worker of a pool runs.

The parallel/ glue for disaggregated prefill/decode serving (ROADMAP item
1): `parallel/{mesh,sharding,pipeline}.py` shape programs WITHIN a worker;
this module shapes the pool ACROSS workers — how many processes run
chunked prefill + KV export (``APP_ENGINE_ROLE=prefill``) versus decode
replicas importing handed-off pages (``role=decode``). bench.py's
disaggregated round and deploy tooling consume the plan; the routing
frontend (server/failover.py) discovers the resulting roles from /health
at runtime, so the plan never has to be communicated out of band.

Prefill:decode sizing. Prefill is compute-bound (one prompt saturates a
chip's MXU), decode is weight-read-bound and batches across requests, so
decode replicas want the larger share of a pool; ~1/3 prefill is the
RAGO-style starting split for chat-shaped traffic (long prompts, short
answers skew higher). The split is MEASURED, not hardcoded (the
Gemma-on-TPU topology study, arxiv 2605.25645, frames it as a workload
property): :func:`tuned_prefill_share` derives it from the latest
``make bench-disagg`` round JSON — per-role worker utilization from the
round's fleet snapshot, confidence-damped by ``router_imbalance`` (a
decode pool whose replicas were unevenly hit is noisy evidence) — with
``APP_PREFILL_SHARE`` as the operator override. The router's least-loaded
scoring absorbs the residual error within a role either way.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

DEFAULT_PREFILL_SHARE = 1.0 / 3.0
# derived shares clamp here: a bench round must never plan a pool with a
# starved role (plan_engine_roles keeps >= 1 of each regardless)
_SHARE_LO, _SHARE_HI = 0.15, 0.6

_ROUND_GLOBS = ("MULTICHIP_r*.json", "BENCH_r*.json")
_ROUND_NUM = re.compile(r"_r(\d+)\.json$")


def _latest_round_with_disagg(search_dir: str) -> Optional[Tuple[str, dict]]:
    """Newest (highest round number) bench JSON in ``search_dir`` carrying
    a disaggregated round — either the standalone `make bench-disagg` line
    (top-level ``workers``/``router_imbalance``) or a main round embedding
    it under ``"disagg"``."""
    candidates: List[Tuple[int, str]] = []
    for pattern in _ROUND_GLOBS:
        for path in glob.glob(os.path.join(search_dir, pattern)):
            m = _ROUND_NUM.search(path)
            if m:
                candidates.append((int(m.group(1)), path))
    for _, path in sorted(candidates, reverse=True):
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError) as exc:
            logger.debug("skipping unreadable bench round %s: %s", path, exc)
            continue
        if not isinstance(data, dict):
            continue
        # the driver wraps bench's JSON line under "parsed"; the main
        # round embeds the disagg phase under "disagg"; the standalone
        # `make bench-disagg` line IS the round — accept all three shapes
        for container in (data, data.get("parsed")):
            if not isinstance(container, dict):
                continue
            round_ = container.get("disagg") if isinstance(
                container.get("disagg"), dict) else container
            if isinstance(round_, dict) and "router_imbalance" in round_:
                return path, round_
    return None


def _share_from_round(round_: dict) -> Optional[float]:
    """Per-role load balance from the round's fleet snapshot: the share
    that would equalize per-worker utilization, given what this round's
    workers reported. Returns None when the snapshot carries no usable
    signal (no fleet cards, or every worker idle by probe time)."""
    fleet = round_.get("fleet")
    workers = fleet.get("workers") if isinstance(fleet, dict) else None
    if not isinstance(workers, dict):
        return None
    load: Dict[str, List[float]] = {"prefill": [], "decode": []}
    for card in workers.values():
        if not isinstance(card, dict):
            continue
        role = str(card.get("role", ""))
        if role not in load:
            continue
        batch = float(card.get("batch") or 0) or 1.0
        depth = (float(card.get("running") or 0)
                 + float(card.get("prefilling") or 0)
                 + float(card.get("waiting") or 0))
        load[role].append(depth / batch)
    if not load["prefill"] or not load["decode"]:
        return None
    # summed per-worker utilization = each role's total outstanding work
    # in batch units; equalizing per-worker load assigns workers
    # proportionally to it
    work_pf = sum(load["prefill"])
    work_dec = sum(load["decode"])
    total = work_pf + work_dec
    if total <= 0:
        return None            # idle-by-probe-time snapshot: no signal
    return work_pf / total


def tuned_prefill_share(default: float = DEFAULT_PREFILL_SHARE,
                        search_dir: Optional[str] = None
                        ) -> Tuple[float, str]:
    """Resolve the pool's prefill share: ``(share, source)``.

    Precedence: ``APP_PREFILL_SHARE`` (operator override, loud ValueError
    on junk) → the latest bench-disagg round's per-role load balance,
    damped toward ``default`` by ``router_imbalance`` (an unevenly-hit
    decode pool is weak evidence about the ROLE split) → ``default``.
    ``source`` names what decided ("env", "bench:<file>", "default") so
    the round JSON can record how its own topology was chosen."""
    env = os.environ.get("APP_PREFILL_SHARE", "").strip()
    if env:
        share = float(env)
        if not 0.0 < share < 1.0:
            raise ValueError(
                f"APP_PREFILL_SHARE must be in (0, 1), got {share}")
        return share, "env"
    search_dir = (search_dir
                  or os.environ.get("APP_BENCH_DIR", "").strip()
                  or os.getcwd())
    found = _latest_round_with_disagg(search_dir)
    if found is None:
        return default, "default"
    path, round_ = found
    derived = _share_from_round(round_)
    if derived is None:
        return default, "default"
    imbalance = float(round_.get("router_imbalance", 0.0) or 0.0)
    confidence = max(0.0, 1.0 - min(1.0, imbalance))
    share = default + (derived - default) * confidence
    share = min(max(share, _SHARE_LO), _SHARE_HI)
    return share, f"bench:{os.path.basename(path)}"


def plan_engine_roles(n_workers: int,
                      prefill_share: Optional[float] = None) -> List[str]:
    """Role per worker for an ``n_workers`` pool.

    One worker stays unified (disaggregation needs at least one of each
    role to beat it); larger pools split ``prefill_share`` of workers to
    prefill, the rest to decode, always keeping at least one of each.
    ``prefill_share=None`` resolves through :func:`tuned_prefill_share`
    (env override → bench-disagg data → the 1/3 default); pass a value to
    pin it explicitly.
    """
    if n_workers < 1:
        raise ValueError(f"need at least one worker, got {n_workers}")
    if prefill_share is None:
        prefill_share, source = tuned_prefill_share()
        if source != "default":
            logger.info("prefill share %.3f from %s", prefill_share, source)
    if not 0.0 < prefill_share < 1.0:
        raise ValueError(f"prefill_share must be in (0, 1), "
                         f"got {prefill_share}")
    if n_workers == 1:
        return ["unified"]
    n_prefill = min(max(1, round(n_workers * prefill_share)), n_workers - 1)
    return ["prefill"] * n_prefill + ["decode"] * (n_workers - n_prefill)


def describe_topology(roles: List[str]) -> Dict[str, int]:
    """Role → count summary (bench JSON + logs)."""
    out: Dict[str, int] = {}
    for r in roles:
        out[r] = out.get(r, 0) + 1
    return out
