"""Serving-topology planning: which engine role each worker of a pool runs.

The parallel/ glue for disaggregated prefill/decode serving (ROADMAP item
1): `parallel/{mesh,sharding,pipeline}.py` shape programs WITHIN a worker;
this module shapes the pool ACROSS workers — how many processes run
chunked prefill + KV export (``APP_ENGINE_ROLE=prefill``) versus decode
replicas importing handed-off pages (``role=decode``). bench.py's
disaggregated round and deploy tooling consume the plan; the routing
frontend (server/failover.py) discovers the resulting roles from /health
at runtime, so the plan never has to be communicated out of band.

Prefill:decode sizing. Prefill is compute-bound (one prompt saturates a
chip's MXU), decode is weight-read-bound and batches across requests, so
decode replicas want the larger share of a pool; ~1/3 prefill is the
RAGO-style starting split for chat-shaped traffic (long prompts, short
answers skew higher; the router's least-loaded scoring absorbs the error
within a role).
"""

from __future__ import annotations

from typing import Dict, List


def plan_engine_roles(n_workers: int,
                      prefill_share: float = 1.0 / 3.0) -> List[str]:
    """Role per worker for an ``n_workers`` pool.

    One worker stays unified (disaggregation needs at least one of each
    role to beat it); larger pools split ``prefill_share`` of workers to
    prefill, the rest to decode, always keeping at least one of each.
    """
    if n_workers < 1:
        raise ValueError(f"need at least one worker, got {n_workers}")
    if not 0.0 < prefill_share < 1.0:
        raise ValueError(f"prefill_share must be in (0, 1), "
                         f"got {prefill_share}")
    if n_workers == 1:
        return ["unified"]
    n_prefill = min(max(1, round(n_workers * prefill_share)), n_workers - 1)
    return ["prefill"] * n_prefill + ["decode"] * (n_workers - n_prefill)


def describe_topology(roles: List[str]) -> Dict[str, int]:
    """Role → count summary (bench JSON + logs)."""
    out: Dict[str, int] = {}
    for r in roles:
        out[r] = out.get(r, 0) + 1
    return out
