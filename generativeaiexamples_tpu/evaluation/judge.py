"""LLM-as-judge evaluation: few-shot Likert 1–5 rating.

Parity with the reference judge (ref: rag_evaluator/evaluator.py
eval_llm_judge:165-235 + LLAMA_PROMPT_TEMPLATE:35-86): a few-shot prompt
shows a 5-rated and a 1-rated example, the judge returns JSON
{"Rating": n, "Explanation": ...}; ratings of 0 are clamped to 1 and the
mean is reported (evaluator.py:215-219).
"""

from __future__ import annotations

import json
import logging
import statistics
from typing import Any, Dict, List, Optional, Sequence

from generativeaiexamples_tpu.chains.query_decomposition import extract_json

logger = logging.getLogger(__name__)

_SETTINGS = dict(max_tokens=200, temperature=0.1, top_p=1.0)

SYS_PROMPT = (
    "You are an impartial judge that evaluates the quality of an "
    "assistant's answer to the question provided. Your evaluation takes "
    "into account helpfulness, relevancy, accuracy, and level of detail of "
    "the answer. You must use both the reference context and reference "
    "answer to guide your evaluation.")

_EXAMPLE_CTX = (
    "On 8 September 2022, Buckingham Palace announced the Queen's doctors "
    "were concerned for her health. She died peacefully at 15:10 BST at the "
    "age of 96; Charles immediately succeeded as monarch.")

FEW_SHOT = (
    "Example 1:\n"
    "[Question]\nWhen did Queen Elizabeth II die?\n"
    f"[Reference Context]\n{_EXAMPLE_CTX}\n"
    "[Reference Answer]\nQueen Elizabeth II died on September 8, 2022.\n"
    "[Assistant's Answer]\nShe died on September 8, 2022\n"
    '{"Rating": 5, "Explanation": "The answer is helpful, relevant, '
    'accurate, and concise. It matches the reference context and answer."}\n'
    "\nExample 2:\n"
    "[Question]\nWhen did Queen Elizabeth II die?\n"
    f"[Reference Context]\n{_EXAMPLE_CTX}\n"
    "[Reference Answer]\nQueen Elizabeth II died on September 8, 2022.\n"
    "[Assistant's Answer]\nQueen Elizabeth II was the longest reigning "
    "monarch of the United Kingdom.\n"
    '{"Rating": 1, "Explanation": "The answer is not helpful or relevant. '
    'It does not answer the question."}\n')

PROMPT_TEMPLATE = (
    "{system_prompt}\n\n{few_shot}\n"
    "Follow the exact same format as above. Rating must be between 1 and 5. "
    "Return the rating and explanation for the following assistant's answer "
    "as JSON.\n"
    "[Question]\n{question}\n"
    "[Reference Context]\n{ctx_ref}\n"
    "[Reference Answer]\n{answer_ref}\n"
    "[Assistant's Answer]\n{answer}\n")


class LLMJudge:
    def __init__(self, llm) -> None:
        self.llm = llm

    def judge_one(self, question: str, ground_truth_context: str,
                  ground_truth_answer: str, answer: str) -> Dict[str, Any]:
        prompt = PROMPT_TEMPLATE.format(
            system_prompt=SYS_PROMPT, few_shot=FEW_SHOT, question=question,
            ctx_ref=ground_truth_context, answer_ref=ground_truth_answer,
            answer=answer)
        raw = "".join(self.llm.chat(
            [{"role": "user", "content": prompt}], **_SETTINGS))
        parsed = extract_json(raw) or {}
        rating: Optional[int] = None
        try:
            rating = int(parsed.get("Rating"))
            rating = max(1, min(5, rating))  # clamp; 0→1 per evaluator.py:215
        except (TypeError, ValueError):
            logger.info("judge returned unparseable rating: %.120s", raw)
        return {"rating": rating,
                "explanation": str(parsed.get("Explanation", ""))}

    def judge(self, samples: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        """samples: dicts with question / ground_truth_context /
        ground_truth_answer / answer keys (ref eval file schema)."""
        results: List[Dict[str, Any]] = []
        for d in samples:
            res = self.judge_one(
                d["question"], d.get("ground_truth_context", ""),
                d.get("ground_truth_answer", ""), d["answer"])
            results.append({**d, **res})
        ratings = [r["rating"] for r in results if r["rating"]]
        mean = round(statistics.mean(ratings), 1) if ratings else None
        return {"results": results, "mean_rating": mean,
                "num_rated": len(ratings)}
