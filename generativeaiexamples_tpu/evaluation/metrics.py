"""Ragas-style RAG metrics on in-tree models.

Implements the metric suite the reference gets from the ragas library
(ref: rag_evaluator/evaluator.py:26-33 imports answer_relevancy,
answer_similarity, context_precision, context_recall, context_relevancy,
faithfulness; harmonic-mean "ragas_score" evaluator.py:95-97,154-158):

  faithfulness       statements in the answer supported by the retrieved
                     context (statement extraction + NLI-style verdicts)
  answer_relevancy   cosine similarity between the question and questions
                     regenerated from the answer
  answer_similarity  embedding cosine between answer and ground truth
  context_precision  average precision of retrieved chunks judged useful
                     for the ground-truth answer
  context_recall     ground-truth sentences attributable to the context
  context_relevancy  context sentences needed to answer the question

The grader LLM is any object with the `chat(messages, **settings)` iterator
contract (chains/llm_client.py); embeddings come from encoders/embedder.py.
"""

from __future__ import annotations

import json
import logging
import re
import statistics
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_GRADER_SETTINGS = dict(max_tokens=200, temperature=0.1, top_p=1.0)
# ref evaluator.py:102-106 llm_params


@dataclass
class EvalSample:
    """One row of the eval file (ref evaluator.py:126-131 keys)."""
    question: str
    answer: str
    contexts: List[str] = field(default_factory=list)
    ground_truth: str = ""


def _sentences(text: str) -> List[str]:
    parts = re.split(r"(?<=[.!?])\s+|\n+", text.strip())
    return [p.strip() for p in parts if len(p.strip()) > 2]


def _json_list(text: str) -> Optional[List[Any]]:
    start = text.find("[")
    if start == -1:
        return None
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "[":
            depth += 1
        elif text[i] == "]":
            depth -= 1
            if depth == 0:
                try:
                    return json.loads(text[start:i + 1])
                except json.JSONDecodeError:
                    return None
    return None


class RagasEvaluator:
    def __init__(self, llm, embedder) -> None:
        self.llm = llm
        self.embedder = embedder

    # ------------------------------------------------------------ LLM utils

    def _ask(self, prompt: str) -> str:
        return "".join(self.llm.chat(
            [{"role": "user", "content": prompt}], **_GRADER_SETTINGS))

    def _verdict(self, prompt: str) -> bool:
        out = self._ask(prompt + "\nAnswer with exactly one word: yes or no.")
        return out.strip().lower().startswith("yes")

    def _cosine(self, a: str, b: str) -> float:
        va, vb = self.embedder.embed_queries([a, b])
        return max(0.0, min(1.0, float(np.dot(va, vb))))

    # -------------------------------------------------------------- metrics

    def faithfulness(self, s: EvalSample) -> float:
        """Fraction of answer statements inferable from the context."""
        if not s.contexts:
            return 0.0
        raw = self._ask(
            "Break the following answer into its individual factual "
            "statements. Return a JSON list of strings only.\n\n"
            f"Answer: {s.answer}")
        statements = _json_list(raw) or _sentences(s.answer)
        statements = [str(x) for x in statements][:10]
        if not statements:
            return 0.0
        ctx = "\n".join(s.contexts)
        supported = sum(
            self._verdict(
                f"Context:\n{ctx}\n\nStatement: {st}\n\n"
                "Can the statement be directly inferred from the context?")
            for st in statements)
        return supported / len(statements)

    def answer_relevancy(self, s: EvalSample, n_questions: int = 3) -> float:
        """Mean cosine(question, questions regenerated from the answer)."""
        raw = self._ask(
            f"Generate {n_questions} questions that the following answer "
            "directly answers. Return a JSON list of strings only.\n\n"
            f"Answer: {s.answer}")
        questions = [str(q) for q in (_json_list(raw) or [])][:n_questions]
        if not questions:
            return 0.0
        vecs = self.embedder.embed_queries([s.question] + questions)
        sims = np.clip(vecs[1:] @ vecs[0], 0.0, 1.0)
        return float(np.mean(sims))

    def answer_similarity(self, s: EvalSample) -> float:
        """Embedding cosine between answer and ground truth."""
        if not s.ground_truth:
            return 0.0
        return self._cosine(s.answer, s.ground_truth)

    def context_precision(self, s: EvalSample) -> float:
        """Average precision over retrieved chunks judged useful for
        arriving at the ground truth."""
        if not s.contexts:
            return 0.0
        verdicts = [
            self._verdict(
                f"Question: {s.question}\n"
                f"Ground-truth answer: {s.ground_truth}\n\n"
                f"Context chunk:\n{c}\n\n"
                "Was this chunk useful in arriving at the answer?")
            for c in s.contexts]
        score, hits = 0.0, 0
        for k, v in enumerate(verdicts, start=1):
            if v:
                hits += 1
                score += hits / k
        return score / hits if hits else 0.0

    def context_recall(self, s: EvalSample) -> float:
        """Fraction of ground-truth sentences attributable to the context."""
        if not s.contexts or not s.ground_truth:
            return 0.0
        ctx = "\n".join(s.contexts)
        sentences = _sentences(s.ground_truth)[:10]
        if not sentences:
            return 0.0
        attributed = sum(
            self._verdict(
                f"Context:\n{ctx}\n\nSentence: {sent}\n\n"
                "Can the sentence be attributed to the context?")
            for sent in sentences)
        return attributed / len(sentences)

    def context_relevancy(self, s: EvalSample) -> float:
        """Fraction of context sentences needed to answer the question."""
        if not s.contexts:
            return 0.0
        sentences = [sent for c in s.contexts for sent in _sentences(c)][:20]
        if not sentences:
            return 0.0
        needed = sum(
            self._verdict(
                f"Question: {s.question}\n\nSentence: {sent}\n\n"
                "Is this sentence needed to answer the question?")
            for sent in sentences)
        return needed / len(sentences)

    # ------------------------------------------------------------- driving

    METRICS = ("faithfulness", "answer_relevancy", "answer_similarity",
               "context_precision", "context_recall", "context_relevancy")

    def evaluate_sample(self, s: EvalSample) -> Dict[str, float]:
        row = {name: getattr(self, name)(s) for name in self.METRICS}
        row["ragas_score"] = ragas_score(row)
        return row

    def evaluate(self, samples: Sequence[EvalSample]) -> Dict[str, Any]:
        """Per-sample rows + aggregate means (ref evaluator.py:140-160)."""
        rows = [self.evaluate_sample(s) for s in samples]
        aggregate = {name: float(np.mean([r[name] for r in rows]))
                     for name in self.METRICS} if rows else {}
        if rows:
            aggregate["ragas_score"] = ragas_score(aggregate)
        return {"rows": rows, "aggregate": aggregate}


def ragas_score(row: Dict[str, float]) -> float:
    """Harmonic mean of faithfulness, context_relevancy, answer_relevancy,
    context_recall (ref calculate_ragas_score, evaluator.py:95-97)."""
    values = [row["faithfulness"], row["context_relevancy"],
              row["answer_relevancy"], row["context_recall"]]
    if any(v <= 0 for v in values):
        return 0.0
    return statistics.harmonic_mean(values)
